"""The TPU assignment solver.

Replicates the reference's sequential greedy semantics — pod k's
placement affects pod k+1's feasibility and scores — as a jitted
lax.scan whose carry is the cluster occupancy state. Each scan step
evaluates the full default predicate/priority pipeline for ONE pod
against ALL nodes as vector ops:

  predicates (masks):           reference
    resources + pod count       PodFitsResources  predicates.go:139-156
    nodeSelector subset         MatchNodeSelector predicates.go:184-190
    hostPort conflicts          PodFitsPorts      predicates.go:337-349
    exclusive volumes           NoDiskConflict    predicates.go:85-95
    pinned host                 HostName          predicates.go:192-197
  priorities (scores, exact integer math):
    LeastRequested              priorities.go:31-95 (int32 division)
    BalancedResourceAllocation  priorities.go:146-205 (f32 fractions)
    ServiceSpreading            spreading.go:38-87 (f32, like Go's float32)

Score-tie selection is "lowest node index", matching the scalar
oracle's deterministic tie-break (generic.py select_host).

All node-axis tensors may be sharded over a Mesh axis; XLA SPMD then
turns the per-step argmax into a sharded reduce + tiny all-reduce over
ICI, and the occupancy updates stay local to the owning shard.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.models.algspec import DEFAULT_LOWERED, LoweredSpec
from kubernetes_tpu.ops.ledger import traced_jit
from kubernetes_tpu.ops.matrices import DeviceSnapshot

# Weighted-sum weights for the default provider (defaults.go:51-60):
# LeastRequested=1, BalancedResourceAllocation=1, ServiceSpreading=1.
DEFAULT_WEIGHTS = (1, 1, 1)


def _pred_resources(pod: Dict, nodes: Dict) -> jnp.ndarray:
    """PodFitsResources (predicates.go:139-156) as bool[N]."""
    cpu_cap, mem_cap = nodes["cpu_cap"], nodes["mem_cap"]
    fits_cpu = (cpu_cap == 0) | (nodes["cpu_fit"] + pod["cpu"] <= cpu_cap)
    fits_mem = (mem_cap == 0) | (nodes["mem_fit"] + pod["mem"] <= mem_cap)
    fits_count = nodes["pods_used"] + 1 <= nodes["pods_cap"]
    nonzero_ok = (~nodes["over"]) & fits_cpu & fits_mem & fits_count
    # Zero-request pods only check pod-count headroom (predicates.go:146).
    zero_ok = nodes["pods_used"] < nodes["pods_cap"]
    return jnp.where(pod["zero_req"], zero_ok, nonzero_ok)


def _pred_selector(pod: Dict, nodes: Dict) -> jnp.ndarray:
    """MatchNodeSelector: selector bits must be a subset of labels."""
    sel = pod["sel"][None, :]
    return jnp.all((sel & nodes["labels"]) == sel, axis=1)


def _pred_ports(pod: Dict, nodes: Dict) -> jnp.ndarray:
    """PodFitsPorts."""
    return ~jnp.any(pod["port"][None, :] & nodes["uport"], axis=1)


def _pred_disk(pod: Dict, nodes: Dict) -> jnp.ndarray:
    """NoDiskConflict: conflict when either side holds it read-write."""
    return ~jnp.any(
        (pod["vol_rw"][None, :] & nodes["uvol_any"])
        | (pod["vol_any"][None, :] & nodes["uvol_rw"]),
        axis=1,
    )


def _pred_hostname(pod: Dict, N: int) -> jnp.ndarray:
    """HostName."""
    idx = jnp.arange(N, dtype=jnp.int32)
    return (pod["pinned"] == -1) | (idx == pod["pinned"])


def _feasible(
    pod: Dict, nodes: Dict, N: int, ls: LoweredSpec = DEFAULT_LOWERED
) -> jnp.ndarray:
    """The configured predicates as one bool[N] mask (defaults when no
    policy is lowered — each term is gated by the static LoweredSpec,
    so a policy that omits a predicate omits its ops entirely). The
    per-predicate helpers above are the single implementation shared
    with the explain readback (explain_rows): the decision and its
    explanation can never drift."""
    ok = nodes["sched"]
    if ls.resources:
        ok = ok & _pred_resources(pod, nodes)
    if ls.selector:
        ok = ok & _pred_selector(pod, nodes)
    if ls.ports:
        ok = ok & _pred_ports(pod, nodes)
    if ls.disk:
        ok = ok & _pred_disk(pod, nodes)
    if ls.hostname:
        ok = ok & _pred_hostname(pod, N)
    if ls.node_label:
        # -- CheckNodeLabelPresence: static node mask (predicates.go:226) --
        ok = ok & nodes["policy_ok"]
    if ls.service_affinity:
        # -- CheckServiceAffinity (predicates.go:268-335) --
        # Per affinity label k the pod needs "l_k = v" where v is its
        # own pinned nodeSelector value, else the value on the node
        # hosting the first service peer (the anchor); no requirement
        # when neither exists. A peer on an unknown node is the
        # scalar's GetNodeInfo error: the pod fits nowhere.
        pin = pod["aff_pin"]  # i32[K]
        s = pod["svc"]
        scratch = nodes["anchor"].shape[0] - 1
        slot = jnp.where(s >= 0, s, scratch)
        anchor = nodes["anchor"][slot]
        peers = nodes["svc_total"][slot] > 0
        consults = jnp.any(pin < 0) & (s >= 0) & peers
        anchor_err = consults & (anchor == -2)
        anchor_ok = consults & (anchor >= 0)
        a_vid = jnp.where(
            anchor_ok, nodes["aff_vid"][jnp.maximum(anchor, 0)], -1
        )  # i32[K]
        need = jnp.where(pin >= 0, pin, a_vid)
        ok = ok & jnp.all(
            (need[None, :] < 0) | (nodes["aff_vid"] == need[None, :]), axis=1
        )
        ok = ok & ~anchor_err
    return ok


def _component_scores(
    pod: Dict, nodes: Dict
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The three default priority columns — (LeastRequested,
    BalancedResourceAllocation, ServiceSpreading) as separate int32[N]
    vectors. _scores sums them weighted; the explain readback
    (explain_rows) reports them decomposed. One implementation, so the
    published breakdown can never drift from the decision.

    Integer score math in int32: columns are integer-valued f32 with
    magnitudes < 2^24, so the cast is exact and the Go int64 division
    semantics (truncation of nonnegative quotients) are reproduced
    without float rounding hazards."""
    cpu_cap = nodes["cpu_cap"].astype(jnp.int32)
    mem_cap = nodes["mem_cap"].astype(jnp.int32)
    cpu_req = (nodes["cpu_used"] + pod["cpu"]).astype(jnp.int32)
    mem_req = (nodes["mem_used"] + pod["mem"]).astype(jnp.int32)

    def calc_score(req, cap):
        # priorities.go:31-40: 0 if cap == 0 or req > cap.
        raw = jnp.where(cap > 0, ((cap - req) * 10) // jnp.maximum(cap, 1), 0)
        return jnp.where((cap == 0) | (req > cap), 0, raw)

    lr = (calc_score(cpu_req, cpu_cap) + calc_score(mem_req, mem_cap)) // 2

    # BalancedResourceAllocation (priorities.go:146-205). TPU float
    # division is reciprocal-based and NOT correctly rounded (~1 ulp
    # low), which truncates scores one short at exact boundaries like
    # |0.75-0.25|*10 == 5. The epsilon absorbs that device error; it is
    # far below the smallest legitimate gap between distinct exact
    # score values for realistic capacities.
    cfrac = jnp.where(cpu_cap == 0, 1.0, cpu_req / jnp.maximum(cpu_cap, 1))
    mfrac = jnp.where(mem_cap == 0, 1.0, mem_req / jnp.maximum(mem_cap, 1))
    bra = jnp.where(
        (cfrac >= 1) | (mfrac >= 1),
        0,
        (10 - jnp.abs(cfrac - mfrac) * 10 + 1e-5).astype(jnp.int32),
    )

    # ServiceSpreading (spreading.go:38-87) in exact integer math
    # (counts are small integers): 10*(maxc-count) // maxc. Go truncates
    # the float32 quotient; integer division agrees except where Go's
    # f32 rounding lands exactly on an integer from below — rare and
    # covered by the >=99% parity budget.
    svc = pod["svc"]
    counts = jax.lax.dynamic_index_in_dim(
        nodes["svc_counts"], jnp.maximum(svc, 0), axis=1, keepdims=False
    ).astype(jnp.int32)
    maxc = jnp.max(counts)
    spread_raw = (10 * (maxc - counts)) // jnp.maximum(maxc, 1)
    spread = jnp.where((svc < 0) | (maxc == 0), 10, spread_raw)
    return lr, bra, spread


def _scores(
    pod: Dict,
    nodes: Dict,
    weights,
    ls: LoweredSpec = DEFAULT_LOWERED,
    feas: jnp.ndarray = None,
) -> jnp.ndarray:
    """Weighted configured priorities as one int32[N] score vector.

    `feas` is the pod's feasibility mask: the reference prioritizes
    over the FILTERED node list (generic_scheduler.go:80-86), which
    only matters for ServiceAntiAffinity — its per-zone peer counts
    skip peers hosted on filtered-out nodes (spreading.go:133-147).
    Every other priority's per-node score is filter-independent."""
    w_lr, w_bra, w_spread = weights
    total = jnp.zeros(nodes["cpu_cap"].shape[0], dtype=jnp.int32)

    if w_lr or w_bra or w_spread:
        # Unused components are dead code XLA eliminates; the shared
        # helper keeps the explain readback's score decomposition
        # (explain_rows) THE solver arithmetic, not a twin.
        lr, bra, spread = _component_scores(pod, nodes)
    if w_lr:
        total = total + lr * w_lr
    if w_bra:
        total = total + bra * w_bra
    if w_spread:
        total = total + spread * w_spread

    svc = pod["svc"]
    if ls.aa_weights:
        counts = jax.lax.dynamic_index_in_dim(
            nodes["svc_counts"], jnp.maximum(svc, 0), axis=1, keepdims=False
        ).astype(jnp.int32)

    if ls.static_prio:
        # CalculateNodeLabelPriority: pod-independent, weights folded
        # into the column host-side (priorities.go:113-138).
        total = total + nodes["static_prio"]

    if ls.aa_weights:
        # ServiceAntiAffinity (spreading.go:105-169): spread the pod's
        # first service across the values ("zones") of one node label.
        # numServicePods counts peers regardless of node presence
        # (svc_total); per-zone counts sum the per-node peer counts.
        scratch = nodes["svc_total"].shape[0] - 1
        slot = jnp.where(svc >= 0, svc, scratch)
        num = jnp.where(svc >= 0, nodes["svc_total"][slot], 0.0).astype(jnp.int32)
        for i, (w, nz) in enumerate(zip(ls.aa_weights, ls.aa_zones)):
            zone = nodes["aa_zone"][:, i]
            in_zone = zone >= 0
            if feas is not None:
                in_zone = in_zone & feas
            zc = jnp.zeros(nz, dtype=jnp.int32).at[jnp.maximum(zone, 0)].add(
                jnp.where(in_zone, counts, 0)
            )
            count_z = zc[jnp.maximum(zone, 0)]
            score = jnp.where(
                num > 0, (10 * (num - count_z)) // jnp.maximum(num, 1), 10
            )
            score = jnp.where(zone < 0, 0, score)
            total = total + score * w

    return total


def _commit(nodes: Dict, pod: Dict, choice: jnp.ndarray, N: int) -> Dict:
    """Apply one placement to the occupancy carry (the batch analog of
    Modeler.AssumePod, modeler.go:113)."""
    assigned = choice >= 0
    onehot = (jnp.arange(N, dtype=jnp.int32) == choice) & assigned
    fonehot = onehot.astype(jnp.float32)
    new = dict(nodes)
    new["cpu_fit"] = nodes["cpu_fit"] + fonehot * pod["cpu"]
    new["mem_fit"] = nodes["mem_fit"] + fonehot * pod["mem"]
    new["cpu_used"] = nodes["cpu_used"] + fonehot * pod["cpu"]
    new["mem_used"] = nodes["mem_used"] + fonehot * pod["mem"]
    new["pods_used"] = nodes["pods_used"] + fonehot
    mask = onehot[:, None]
    new["uport"] = jnp.where(mask, nodes["uport"] | pod["port"][None, :], nodes["uport"])
    new["uvol_any"] = jnp.where(
        mask, nodes["uvol_any"] | pod["vol_any"][None, :], nodes["uvol_any"]
    )
    new["uvol_rw"] = jnp.where(
        mask, nodes["uvol_rw"] | pod["vol_rw"][None, :], nodes["uvol_rw"]
    )
    # As an existing pod, the placement counts toward EVERY service
    # whose selector matches it. Membership travels as a top-K id list
    # (i32[K], -1 padded) instead of a dense f32[S] row: at 50k pods x
    # 500 services the dense rows were 100 MB of upload per solve.
    # The commit is a K-element scatter-add into row `choice` — NOT a
    # broadcasted full-matrix add: rewriting the N x S counts matrix
    # every scan step costs ~N*S*8 bytes of HBM traffic per pod
    # (~500 GB over a 50k backlog), which alone blew the <2s budget.
    ids = pod["svc_ids"]
    row = jnp.maximum(choice, 0)
    valid = ((ids >= 0) & assigned).astype(jnp.float32)
    new["svc_counts"] = nodes["svc_counts"].at[row, jnp.maximum(ids, 0)].add(
        valid, mode="drop"
    )
    if "anchor" in nodes:
        # ServiceAffinity/AntiAffinity carry: the placed pod becomes a
        # peer of every service it matches; it becomes a service's
        # anchor only when that service had no listed peer yet (the
        # scalar's nsServicePods[0] is first-in-list-order, and the
        # backlog commits in order). Invalid/padded ids route to the
        # scratch slot (last index), which no real pod ever reads.
        scratch = nodes["anchor"].shape[0] - 1
        slot = jnp.where((ids >= 0) & assigned, ids, scratch)
        new["svc_total"] = nodes["svc_total"].at[slot].add(1.0)
        cur = nodes["anchor"][slot]
        new["anchor"] = nodes["anchor"].at[slot].set(
            jnp.where(cur == -1, choice, cur)
        )
    return new


def _scan_solve(pods, nodes, weights, lspec=DEFAULT_LOWERED):
    N = nodes["cpu_cap"].shape[0]

    def step(carry, pod):
        feas = _feasible(pod, carry, N, lspec)
        score = _scores(pod, carry, weights, lspec, feas)
        masked = jnp.where(feas, score, -1)
        best = jnp.argmax(masked).astype(jnp.int32)  # first max = lowest index
        # Feasibility folds into the same reduction: infeasible nodes
        # carry -1, so "any feasible" == "max masked value >= 0". One
        # N-wide reduction instead of two.
        choice = jnp.where(masked[best] >= 0, best, -1)
        return _commit(carry, pod, choice, N), choice

    # The scan is latency-bound on TPU (per-iteration sequencing
    # overhead ~30us dominates the ~500KB the body actually touches),
    # so unrolling amortizes it. Swept at 50k x 5k on v5e: unroll
    # 2/8/16/32 solve in 1.27/1.16/1.15/1.12s with compile+first-run
    # at 6.2/5.0/-/8.7s — 8 takes most of the runtime win at the
    # LOWEST compile cost. Decisions are bit-identical for any unroll.
    return jax.lax.scan(step, nodes, pods, unroll=8)


@traced_jit(static_argnames=("weights", "lspec"))
def _solve_xla(pods, nodes, weights, lspec):
    _, assignment = _scan_solve(pods, nodes, weights, lspec)
    return assignment


@traced_jit(static_argnames=("weights", "lspec"), donate_argnames=("nodes",))
def _solve_with_state_xla(pods, nodes, weights, lspec):
    final, assignment = _scan_solve(pods, nodes, weights, lspec)
    return assignment, final


def _use_pallas(pods, nodes, lspec) -> bool:
    from kubernetes_tpu.ops.pallas_scan import pallas_eligible

    return pallas_eligible(pods, nodes, lspec)


def solve(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
    lspec: LoweredSpec = DEFAULT_LOWERED,
) -> jnp.ndarray:
    """Sequential-parity assignment: i32[P] of node indices (-1 =
    unschedulable). The scan IS the reference's scheduleOne loop.
    `lspec` selects the configured predicate/priority pipeline (static:
    one compiled executable per distinct policy).

    Dispatch: the default spec on a single unsharded TPU device runs
    the pallas kernel (ops/pallas_scan.py — same decisions, ~3x faster:
    the whole occupancy carry lives in VMEM instead of round-tripping
    HBM every scan step). Policy specs, meshes, and CPU run the XLA
    scan. Bit-identical by test (tests/test_pallas_scan.py) and by the
    bench's measured sequential-oracle parity chain."""
    if _use_pallas(pods, nodes, lspec):
        from kubernetes_tpu.ops.pallas_scan import solve_pallas

        return solve_pallas(pods, nodes, weights)
    return _solve_xla(pods, nodes, weights, lspec)


def solve_with_state(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
    lspec: LoweredSpec = DEFAULT_LOWERED,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Like solve, but also returns the post-commit occupancy carry.
    On the XLA path `nodes` is DONATED: the caller's buffers are
    consumed and the returned state aliases them — the substrate for
    incremental churn (SolverSession keeps this state device-resident
    across ticks). The pallas path (same dispatch rule as solve())
    returns fresh state arrays instead; either way the caller must not
    reuse its argument."""
    if _use_pallas(pods, nodes, lspec):
        from kubernetes_tpu.ops.pallas_scan import solve_with_state_pallas

        return solve_with_state_pallas(pods, nodes, weights)
    return _solve_with_state_xla(pods, nodes, weights, lspec)


# -- explain readback --------------------------------------------------


def _explain_row(pod: Dict, nodes: Dict, N: int):
    """One pod's per-node verdict against a FIXED occupancy state:
    packed predicate-failure bits (bit i = matrices.EXPLAIN_PREDICATES
    [i] REJECTED the node) plus the default priority components. Built
    from the same _pred_* / _component_scores the solver decides with."""
    preds = (
        nodes["sched"],
        _pred_resources(pod, nodes),
        _pred_selector(pod, nodes),
        _pred_ports(pod, nodes),
        _pred_disk(pod, nodes),
        _pred_hostname(pod, N),
    )
    bits = jnp.zeros(N, jnp.uint32)
    for i, ok in enumerate(preds):
        bits = bits | ((~ok).astype(jnp.uint32) << i)
    lr, bra, spread = _component_scores(pod, nodes)
    return bits, lr, bra, spread


@traced_jit
def explain_rows(pods: Dict[str, jnp.ndarray], nodes: Dict[str, jnp.ndarray]):
    """The explain readback: default-pipeline verdicts for a batch of
    pods, vmapped — (bits u32[P, N], lr i32[P, N], bra, spread). The
    occupancy state `nodes` is FIXED (no commits): callers choose
    which state — pre-solve for "why did this pod win", post-solve for
    "why is this pod still stuck" — and strip padding themselves
    (ops.pipeline.explain_matrix does both). Off the solve hot path by
    construction: one dispatch per tick, over arrays the tick already
    staged."""
    N = nodes["cpu_cap"].shape[0]
    return jax.vmap(lambda p: _explain_row(p, nodes, N))(pods)


def solve_assignments(
    dsnap: DeviceSnapshot, weights: Optional[Tuple[int, int, int]] = None
) -> np.ndarray:
    """Run the solver and strip padding: returns i32[n_pods] with real
    node indices (-1 unschedulable). Policy lowering (lspec + weights)
    rides on the DeviceSnapshot; an explicit `weights` overrides."""
    if weights is None:
        weights = dsnap.weights
    out = np.asarray(solve(dsnap.pods, dsnap.nodes, weights, dsnap.lowered))
    from kubernetes_tpu.utils import sli

    sli.note_transfer("d2h", out.nbytes)
    out = out[: dsnap.n_pods]
    # Padding nodes can never be chosen (schedulable=False), but clamp
    # defensively so a bug can't leak a phantom index.
    out = np.where(out >= dsnap.n_nodes, -1, out)
    return out
