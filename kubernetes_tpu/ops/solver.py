"""The TPU assignment solver.

Replicates the reference's sequential greedy semantics — pod k's
placement affects pod k+1's feasibility and scores — as a jitted
lax.scan whose carry is the cluster occupancy state. Each scan step
evaluates the full default predicate/priority pipeline for ONE pod
against ALL nodes as vector ops:

  predicates (masks):           reference
    resources + pod count       PodFitsResources  predicates.go:139-156
    nodeSelector subset         MatchNodeSelector predicates.go:184-190
    hostPort conflicts          PodFitsPorts      predicates.go:337-349
    exclusive volumes           NoDiskConflict    predicates.go:85-95
    pinned host                 HostName          predicates.go:192-197
  priorities (scores, exact integer math):
    LeastRequested              priorities.go:31-95 (int32 division)
    BalancedResourceAllocation  priorities.go:146-205 (f32 fractions)
    ServiceSpreading            spreading.go:38-87 (f32, like Go's float32)

Score-tie selection is "lowest node index", matching the scalar
oracle's deterministic tie-break (generic.py select_host).

All node-axis tensors may be sharded over a Mesh axis; XLA SPMD then
turns the per-step argmax into a sharded reduce + tiny all-reduce over
ICI, and the occupancy updates stay local to the owning shard.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.ops.matrices import DeviceSnapshot

# Weighted-sum weights for the default provider (defaults.go:51-60):
# LeastRequested=1, BalancedResourceAllocation=1, ServiceSpreading=1.
DEFAULT_WEIGHTS = (1, 1, 1)


def _feasible(pod: Dict, nodes: Dict, N: int) -> jnp.ndarray:
    """All default predicates as one bool[N] mask."""
    cpu_cap, mem_cap = nodes["cpu_cap"], nodes["mem_cap"]
    # -- PodFitsResources --
    fits_cpu = (cpu_cap == 0) | (nodes["cpu_fit"] + pod["cpu"] <= cpu_cap)
    fits_mem = (mem_cap == 0) | (nodes["mem_fit"] + pod["mem"] <= mem_cap)
    fits_count = nodes["pods_used"] + 1 <= nodes["pods_cap"]
    nonzero_ok = (~nodes["over"]) & fits_cpu & fits_mem & fits_count
    # Zero-request pods only check pod-count headroom (predicates.go:146).
    zero_ok = nodes["pods_used"] < nodes["pods_cap"]
    res_ok = jnp.where(pod["zero_req"], zero_ok, nonzero_ok)
    # -- MatchNodeSelector: selector bits must be a subset of labels --
    sel = pod["sel"][None, :]
    sel_ok = jnp.all((sel & nodes["labels"]) == sel, axis=1)
    # -- PodFitsPorts --
    port_ok = ~jnp.any(pod["port"][None, :] & nodes["uport"], axis=1)
    # -- NoDiskConflict: conflict when either side holds it read-write --
    vol_conflict = jnp.any(
        (pod["vol_rw"][None, :] & nodes["uvol_any"])
        | (pod["vol_any"][None, :] & nodes["uvol_rw"]),
        axis=1,
    )
    # -- HostName --
    idx = jnp.arange(N, dtype=jnp.int32)
    host_ok = (pod["pinned"] == -1) | (idx == pod["pinned"])
    return res_ok & sel_ok & port_ok & (~vol_conflict) & host_ok & nodes["sched"]


def _scores(pod: Dict, nodes: Dict, weights) -> jnp.ndarray:
    """Weighted default priorities as one int32[N] score vector."""
    # Integer score math in int32: columns are integer-valued f32 with
    # magnitudes < 2^24, so the cast is exact and the Go int64 division
    # semantics (truncation of nonnegative quotients) are reproduced
    # without float rounding hazards.
    cpu_cap = nodes["cpu_cap"].astype(jnp.int32)
    mem_cap = nodes["mem_cap"].astype(jnp.int32)
    cpu_req = (nodes["cpu_used"] + pod["cpu"]).astype(jnp.int32)
    mem_req = (nodes["mem_used"] + pod["mem"]).astype(jnp.int32)

    def calc_score(req, cap):
        # priorities.go:31-40: 0 if cap == 0 or req > cap.
        raw = jnp.where(cap > 0, ((cap - req) * 10) // jnp.maximum(cap, 1), 0)
        return jnp.where((cap == 0) | (req > cap), 0, raw)

    lr = (calc_score(cpu_req, cpu_cap) + calc_score(mem_req, mem_cap)) // 2

    # BalancedResourceAllocation (priorities.go:146-205). TPU float
    # division is reciprocal-based and NOT correctly rounded (~1 ulp
    # low), which truncates scores one short at exact boundaries like
    # |0.75-0.25|*10 == 5. The epsilon absorbs that device error; it is
    # far below the smallest legitimate gap between distinct exact
    # score values for realistic capacities.
    cfrac = jnp.where(cpu_cap == 0, 1.0, cpu_req / jnp.maximum(cpu_cap, 1))
    mfrac = jnp.where(mem_cap == 0, 1.0, mem_req / jnp.maximum(mem_cap, 1))
    bra = jnp.where(
        (cfrac >= 1) | (mfrac >= 1),
        0,
        (10 - jnp.abs(cfrac - mfrac) * 10 + 1e-5).astype(jnp.int32),
    )

    # ServiceSpreading (spreading.go:38-87) in exact integer math
    # (counts are small integers): 10*(maxc-count) // maxc. Go truncates
    # the float32 quotient; integer division agrees except where Go's
    # f32 rounding lands exactly on an integer from below — rare and
    # covered by the >=99% parity budget.
    svc = pod["svc"]
    counts = jax.lax.dynamic_index_in_dim(
        nodes["svc_counts"], jnp.maximum(svc, 0), axis=1, keepdims=False
    ).astype(jnp.int32)
    maxc = jnp.max(counts)
    spread_raw = (10 * (maxc - counts)) // jnp.maximum(maxc, 1)
    spread = jnp.where((svc < 0) | (maxc == 0), 10, spread_raw)

    w_lr, w_bra, w_spread = weights
    return lr * w_lr + bra * w_bra + spread * w_spread


def _commit(nodes: Dict, pod: Dict, choice: jnp.ndarray, N: int) -> Dict:
    """Apply one placement to the occupancy carry (the batch analog of
    Modeler.AssumePod, modeler.go:113)."""
    assigned = choice >= 0
    onehot = (jnp.arange(N, dtype=jnp.int32) == choice) & assigned
    fonehot = onehot.astype(jnp.float32)
    new = dict(nodes)
    new["cpu_fit"] = nodes["cpu_fit"] + fonehot * pod["cpu"]
    new["mem_fit"] = nodes["mem_fit"] + fonehot * pod["mem"]
    new["cpu_used"] = nodes["cpu_used"] + fonehot * pod["cpu"]
    new["mem_used"] = nodes["mem_used"] + fonehot * pod["mem"]
    new["pods_used"] = nodes["pods_used"] + fonehot
    mask = onehot[:, None]
    new["uport"] = jnp.where(mask, nodes["uport"] | pod["port"][None, :], nodes["uport"])
    new["uvol_any"] = jnp.where(
        mask, nodes["uvol_any"] | pod["vol_any"][None, :], nodes["uvol_any"]
    )
    new["uvol_rw"] = jnp.where(
        mask, nodes["uvol_rw"] | pod["vol_rw"][None, :], nodes["uvol_rw"]
    )
    # As an existing pod, the placement counts toward EVERY service
    # whose selector matches it. Membership travels as a top-K id list
    # (i32[K], -1 padded) instead of a dense f32[S] row: at 50k pods x
    # 500 services the dense rows were 100 MB of upload per solve.
    # The commit is a K-element scatter-add into row `choice` — NOT a
    # broadcasted full-matrix add: rewriting the N x S counts matrix
    # every scan step costs ~N*S*8 bytes of HBM traffic per pod
    # (~500 GB over a 50k backlog), which alone blew the <2s budget.
    ids = pod["svc_ids"]
    row = jnp.maximum(choice, 0)
    valid = ((ids >= 0) & assigned).astype(jnp.float32)
    new["svc_counts"] = nodes["svc_counts"].at[row, jnp.maximum(ids, 0)].add(
        valid, mode="drop"
    )
    return new


def _scan_solve(pods, nodes, weights):
    N = nodes["cpu_cap"].shape[0]

    def step(carry, pod):
        feas = _feasible(pod, carry, N)
        score = _scores(pod, carry, weights)
        masked = jnp.where(feas, score, -1)
        best = jnp.argmax(masked).astype(jnp.int32)  # first max = lowest index
        # Feasibility folds into the same reduction: infeasible nodes
        # carry -1, so "any feasible" == "max masked value >= 0". One
        # N-wide reduction instead of two.
        choice = jnp.where(masked[best] >= 0, best, -1)
        return _commit(carry, pod, choice, N), choice

    # The scan is latency-bound on TPU (per-iteration sequencing
    # overhead ~30us dominates the ~500KB the body actually touches).
    # unroll=2 halves that overhead — measured 1.6s -> 0.93s on the
    # 50k x 5k backlog — while higher factors lose to register/VMEM
    # pressure. Decisions are bit-identical for any unroll.
    return jax.lax.scan(step, nodes, pods, unroll=2)


@functools.partial(jax.jit, static_argnames=("weights",))
def solve(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
) -> jnp.ndarray:
    """Sequential-parity assignment: i32[P] of node indices (-1 =
    unschedulable). The scan IS the reference's scheduleOne loop."""
    _, assignment = _scan_solve(pods, nodes, weights)
    return assignment


@functools.partial(
    jax.jit, static_argnames=("weights",), donate_argnames=("nodes",)
)
def solve_with_state(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Like solve, but also returns the post-commit occupancy carry.
    `nodes` is DONATED: the caller's buffers are consumed and the
    returned state aliases them — the substrate for incremental churn
    (SolverSession keeps this state device-resident across ticks)."""
    final, assignment = _scan_solve(pods, nodes, weights)
    return assignment, final


def solve_assignments(
    dsnap: DeviceSnapshot, weights: Tuple[int, int, int] = DEFAULT_WEIGHTS
) -> np.ndarray:
    """Run the solver and strip padding: returns i32[n_pods] with real
    node indices (-1 unschedulable)."""
    out = np.asarray(solve(dsnap.pods, dsnap.nodes, weights))
    out = out[: dsnap.n_pods]
    # Padding nodes can never be chosen (schedulable=False), but clamp
    # defensively so a bug can't leak a phantom index.
    out = np.where(out >= dsnap.n_nodes, -1, out)
    return out
