"""Sinkhorn-matched wave solver: entropic assignment with congestion
prices.

The north star (BASELINE.json) frames batch scheduling as an
assignment problem: "masked softmax scoring + Hungarian/Sinkhorn
matching". The plain wave solver (ops.wave) already batches windows of
pods per device step, but every pod picks its argmax node
*independently* — popular nodes draw many winners, the capacity packer
rejects most, and the conflict losers burn another wave. Here each
wave first runs a few log-domain Sinkhorn iterations over the masked
score matrix:

    T = diag(u) . exp(S/eps) . diag(v)

with row marginals fixed at 1 (each pod places once) and column
scalings CAPPED at each node's remaining pod-count capacity — the
unbalanced-OT variant: a column that would receive more mass than it
can hold gets its price lowered (g_j < 0) until demand matches
capacity, while under-subscribed columns are never artificially
boosted (g_j <= 0). Pods then argmax the PRICED scores S_ij + g_j:
congestion pricing spreads one wave's choices across the fleet, so far
more pods survive the capacity packer per wave and the whole backlog
settles in a fraction of the waves.

Feasibility stays exact: prices only reorder *feasible* choices, and
the shared windowed loop (ops.wave.run_windowed) applies the same
capacity-aware packer + bulk commit as the plain wave solver, so the
CPU/memory/pod-count/port/volume invariants live in exactly one
place. Decision parity with the sequential oracle is approximate by
design (the scan in ops.solver remains the parity path); what this
mode buys is throughput, published by bench.py.

No reference code corresponds — kubernetes schedules one pod per loop
iteration (plugin/pkg/scheduler/scheduler.go:113-158).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.ledger import traced_jit
from kubernetes_tpu.ops.solver import DEFAULT_WEIGHTS
from kubernetes_tpu.ops.wave import _tie_hash, run_windowed, strip_assignments

_NEG = jnp.float32(-1e30)


def _congestion_prices(
    masked: jnp.ndarray,  # f32[W, N]: weighted score, -1 where infeasible
    valid: jnp.ndarray,  # bool[W]: real (non-padding) undecided pod
    capacity: jnp.ndarray,  # f32[N]: remaining pod-count capacity
    eps: float,
    iters: int,
    tol: float = 0.0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Capped Sinkhorn with convergence telemetry. Returns
    (g f32[N], iters_run i32, residual f32): row-normalize the plan so
    each shipping pod distributes one unit of mass by
    softmax((S + g)/eps), then lower g wherever a column's mass exceeds
    its capacity.

    The residual is the worst column's log-domain mass excess over its
    capacity, measured entering the last executed price update (0 =
    demand already fits everywhere; further updates are no-ops), and
    iters_run counts the updates actually executed — the convergence
    telemetry scheduler_sinkhorn_residual / scheduler_solve_iterations
    surface. `tol` stops the loop early once the residual is at or
    below it; the default 0.0 reproduces the historic fixed-iteration
    prices bit-for-bit (a zero residual means every remaining update
    is the identity)."""
    logits = jnp.where(masked >= 0, masked / eps, _NEG)
    # Pods with zero feasible nodes ship NO mass: letting them
    # row-normalize anyway would spray phantom demand across nodes they
    # can never use, depressing prices exactly where feasible pods
    # should be going (they finalize -1 this wave regardless).
    ships = valid & jnp.any(masked >= 0, axis=1)
    log_a = jnp.where(ships, 0.0, _NEG)
    log_b = jnp.where(capacity > 0, jnp.log(jnp.maximum(capacity, 1e-9)), _NEG)

    def cond(state):
        i, _, res = state
        return (i < iters) & (res > tol)

    def body(state):
        i, g, _ = state
        # g lives in the SCORE domain (it is added to S at the argmax),
        # so inside the softmax it scales by 1/eps like the scores.
        row = logits + g[None, :] / eps
        row_lse = jax.nn.logsumexp(row, axis=1, keepdims=True)
        log_t = log_a[:, None] + row - jnp.maximum(row_lse, _NEG)
        col_mass = jax.nn.logsumexp(log_t, axis=0)  # f32[N]
        excess = jnp.where(
            capacity > 0, jnp.maximum(col_mass - log_b, 0.0), 0.0
        )
        # Overloaded columns get cheaper; never boost empty ones.
        g = g + jnp.minimum(0.0, log_b - col_mass) * eps
        return i + 1, g, jnp.max(excess)

    i, g, res = jax.lax.while_loop(
        cond,
        body,
        (jnp.int32(0), jnp.zeros_like(capacity), jnp.float32(jnp.inf)),
    )
    # A window that never iterated (iters == 0) reports residual 0.
    return g, i, jnp.where(jnp.isinf(res), 0.0, res)


def _priced_choose(masked, idx, valid, carry, N, *, eps, iters, price_cap,
                   tol=0.0):
    """Sinkhorn-priced choice: argmax over S_ij + g_j with a tiny
    deterministic jitter as tie-break. Returns (choice, iters_run,
    residual) — the telemetry rides the windowed loop's carry
    (ops.wave.run_windowed) up to the solve wrappers.

    price_cap bounds how far pricing may push a pod off its greedy
    best: with g clamped to [-price_cap, 0], the chosen node satisfies
    S_chosen >= S_best + g_best - g_chosen >= S_best - price_cap — a
    PROOF-backed per-choice regret bound (the quality axis VERDICT r3
    weak #4 flagged: unclamped prices bought speed at p99 regret 14).
    Congestion relief degrades gracefully: overloaded columns still
    repel up to the cap, they just can't exile pods arbitrarily far."""
    remaining = jnp.maximum(carry["pods_cap"] - carry["pods_used"], 0.0)
    g, iters_run, residual = _congestion_prices(
        masked.astype(jnp.float32), valid, remaining, eps, iters, tol
    )
    g = jnp.maximum(g, -jnp.float32(price_cap))
    priced = jnp.where(
        masked >= 0, masked.astype(jnp.float32) + g[None, :], -jnp.inf
    )
    jitter = _tie_hash(idx, N).astype(jnp.float32) * jnp.float32(1e-6)
    choice = jnp.argmax(priced + jitter, axis=1).astype(jnp.int32)
    return choice, iters_run, residual


def sinkhorn_assignments(dsnap, **kw):
    """Run the Sinkhorn wave solver and strip padding: returns
    (i32[n_pods] with -1 = unschedulable, wave count). Convergence
    telemetry (total price iterations + final residual) is observed
    into scheduler_solve_iterations / scheduler_sinkhorn_residual and
    noted on the solve span."""
    from kubernetes_tpu.utils import flightrecorder, tracing

    with tracing.phase("solve", solver="sinkhorn") as sp:
        out, waves, titers, residual = solve_sinkhorn_stats(
            dsnap.pods, dsnap.nodes, **kw
        )
        stripped = strip_assignments(dsnap, out)
        waves = int(waves)
        titers = int(titers)
        residual = float(residual)
        sp.note(
            waves=waves, sinkhorn_iters=titers,
            sinkhorn_residual=round(residual, 4),
        )
    flightrecorder.observe_solve_telemetry(
        "sinkhorn", titers, residual=residual, waves=waves
    )
    return stripped, waves


@traced_jit(
    static_argnames=("weights", "window", "per_node_limit", "eps", "iters",
                     "price_cap", "tol"),
)
def solve_sinkhorn_stats(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
    window: int = 4096,
    per_node_limit: int = 2,
    eps: float = 2.0,
    iters: int = 8,
    price_cap: float = 4.0,
    tol: float = 0.0,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(assignment i32[P] with -1 = unschedulable, wave count, total
    Sinkhorn price iterations, final residual).

    Same contract and commit path as ops.wave.solve_waves; the choice
    step is Sinkhorn-priced instead of raw argmax, so the per-node
    acceptance limit can be far looser (prices already meter demand to
    capacity) — that is where the wave-count win comes from. The
    telemetry scalars ride the windowed loop's carry: the iteration
    total sums every wave's price updates, the residual is the LAST
    wave's (see _congestion_prices)."""
    choose = functools.partial(
        _priced_choose, eps=eps, iters=iters, price_cap=price_cap, tol=tol
    )
    assignment, _, waves, titers, residual = run_windowed(
        pods, nodes, weights, window, per_node_limit, choose
    )
    return assignment, waves, titers, residual


def solve_sinkhorn(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    **kw,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(assignment i32[P] with -1 = unschedulable, wave count) — thin
    alias of solve_sinkhorn_stats (ONE jit cache) for callers that
    don't read the convergence telemetry."""
    assignment, waves, _, _ = solve_sinkhorn_stats(pods, nodes, **kw)
    return assignment, waves


@traced_jit(
    static_argnames=("weights", "window", "per_node_limit", "eps", "iters",
                     "price_cap", "tol"),
    donate_argnames=("nodes",),
)
def solve_sinkhorn_with_state(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
    window: int = 4096,
    per_node_limit: int = 2,
    eps: float = 2.0,
    iters: int = 8,
    price_cap: float = 4.0,
    tol: float = 0.0,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray,
           jnp.ndarray]:
    """Like solve_sinkhorn_stats, but also returns the post-commit
    occupancy carry; `nodes` is DONATED (the incremental-churn
    substrate). Returns (assignment, carry, waves, total Sinkhorn
    iterations, final residual)."""
    choose = functools.partial(
        _priced_choose, eps=eps, iters=iters, price_cap=price_cap, tol=tol
    )
    assignment, carry, waves, titers, residual = run_windowed(
        pods, nodes, weights, window, per_node_limit, choose
    )
    return assignment, carry, waves, titers, residual
