"""Rebalancing kernel: the descheduler's migration plan as one dense
scan over the movable-pod axis.

Roadmap item 5's device half. The capacity plane (ops/capacity.py)
measures fragmentation; this kernel spends that measurement: given the
cluster's occupancy columns and a worklist of movable bound pods
(host-sorted largest-first — best-fit-decreasing), it re-places each
pod against the *evolving* occupancy carry and emits a minimal-move
migration plan:

- **destination choice** is best-fit: among feasible live nodes
  (schedulable, not overcommitted, fits cpu/mem and one pods-allowance
  slot, not the pod's current node) pick the one with the least
  leftover capacity in the pod's own units — consolidation pressure,
  the inverse of the solver's spreading default, because defrag WANTS
  tight packing so whole nodes drain free.
- **gain** is the marginal fragmentation-score improvement in the
  capacity plane's own objective: the change in summed integral probe
  fits (``capacity_report``'s ``headroom`` numerator) at the two
  touched nodes, int32 in probe units. The aggregate frag score is
  ``1 - usable*FRAC_Q/potential`` and cross-node free capacity (the
  ``potential`` denominator) is conserved by a move, so ranking by
  delta-usable IS ranking by score improvement.
- a move commits only while the **move budget** lasts and only if
  ``gain > 0`` — unless the pod is **forced** (``pod_force``: the
  autoscaler's cordon-drain path, where the source node is leaving and
  any feasible destination beats stranding).

The scan carries the occupancy columns forward through every committed
move, so later pods see earlier moves — the plan is self-consistent
and can be executed in emission order. Bit-exactness discipline is
inherited from ops/capacity.py: every cross-node/cross-probe reduction
sums int32 (fits clipped to FIT_CAP, fractions quantized to 1/FRAC_Q),
argmin tie-breaks take the first minimum in both XLA and NumPy, and
the remaining float work is elementwise f32 — so the KT006 twin
(``ops.oracle.plan_moves_numpy``) matches bit-for-bit, no tolerance.

Gang atomicity is deliberately NOT in the kernel: the host half
(utils/rebalance.py) groups the per-pod rows by gang and drops partial
groups, because gang membership is label metadata the columns never
carry — same split as the solver (device proposes, gang.py accepts).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.capacity import BIG_FIT, FIT_CAP, FRAC_Q
from kubernetes_tpu.ops.ledger import traced_jit

#: Sentinel best-fit key for infeasible destinations: above any real
#: quantized leftover (FIT_CAP * FRAC_Q = 2^17) by a wide margin.
NO_FIT_KEY = 2**30


@traced_jit
def plan_moves(
    cpu_cap,
    mem_cap,
    pods_cap,
    cpu_fit,
    mem_fit,
    pods_used,
    over,
    sched,
    pod_cpu,
    pod_mem,
    pod_node,
    pod_live,
    pod_force,
    probe_cpu,
    probe_mem,
    probe_min,
    probe_live,
    move_budget,
):
    """One defrag plan: re-place every movable pod best-fit against the
    evolving occupancy carry, commit moves with positive probe-fit gain
    (or forced drains) under a move budget.

    Node columns are the NODE_SCHEMA occupancy view (same eight
    ``capacity_report`` consumes). Pod rows are the movable worklist:
    requests in column units, ``pod_node`` the current placement index,
    ``pod_live`` masking padding rows, ``pod_force`` the drain flag.
    Probes are the capacity plane's probe-shape set — the objective.
    ``move_budget`` is an i32 scalar array. Returns a flat tuple:

    ``(dest i32[D], moved b8[D], gain i32[D], n_moves i32[],
    score_before f32[], score_after f32[])``

    ``dest`` is -1 for uncommitted rows; ``gain`` is the committed
    move's delta-usable (0 otherwise); the scores are the capacity
    plane's exact ``frag_score`` over the carry before and after.
    """
    f0 = jnp.float32(0.0)
    f1 = jnp.float32(1.0)
    big = jnp.float32(BIG_FIT)
    live = sched & ~over
    livef = live.astype(jnp.float32)
    n = cpu_cap.shape[0]
    plive_i = probe_live.astype(jnp.int32)

    def node_fits(free_cpu, free_mem, free_pods):
        """Per-probe integral/quantized fits for free vectors of any
        trailing shape — capacity_report's fit math verbatim."""
        pc = probe_cpu[:, None]
        pm = probe_mem[:, None]
        per_cpu = jnp.where(
            pc > f0, free_cpu[None, :] / jnp.maximum(pc, f1), big
        )
        per_mem = jnp.where(
            pm > f0, free_mem[None, :] / jnp.maximum(pm, f1), big
        )
        fit_frac = jnp.minimum(
            jnp.minimum(per_cpu, per_mem), free_pods[None, :]
        )
        fit_frac = jnp.clip(fit_frac, f0, jnp.float32(FIT_CAP))
        fit_int = jnp.floor(fit_frac).astype(jnp.int32)
        frac_q = jnp.floor(fit_frac * jnp.float32(FRAC_Q)).astype(jnp.int32)
        return fit_int, frac_q

    def free_vectors(cf, mf, pu):
        free_cpu = jnp.maximum(cpu_cap - cf, f0) * livef
        free_mem = jnp.maximum(mem_cap - mf, f0) * livef
        free_pods = jnp.maximum(pods_cap - pu, f0) * livef
        return free_cpu, free_mem, free_pods

    def frag_score(cf, mf, pu):
        """capacity_report's aggregate score over one occupancy state:
        int32 totals, f32 ratio, clipped [0, 1]."""
        fit_int, frac_q = node_fits(*free_vectors(cf, mf, pu))
        usable = jnp.sum(jnp.sum(fit_int, axis=1) * plive_i)
        potential = jnp.sum(jnp.sum(frac_q, axis=1) * plive_i)
        score = jnp.where(
            potential > jnp.int32(0),
            f1
            - (usable.astype(jnp.float32) * jnp.float32(FRAC_Q))
            / potential.astype(jnp.float32),
            f0,
        )
        return jnp.clip(score, f0, f1)

    def node_usable(fc, fm, fp):
        """One node's summed integral probe fit (i32 scalar) — the
        gain evaluation at a touched node."""
        pcu = jnp.where(probe_cpu > f0, fc / jnp.maximum(probe_cpu, f1), big)
        pme = jnp.where(probe_mem > f0, fm / jnp.maximum(probe_mem, f1), big)
        ff = jnp.clip(jnp.minimum(jnp.minimum(pcu, pme), fp), f0,
                      jnp.float32(FIT_CAP))
        return jnp.sum(jnp.floor(ff).astype(jnp.int32) * plive_i)

    score_before = frag_score(cpu_fit, mem_fit, pods_used)

    def step(carry, pod):
        cf, mf, pu, moves = carry
        cpu, mem, src, alive, force = pod
        free_cpu, free_mem, free_pods = free_vectors(cf, mf, pu)

        src_c = jnp.clip(src, 0, n - 1)
        src_valid = (src >= 0) & (src < n)
        is_src = (jnp.arange(n, dtype=jnp.int32) == src_c) & src_valid

        feasible = (
            live
            & (free_cpu >= cpu)
            & (free_mem >= mem)
            & (free_pods >= f1)
            & ~is_src
        )

        # Best-fit key: quantized leftover capacity at the candidate,
        # measured in the pod's own units (zero-request dims read
        # unconstrained); first-minimum argmin in both XLA and NumPy.
        kc = jnp.where(cpu > f0, (free_cpu - cpu) / jnp.maximum(cpu, f1), big)
        km = jnp.where(mem > f0, (free_mem - mem) / jnp.maximum(mem, f1), big)
        key_frac = jnp.clip(
            jnp.minimum(kc, km), f0, jnp.float32(FIT_CAP)
        )
        key = jnp.floor(key_frac * jnp.float32(FRAC_Q)).astype(jnp.int32)
        key = jnp.where(feasible, key, jnp.int32(NO_FIT_KEY))
        dst = jnp.argmin(key).astype(jnp.int32)
        any_feasible = jnp.any(feasible)

        # Gain: delta summed integral probe fit at the two touched
        # nodes (free capacity elsewhere is untouched). Source free
        # capacity GROWS by the pod's requests; destination SHRINKS.
        src_live = src_valid & live[src_c]

        u_src_before = jnp.where(
            src_live,
            node_usable(free_cpu[src_c], free_mem[src_c], free_pods[src_c]),
            jnp.int32(0),
        )
        u_src_after = jnp.where(
            src_live,
            node_usable(
                jnp.maximum(cpu_cap[src_c] - (cf[src_c] - cpu), f0),
                jnp.maximum(mem_cap[src_c] - (mf[src_c] - mem), f0),
                jnp.maximum(pods_cap[src_c] - (pu[src_c] - f1), f0),
            ),
            jnp.int32(0),
        )
        u_dst_before = node_usable(free_cpu[dst], free_mem[dst],
                                   free_pods[dst])
        u_dst_after = node_usable(
            jnp.maximum(cpu_cap[dst] - (cf[dst] + cpu), f0),
            jnp.maximum(mem_cap[dst] - (mf[dst] + mem), f0),
            jnp.maximum(pods_cap[dst] - (pu[dst] + f1), f0),
        )
        gain = (u_src_after + u_dst_after) - (u_src_before + u_dst_before)

        commit = (
            alive
            & any_feasible
            & (moves < move_budget)
            & ((gain > jnp.int32(0)) | force)
        )
        cmf = commit.astype(jnp.float32)
        dst_hot = (jnp.arange(n, dtype=jnp.int32) == dst).astype(jnp.float32)
        src_hot = is_src.astype(jnp.float32)
        cf = cf + cmf * cpu * (dst_hot - src_hot)
        mf = mf + cmf * mem * (dst_hot - src_hot)
        pu = pu + cmf * (dst_hot - src_hot)
        moves = moves + commit.astype(jnp.int32)

        out = (
            jnp.where(commit, dst, jnp.int32(-1)),
            commit,
            jnp.where(commit, gain, jnp.int32(0)),
        )
        return (cf, mf, pu, moves), out

    init = (cpu_fit, mem_fit, pods_used, jnp.int32(0))
    (cf, mf, pu, n_moves), (dest, moved, gain) = jax.lax.scan(
        step,
        init,
        (pod_cpu, pod_mem, pod_node, pod_live, pod_force),
    )
    score_after = frag_score(cf, mf, pu)
    return dest, moved, gain, n_moves, score_before, score_after
