"""The kernel/oracle parity registry: every jitted kernel in ops/ and
the NumPy twin that referees it.

The paper's whole bet is that scheduling decisions can move onto the
accelerator WITHOUT changing them — so a jitted kernel without a host
oracle is an unreviewable kernel. This module is the machine-checkable
ledger of that contract. ktlint's KT006 pass (tools/ktlint/
rules_parity.py) statically cross-checks it against the tree:

- every ``jax.jit``-decorated function under ``kubernetes_tpu/ops/``
  must appear as a key here;
- every entry's ``oracle`` must resolve to a real function (dotted
  path relative to ``kubernetes_tpu/``, or ``tests.`` for test-local
  helpers);
- every entry's ``suite`` file must exist and actually mention the
  kernel (or its ``exercised_as`` public wrapper, or the oracle) — a
  registered-but-never-run twin is as useless as no twin.

``tests/test_ktsan.py`` additionally imports this registry at runtime
and asserts every reference resolves via getattr, so a rename cannot
rot the ledger between static sweeps.

Keys are ``<ops module>.<dotted def path>`` (nested jits include their
enclosing function: ``preemption._victim_prefix_kernel.kernel``).

KT006 intentionally has no baseline: a new kernel lands WITH its twin
or it does not land. Use ``exercised_as`` when the suite drives the
kernel through a public wrapper rather than by its private name.

Every key here ALSO needs a shape/dtype/sharding contract in
``kubernetes_tpu/ops/contracts.py`` (CONTRACTS) — the ktshape checker
(``python -m tools.ktlint --kernel-contracts``) enforces completeness
in both directions, so this registry and the contract registry are one
kernel inventory with two faces: the twin referees the DECISIONS, the
contract pins the INTERFACE (bucket lattices, oracle dtypes, pod-axis
coupling class).
"""

from __future__ import annotations

# NOTE: must stay a literal dict — KT006 reads it by AST, without
# importing jax.
ORACLE_TWINS = {
    "capacity.capacity_report": {
        # Bit-exact twin (int32-quantized reductions): the parity suite
        # asserts array_equal on every leaf, no tolerance.
        "oracle": "ops.oracle.capacity_report_numpy",
        "suite": "tests/test_solver_parity.py",
    },
    "incremental._scatter_rows": {
        "oracle": "ops.oracle.scatter_rows_numpy",
        "suite": "tests/test_ktsan.py",
    },
    "matrices.gang_member_counts": {
        "oracle": "scheduler.gang.member_counts_host",
        "suite": "tests/test_gang.py",
    },
    "pallas_scan._solve_packed": {
        # Parity chain: pallas == XLA scan (bit-exact, its suite) and
        # XLA scan == sequential NumPy oracle (test_solver_parity.py).
        "oracle": "ops.oracle.solve_sequential_numpy",
        "suite": "tests/test_pallas_scan.py",
        "exercised_as": "solve_with_state_pallas",
    },
    "preemption._victim_prefix_kernel.kernel": {
        "oracle": "scheduler.batch.preempt_backlog_scalar",
        "suite": "tests/test_solver_parity.py",
        "exercised_as": "preempt_backlog_scalar",
    },
    "rebalance.plan_moves": {
        # Bit-exact twin (the capacity plane's int32-quantized fit
        # math + a Python rewrite of the lax.scan): array_equal on
        # every leaf, no tolerance.
        "oracle": "ops.oracle.plan_moves_numpy",
        "suite": "tests/test_solver_parity.py",
    },
    "sinkhorn.solve_sinkhorn_stats": {
        "oracle": "ops.oracle.validate_assignment_numpy",
        "suite": "tests/test_sinkhorn.py",
        "exercised_as": "solve_sinkhorn",
    },
    "sinkhorn.solve_sinkhorn_with_state": {
        "oracle": "ops.oracle.validate_assignment_numpy",
        "suite": "tests/test_sinkhorn.py",
        "exercised_as": "sinkhorn_assignments",
    },
    "solver._solve_xla": {
        "oracle": "ops.oracle.solve_sequential_numpy",
        "suite": "tests/test_solver_parity.py",
    },
    "solver._solve_with_state_xla": {
        "oracle": "ops.oracle.solve_sequential_numpy",
        "suite": "tests/test_solver_parity.py",
    },
    "solver.explain_rows": {
        "oracle": "ops.oracle.explain_bits_numpy",
        "suite": "tests/test_solver_parity.py",
    },
    "wave.solve_waves": {
        "oracle": "ops.oracle.validate_assignment_numpy",
        "suite": "tests/test_wave.py",
    },
    "wave.solve_waves_with_state": {
        "oracle": "ops.oracle.validate_assignment_numpy",
        "suite": "tests/test_wave.py",
        "exercised_as": "solve_waves_with_state",
    },
}
