"""Solver sidecar: the device solver as an isolated process.

The north-star architecture (SURVEY §2.15/§5) keeps the control plane
and the accelerator in SEPARATE processes: the reference-shaped control
plane never touches JAX, the sidecar owns the TPU, and a sidecar crash
degrades to the stock scalar path instead of taking the scheduler down.
This module is that boundary: a length-prefixed pickle protocol over a
unix socket (numpy arrays serialize near-zero-copy with protocol 5),
a client that lowers API objects to the columnar snapshot host-side and
ships only arrays, and a `python -m kubernetes_tpu.ops.sidecar` server
entry point.

Failure contract: any transport/sidecar error raises SidecarError; the
BatchScheduler's existing fallback seam (scheduler/daemon.py
schedule_batch) then runs the scalar oracle — the degradation story the
reference's stock-FitPredicate fallback implies, now process-real.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
import sys
import tempfile
import time
from typing import List, Optional, Sequence

from kubernetes_tpu.models.columnar import Snapshot, build_snapshot


class SidecarError(Exception):
    pass


# -- framing ----------------------------------------------------------


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=5)
    sock.sendall(struct.pack(">Q", len(payload)) + payload)


def _recv_msg(sock: socket.socket):
    head = _recv_exact(sock, 8)
    (n,) = struct.unpack(">Q", head)
    if n > 1 << 31:
        raise SidecarError(f"oversized frame ({n} bytes)")
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise SidecarError("sidecar connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _snapshot_payload(snap: Snapshot) -> dict:
    p, n = snap.pods, snap.nodes
    return {
        "pods": {
            "cpu_milli": p.cpu_milli,
            "mem_mib": p.mem_mib,
            "zero_req": p.zero_req,
            "selector_id": p.selector_id,
            "port_bits": p.port_bits,
            "vol_any_bits": p.vol_any_bits,
            "vol_rw_bits": p.vol_rw_bits,
            "pinned_node": p.pinned_node,
            "service_id": p.service_id,
            "svc_topk": p.svc_topk,
            "sel_bits": p.sel_bits,
            "aff_pin": p.aff_pin,
        },
        "nodes": {
            "cpu_cap": n.cpu_cap,
            "mem_cap": n.mem_cap,
            "pods_cap": n.pods_cap,
            "cpu_fit_used": n.cpu_fit_used,
            "mem_fit_used": n.mem_fit_used,
            "overcommitted": n.overcommitted,
            "cpu_used": n.cpu_used,
            "mem_used": n.mem_used,
            "pods_used": n.pods_used,
            "label_bits": n.label_bits,
            "used_port_bits": n.used_port_bits,
            "used_vol_any_bits": n.used_vol_any_bits,
            "used_vol_rw_bits": n.used_vol_rw_bits,
            "service_counts": n.service_counts,
            "schedulable": n.schedulable,
            "policy_ok": n.policy_ok,
            "static_prio": n.static_prio,
            "aff_vid": n.aff_vid,
            "aa_zone": n.aa_zone,
        },
        # Policy lowering (None/default for the stock pipeline).
        "lowered": snap.lowered,
        "weights": snap.weights,
        "anchor_init": snap.anchor_init,
        "svc_total_init": snap.svc_total_init,
    }


def _snapshot_from_payload(payload: dict) -> Snapshot:
    from kubernetes_tpu.models.columnar import (
        NodeColumns,
        PodColumns,
        Vocab,
    )

    p = payload["pods"]
    n = payload["nodes"]
    P = len(p["cpu_milli"])
    N = len(n["cpu_cap"])
    pods = PodColumns(names=[str(i) for i in range(P)], **p)
    nodes = NodeColumns(names=[str(j) for j in range(N)], **n)
    return Snapshot(
        pods=pods,
        nodes=nodes,
        label_vocab=Vocab(),
        port_vocab=Vocab(),
        vol_vocab=Vocab(),
        service_names=[],
        lowered=payload.get("lowered"),
        weights=payload.get("weights"),
        anchor_init=payload.get("anchor_init"),
        svc_total_init=payload.get("svc_total_init"),
    )


# -- client -----------------------------------------------------------


class SidecarSolver:
    """Client half: lowers API objects host-side, ships arrays to the
    sidecar, returns node names. Raises SidecarError on ANY failure so
    the caller's fallback seam engages.

    Trust model: the frames are pickle, so the socket is a PRIVILEGE
    BOUNDARY — only a same-user sidecar may serve it. The server chmods
    its socket 0600 and the client refuses sockets owned by another
    uid; point --solver-sidecar only at paths this user controls.

    The default timeout is deliberately short: a HUNG (not crashed)
    sidecar would otherwise stall every batch for the full timeout
    before the scalar fallback engages."""

    def __init__(self, sock_path: str, timeout: float = 15.0):
        self.sock_path = sock_path
        self.timeout = timeout

    def _request(self, obj, timeout: float) -> dict:
        try:
            st = os.stat(self.sock_path)
            if st.st_uid != os.geteuid():
                raise SidecarError(
                    f"sidecar socket {self.sock_path!r} owned by uid "
                    f"{st.st_uid}, not us — refusing (pickle boundary)"
                )
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(timeout)
            sock.connect(self.sock_path)
            try:
                _send_msg(sock, obj)
                return _recv_msg(sock)
            finally:
                sock.close()
        except (OSError, pickle.PickleError, EOFError) as e:
            raise SidecarError(f"sidecar transport failure: {e}")

    def solve(
        self,
        pending,
        nodes,
        assigned: Sequence = (),
        services: Sequence = (),
        mode: str = "scan",
        spec=None,
    ) -> List[Optional[str]]:
        # Policy lowering happens client-side (UnloweredPolicyError
        # surfaces here, pre-transport); the sidecar receives finished
        # columns + the static LoweredSpec and just solves.
        snap = build_snapshot(pending, nodes, assigned, services, spec=spec)
        reply = self._request(
            {"op": "solve", "mode": mode, **_snapshot_payload(snap)},
            self.timeout,
        )
        if reply.get("error"):
            raise SidecarError(f"sidecar solve failed: {reply['error']}")
        assignment = reply["assignment"]
        names = snap.nodes.names
        return [
            names[i] if 0 <= i < len(names) else None for i in assignment
        ]

    def ping(self) -> bool:
        try:
            return self._request({"op": "ping"}, 5.0).get("ok", False)
        except SidecarError:
            return False


def spawn_sidecar(
    sock_path: Optional[str] = None, wait: float = 60.0, env=None
) -> tuple:
    """Launch the sidecar subprocess; returns (Popen, sock_path)."""
    if sock_path is None:
        sock_path = os.path.join(
            tempfile.mkdtemp(prefix="ktpu-sidecar-"), "solver.sock"
        )
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubernetes_tpu.ops.sidecar", sock_path],
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        env=env,
    )
    client = SidecarSolver(sock_path)
    deadline = time.monotonic() + wait
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise SidecarError(
                f"sidecar exited rc={proc.returncode} before serving"
            )
        if os.path.exists(sock_path) and client.ping():
            return proc, sock_path
        time.sleep(0.1)
    proc.terminate()
    raise SidecarError("sidecar never became ready")


# -- server -----------------------------------------------------------


def serve(sock_path: str) -> None:
    """Sidecar main loop: owns the accelerator; solves snapshots.

    Per-connection containment is absolute: a garbage frame, a client
    that times out and hangs up mid-reply (BrokenPipe), or a solve
    crash must never exit this loop — a dead sidecar silently demotes
    every future batch to the scalar fallback."""
    from kubernetes_tpu.ops import device_snapshot
    from kubernetes_tpu.ops.solver import solve_assignments
    from kubernetes_tpu.ops.wave import wave_assignments

    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except OSError:
        pass
    server.bind(sock_path)
    os.chmod(sock_path, 0o600)  # pickle boundary: same-user only
    server.listen(4)
    while True:
        conn, _ = server.accept()
        try:
            req = _recv_msg(conn)
            if not isinstance(req, dict):
                _send_msg(conn, {"error": "request must be a dict"})
                continue
            if req.get("op") == "ping":
                _send_msg(conn, {"ok": True})
                continue
            try:
                snap = _snapshot_from_payload(req)
                dsnap = device_snapshot(snap)
                if req.get("mode") == "wave":
                    assignment, _waves = wave_assignments(dsnap)
                elif req.get("mode") == "sinkhorn":
                    from kubernetes_tpu.ops.sinkhorn import sinkhorn_assignments

                    assignment, _waves = sinkhorn_assignments(dsnap)
                else:
                    assignment = solve_assignments(dsnap)
                _send_msg(conn, {"assignment": assignment.tolist()})
            except Exception as e:  # solve failure -> structured error
                _send_msg(conn, {"error": f"{type(e).__name__}: {e}"})
        except Exception:
            pass  # bad frame / client hung up mid-reply; next client
        finally:
            conn.close()


if __name__ == "__main__":
    if len(sys.argv) != 2:
        sys.exit("usage: python -m kubernetes_tpu.ops.sidecar <socket-path>")
    serve(sys.argv[1])
