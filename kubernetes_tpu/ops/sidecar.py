"""Solver sidecar: the device solver as an isolated process.

The north-star architecture (SURVEY §2.15/§5) keeps the control plane
and the accelerator in SEPARATE processes: the reference-shaped control
plane never touches JAX, the sidecar owns the TPU, and a sidecar crash
degrades to the stock scalar path instead of taking the scheduler down.
This module is that boundary: a versioned, SCHEMA'D array protocol over
a unix socket, a client that lowers API objects to the columnar
snapshot host-side and ships only arrays, and a
`python -m kubernetes_tpu.ops.sidecar` server entry point.

Wire format (one frame per message, either direction):

    b"KTPU" | u16 version | u64 total_len | u32 header_len |
    header JSON | array bytes

The JSON header carries the structured message with ndarrays replaced
by {"__nd__": i} placeholders into an arrays table of {dtype, shape};
the raw buffers follow concatenated in table order (near-zero-copy
both ways). Tuples and the solver's LoweredSpec round-trip via tagged
objects. Version skew between control plane and sidecar — the process
that exists precisely to be restarted independently — therefore fails
with a CLEAN SidecarError instead of deserializing garbage, and no
pickle means a malicious frame can name no code to run.

Failure contract: any transport/sidecar error raises SidecarError; the
BatchScheduler's existing fallback seam (scheduler/daemon.py
schedule_batch) then runs the scalar oracle — the degradation story the
reference's stock-FitPredicate fallback implies, now process-real.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import subprocess
import sys
import tempfile
import time
from typing import List, Optional, Sequence

import numpy as np

from kubernetes_tpu.models.algspec import LoweredSpec
from kubernetes_tpu.models.columnar import Snapshot, build_snapshot


class SidecarError(Exception):
    pass


# -- framing ----------------------------------------------------------

_MAGIC = b"KTPU"
_VERSION = 2  # v1 was pickle; bumped with any schema change


def _encode(obj):
    """-> (header_bytes, [contiguous ndarrays])."""
    arrays: List[np.ndarray] = []

    def walk(x):
        if isinstance(x, np.ndarray):
            arrays.append(np.ascontiguousarray(x))
            return {"__nd__": len(arrays) - 1}
        if isinstance(x, LoweredSpec):
            return {"__lowered__": walk(dict(x._asdict()))}
        if isinstance(x, tuple):
            return {"__tuple__": [walk(v) for v in x]}
        if isinstance(x, dict):
            return {str(k): walk(v) for k, v in x.items()}
        if isinstance(x, list):
            return [walk(v) for v in x]
        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return float(x)
        if isinstance(x, np.bool_):
            return bool(x)
        if x is None or isinstance(x, (str, int, float, bool)):
            return x
        raise SidecarError(f"unencodable field type {type(x).__name__}")

    meta = walk(obj)
    header = json.dumps(
        {
            "meta": meta,
            "arrays": [
                {"dtype": a.dtype.str, "shape": list(a.shape)} for a in arrays
            ],
        },
        separators=(",", ":"),
    ).encode()
    return header, arrays


def _decode(header: bytes, body: bytes):
    """Every malformed-frame failure surfaces as SidecarError — the
    'any transport/sidecar error raises SidecarError' contract the
    fallback seam and ping() rely on (a raw TypeError from a corrupt
    dtype string would otherwise crash the readiness loop)."""
    try:
        doc = json.loads(header)
        specs = doc["arrays"]
        views = []
        mv = memoryview(body)  # slices of a memoryview are zero-copy
        off = 0
        for s in specs:
            dt = np.dtype(s["dtype"])
            n = int(np.prod(s["shape"])) * dt.itemsize
            if n < 0 or off + n > len(body):
                raise SidecarError("frame body shorter than its array table")
            views.append(
                np.frombuffer(mv[off:off + n], dtype=dt).reshape(s["shape"])
            )
            off += n

        def walk(x):
            if isinstance(x, dict):
                if "__nd__" in x and len(x) == 1:
                    return views[x["__nd__"]]
                if "__tuple__" in x and len(x) == 1:
                    return tuple(walk(v) for v in x["__tuple__"])
                if "__lowered__" in x and len(x) == 1:
                    return LoweredSpec(**walk(x["__lowered__"]))
                return {k: walk(v) for k, v in x.items()}
            if isinstance(x, list):
                return [walk(v) for v in x]
            return x

        return walk(doc["meta"])
    except SidecarError:
        raise
    except Exception as e:
        raise SidecarError(f"malformed frame: {type(e).__name__}: {e}")


def _send_msg(sock: socket.socket, obj) -> None:
    header, arrays = _encode(obj)
    total = len(header) + sum(a.nbytes for a in arrays)
    sock.sendall(
        _MAGIC + struct.pack(">HQI", _VERSION, total, len(header)) + header
    )
    for a in arrays:
        sock.sendall(a.data if a.nbytes else b"")


def _recv_msg(sock: socket.socket):
    head = _recv_exact(sock, 4 + 2 + 8 + 4)
    if head[:4] != _MAGIC:
        raise SidecarError("not a KTPU frame (magic mismatch)")
    version, total, header_len = struct.unpack(">HQI", head[4:])
    if version != _VERSION:
        raise SidecarError(
            f"sidecar protocol version skew: peer speaks v{version}, "
            f"this build speaks v{_VERSION} — restart the older side"
        )
    if total > 1 << 31 or header_len > total:
        raise SidecarError(f"oversized frame ({total} bytes)")
    header = _recv_exact(sock, header_len)
    body = _recv_exact(sock, total - header_len)
    return _decode(header, body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise SidecarError("sidecar connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _snapshot_payload(snap: Snapshot) -> dict:
    p, n = snap.pods, snap.nodes
    return {
        "pods": {
            "cpu_milli": p.cpu_milli,
            "mem_mib": p.mem_mib,
            "zero_req": p.zero_req,
            "selector_id": p.selector_id,
            "port_bits": p.port_bits,
            "vol_any_bits": p.vol_any_bits,
            "vol_rw_bits": p.vol_rw_bits,
            "pinned_node": p.pinned_node,
            "service_id": p.service_id,
            "svc_topk": p.svc_topk,
            "sel_bits": p.sel_bits,
            "aff_pin": p.aff_pin,
        },
        "nodes": {
            "cpu_cap": n.cpu_cap,
            "mem_cap": n.mem_cap,
            "pods_cap": n.pods_cap,
            "cpu_fit_used": n.cpu_fit_used,
            "mem_fit_used": n.mem_fit_used,
            "overcommitted": n.overcommitted,
            "cpu_used": n.cpu_used,
            "mem_used": n.mem_used,
            "pods_used": n.pods_used,
            "label_bits": n.label_bits,
            "used_port_bits": n.used_port_bits,
            "used_vol_any_bits": n.used_vol_any_bits,
            "used_vol_rw_bits": n.used_vol_rw_bits,
            "service_counts": n.service_counts,
            "schedulable": n.schedulable,
            "policy_ok": n.policy_ok,
            "static_prio": n.static_prio,
            "aff_vid": n.aff_vid,
            "aa_zone": n.aa_zone,
        },
        # Policy lowering (None/default for the stock pipeline).
        "lowered": snap.lowered,
        "weights": snap.weights,
        "anchor_init": snap.anchor_init,
        "svc_total_init": snap.svc_total_init,
    }


def _snapshot_from_payload(payload: dict) -> Snapshot:
    from kubernetes_tpu.models.columnar import (
        NodeColumns,
        PodColumns,
        Vocab,
    )

    p = payload["pods"]
    n = payload["nodes"]
    P = len(p["cpu_milli"])
    N = len(n["cpu_cap"])
    pods = PodColumns(names=[str(i) for i in range(P)], **p)
    nodes = NodeColumns(names=[str(j) for j in range(N)], **n)
    return Snapshot(
        pods=pods,
        nodes=nodes,
        label_vocab=Vocab(),
        port_vocab=Vocab(),
        vol_vocab=Vocab(),
        service_names=[],
        lowered=payload.get("lowered"),
        weights=payload.get("weights"),
        anchor_init=payload.get("anchor_init"),
        svc_total_init=payload.get("svc_total_init"),
    )


# -- client -----------------------------------------------------------


class SidecarSolver:
    """Client half: lowers API objects host-side, ships arrays to the
    sidecar, returns node names. Raises SidecarError on ANY failure so
    the caller's fallback seam engages.

    Trust model: the schema'd protocol carries only JSON + raw
    arrays (no code), but the socket remains same-user-only as defense
    in depth: the server chmods it 0600 and the client refuses sockets
    owned by another uid; point --solver-sidecar only at paths this
    user controls.

    The default timeout is deliberately short: a HUNG (not crashed)
    sidecar would otherwise stall every batch for the full timeout
    before the scalar fallback engages."""

    def __init__(self, sock_path: str, timeout: float = 15.0):
        self.sock_path = sock_path
        self.timeout = timeout

    def _request(self, obj, timeout: float) -> dict:
        try:
            st = os.stat(self.sock_path)
            if st.st_uid != os.geteuid():
                raise SidecarError(
                    f"sidecar socket {self.sock_path!r} owned by uid "
                    f"{st.st_uid}, not us — refusing (same-user boundary)"
                )
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(timeout)
            sock.connect(self.sock_path)
            try:
                _send_msg(sock, obj)
                return _recv_msg(sock)
            finally:
                sock.close()
        except (OSError, EOFError) as e:
            raise SidecarError(f"sidecar transport failure: {e}")

    def solve(
        self,
        pending,
        nodes,
        assigned: Sequence = (),
        services: Sequence = (),
        mode: str = "scan",
        spec=None,
    ) -> List[Optional[str]]:
        # Policy lowering happens client-side (UnloweredPolicyError
        # surfaces here, pre-transport); the sidecar receives finished
        # columns + the static LoweredSpec and just solves.
        snap = build_snapshot(pending, nodes, assigned, services, spec=spec)
        reply = self._request(
            {"op": "solve", "mode": mode, **_snapshot_payload(snap)},
            self.timeout,
        )
        if reply.get("error"):
            raise SidecarError(f"sidecar solve failed: {reply['error']}")
        assignment = reply["assignment"]
        names = snap.nodes.names
        return [
            names[i] if 0 <= i < len(names) else None for i in assignment
        ]

    def ping(self) -> bool:
        try:
            return self._request({"op": "ping"}, 5.0).get("ok", False)
        except SidecarError:
            return False


def spawn_sidecar(
    sock_path: Optional[str] = None, wait: float = 60.0, env=None
) -> tuple:
    """Launch the sidecar subprocess; returns (Popen, sock_path)."""
    if sock_path is None:
        sock_path = os.path.join(
            tempfile.mkdtemp(prefix="ktpu-sidecar-"), "solver.sock"
        )
    proc = subprocess.Popen(
        [sys.executable, "-m", "kubernetes_tpu.ops.sidecar", sock_path],
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
        env=env,
    )
    client = SidecarSolver(sock_path)
    deadline = time.monotonic() + wait
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise SidecarError(
                f"sidecar exited rc={proc.returncode} before serving"
            )
        if os.path.exists(sock_path) and client.ping():
            return proc, sock_path
        time.sleep(0.1)
    proc.terminate()
    raise SidecarError("sidecar never became ready")


# -- server -----------------------------------------------------------


def serve(sock_path: str) -> None:
    """Sidecar main loop: owns the accelerator; solves snapshots.

    Per-connection containment is absolute: a garbage frame, a client
    that times out and hangs up mid-reply (BrokenPipe), or a solve
    crash must never exit this loop — a dead sidecar silently demotes
    every future batch to the scalar fallback."""
    from kubernetes_tpu.ops import device_snapshot
    from kubernetes_tpu.ops.solver import solve_assignments
    from kubernetes_tpu.ops.wave import wave_assignments

    server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except OSError:
        pass
    server.bind(sock_path)
    os.chmod(sock_path, 0o600)  # same-user boundary
    server.listen(4)
    while True:
        conn, _ = server.accept()
        try:
            req = _recv_msg(conn)
            if not isinstance(req, dict):
                _send_msg(conn, {"error": "request must be a dict"})
                continue
            if req.get("op") == "ping":
                _send_msg(conn, {"ok": True})
                continue
            try:
                snap = _snapshot_from_payload(req)
                dsnap = device_snapshot(snap)
                if req.get("mode") == "wave":
                    assignment, _waves = wave_assignments(dsnap)
                elif req.get("mode") == "sinkhorn":
                    from kubernetes_tpu.ops.sinkhorn import sinkhorn_assignments

                    assignment, _waves = sinkhorn_assignments(dsnap)
                else:
                    assignment = solve_assignments(dsnap)
                _send_msg(conn, {"assignment": assignment.tolist()})
            except Exception as e:  # solve failure -> structured error
                _send_msg(conn, {"error": f"{type(e).__name__}: {e}"})
        except Exception:
            pass  # bad frame / client hung up mid-reply; next client
        finally:
            conn.close()


if __name__ == "__main__":
    if len(sys.argv) != 2:
        sys.exit("usage: python -m kubernetes_tpu.ops.sidecar <socket-path>")
    serve(sys.argv[1])
