"""Pallas TPU kernel for the sequential-parity scan solver.

The XLA lax.scan in ops/solver.py is latency-bound: each of the P steps
touches ~500KB of occupancy state in HBM and pays the scan's
per-iteration sequencing (~23us/step at 50k x 5k). That state fits in
VMEM with room to spare, which is exactly the case SURVEY.md §7 step 7
reserves for a hand kernel ("pallas kernels only where XLA fusion falls
short"). This kernel runs the ENTIRE sequential solve as one
pallas_call:

- grid = (P,): TPU grid steps execute sequentially on a core, so the
  occupancy carry lives in the OUTPUT refs (constant index_map keeps
  them VMEM-resident across all steps; they flush to HBM once at the
  end — the standard accumulator pattern).
- pod columns are packed host-side into ONE i32 row per pod
  (scalars + selector/port/volume bitset words + service top-K), so
  each grid step fetches a single tiny block instead of ~10.
- service spreading counts are (S, N) int16 in VMEM (counts are bounded
  by pods_cap <= 110, so int16 is exact; Mosaic vector arithmetic supports i16/i32, not i8); the XLA carry keeps its
  (N, S) f32 schema — the wrapper transposes/casts at the boundary.

Decision parity: the kernel reproduces ops/solver.py's default-spec
math op for op (integer LeastRequested, f32 BalancedResourceAllocation
with the same +1e-5 boundary epsilon, integer ServiceSpreading,
first-max-by-lowest-index tie-break). tests/test_pallas_scan.py checks
bit-identical assignments against the XLA scan (interpret mode on CPU,
the real kernel on TPU); policy specs and sharded meshes fall back to
the XLA path (ops/solver.py chooses).

Reference for the semantics being accelerated: the scheduleOne loop,
plugin/pkg/scheduler/scheduler.go:113-158 + generic_scheduler.go.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from kubernetes_tpu.ops.ledger import traced_jit

# Lane layout of the packed per-pod row (i32). Bitset word counts are
# static per compiled kernel (shape-derived).
#   [0]=cpu [1]=mem [2]=zero [3]=pinned [4]=svc
#   [5 : 5+SW]=sel  [..+PW]=port  [..+VW]=vol_any  [..+VW]=vol_rw
#   [..+K]=svc_ids
_FIXED = 5

# VMEM budget for the kernel's resident blocks (v5e: ~16MB/core; leave
# headroom for double-buffered pod blocks and compiler scratch).
VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def _svc_pad(S: int) -> int:
    """Service axis inside the kernel: banded dynamic-sublane access
    needs >= 8 rows and 8-row alignment."""
    return max(8, ((S + 7) // 8) * 8)


def _vmem_bytes(N: int, S: int, LW: int, PW: int, VW: int) -> int:
    """Resident bytes: the int16 counts carry appears as BOTH a full
    input block and a full output block (so 2x), as do the word
    carries; f32 rows are cheap but counted."""
    counts = 2 * _svc_pad(S) * N * 2
    words = 2 * (PW + 2 * VW) * N * 4 + LW * N * 4
    rows = (5 + 2 * 5 + 1) * N * 4  # consts + init+carry f32 rows
    return counts + words + rows


def pallas_eligible(pods: Dict, nodes: Dict, lspec) -> bool:
    """Default spec, single unsharded TPU device, VMEM-sized shapes."""
    if os.environ.get("KTPU_PALLAS", "") == "off":
        return False
    from kubernetes_tpu.models.algspec import DEFAULT_LOWERED

    if lspec != DEFAULT_LOWERED:
        return False  # policy columns: XLA scan carries them
    try:
        arr = nodes["cpu_cap"]
        if len(getattr(arr, "devices", lambda: [None])()) != 1:
            return False
        platform = next(iter(arr.devices())).platform
    except Exception:
        return False
    if platform != "tpu":
        return False
    N = nodes["cpu_cap"].shape[0]
    S = nodes["svc_counts"].shape[1]
    if N > 8192:
        return False  # the packed (score, 8191-idx) select needs N <= 8192
    return (
        _vmem_bytes(
            N,
            S,
            nodes["labels"].shape[1],
            nodes["uport"].shape[1],
            nodes["uvol_any"].shape[1],
        )
        <= VMEM_BUDGET_BYTES
    )


def _pack_pods(pods: Dict) -> jnp.ndarray:
    """One i32 row per pod; cpu/mem are integer-valued f32 (milli-CPU,
    MiB) so the cast is exact."""
    cols = [
        pods["cpu"].astype(jnp.int32)[:, None],
        pods["mem"].astype(jnp.int32)[:, None],
        pods["zero_req"].astype(jnp.int32)[:, None],
        pods["pinned"][:, None],
        pods["svc"][:, None],
        pods["sel"].astype(jnp.int32),
        pods["port"].astype(jnp.int32),
        pods["vol_any"].astype(jnp.int32),
        pods["vol_rw"].astype(jnp.int32),
        pods["svc_ids"],
    ]
    return jnp.concatenate(cols, axis=1)


def _kernel(
    SW: int, PW: int, VW: int, K: int, N: int, S: int, C: int, weights,
    packed_ref,
    cpu_cap_ref, mem_cap_ref, pods_cap_ref, over_ref, sched_ref,
    labels_ref,
    cpu_fit0_ref, mem_fit0_ref, cpu_used0_ref, mem_used0_ref,
    pods_used0_ref, uport0_ref, uvola0_ref, uvolr0_ref, counts0_ref,
    choice_ref,
    cpu_fit_ref, mem_fit_ref, cpu_used_ref, mem_used_ref, pods_used_ref,
    uport_ref, uvola_ref, uvolr_ref, counts_ref,
):
    """One grid step = C pods, looped sequentially inside (TPU block
    shapes need >=8 sublanes, so per-pod grid steps are out); the
    occupancy carry lives in the OUTPUT refs, resident across the whole
    sequential grid."""
    g = pl.program_id(0)

    @pl.when(g == 0)
    def _init():
        cpu_fit_ref[...] = cpu_fit0_ref[...]
        mem_fit_ref[...] = mem_fit0_ref[...]
        cpu_used_ref[...] = cpu_used0_ref[...]
        mem_used_ref[...] = mem_used0_ref[...]
        pods_used_ref[...] = pods_used0_ref[...]
        uport_ref[...] = uport0_ref[...]
        uvola_ref[...] = uvola0_ref[...]
        uvolr_ref[...] = uvolr0_ref[...]
        counts_ref[...] = counts0_ref[...]

    iota = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
    # Per-chunk choice accumulator: (C//128, 128) i32, flat index j.
    ch_rows = C // 128
    ch_iota = (
        jax.lax.broadcasted_iota(jnp.int32, (ch_rows, 128), 0) * 128
        + jax.lax.broadcasted_iota(jnp.int32, (ch_rows, 128), 1)
    )
    cap_c = cpu_cap_ref[...]  # (1, N) f32
    cap_m = mem_cap_ref[...]
    cap_p = pods_cap_ref[...]
    cap_ci = cap_c.astype(jnp.int32)
    cap_mi = cap_m.astype(jnp.int32)
    w_lr, w_bra, w_spread = weights

    rows8_sel = jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0)

    def body(j, choices):
        # 8-aligned band + sublane select (dynamic sublane indices must
        # be provably 8-aligned on TPU).
        jbase = pl.multiple_of((j // 8) * 8, 8)
        band = packed_ref[pl.ds(jbase, 8), :]  # (8, L) i32
        rmask = (rows8_sel == j % 8).astype(jnp.int32)  # (8, 1)
        row = jnp.sum(band * rmask, axis=0)  # (L,) i32
        cpu_f = row[0].astype(jnp.float32)
        mem_f = row[1].astype(jnp.float32)
        zero = row[2]
        pin = row[3]
        svc = row[4]

        used_p = pods_used_ref[...]
        # -- predicates (ops/solver.py _feasible, default spec) -------
        fits_cpu = (cap_c == 0) | (cpu_fit_ref[...] + cpu_f <= cap_c)
        fits_mem = (cap_m == 0) | (mem_fit_ref[...] + mem_f <= cap_m)
        fits_count = used_p + 1 <= cap_p
        nonzero_ok = (over_ref[...] == 0) & fits_cpu & fits_mem & fits_count
        zero_ok = used_p < cap_p
        # Boolean algebra, not where(): Mosaic can't legalize
        # arith.select on i1 vectors.
        zb = zero != 0
        ok = (sched_ref[...] != 0) & ((zb & zero_ok) | (~zb & nonzero_ok))
        for w in range(SW):
            sw = row[_FIXED + w]
            ok = ok & ((sw & labels_ref[w : w + 1, :]) == sw)
        for w in range(PW):
            pw = row[_FIXED + SW + w]
            ok = ok & ((pw & uport_ref[w : w + 1, :]) == 0)
        for w in range(VW):
            va = row[_FIXED + SW + PW + w]
            vr = row[_FIXED + SW + PW + VW + w]
            ok = ok & (
                ((vr & uvola_ref[w : w + 1, :]) | (va & uvolr_ref[w : w + 1, :]))
                == 0
            )
        ok = ok & ((pin == -1) | (iota == pin))

        # -- priorities (ops/solver.py _scores, default spec) ---------
        req_c = (cpu_used_ref[...] + cpu_f).astype(jnp.int32)
        req_m = (mem_used_ref[...] + mem_f).astype(jnp.int32)
        total = jnp.zeros((1, N), jnp.int32)
        if w_lr:
            def calc(req, cap):
                raw = jnp.where(
                    cap > 0, ((cap - req) * 10) // jnp.maximum(cap, 1), 0
                )
                return jnp.where((cap == 0) | (req > cap), 0, raw)

            total = total + (
                (calc(req_c, cap_ci) + calc(req_m, cap_mi)) // 2
            ) * w_lr
        if w_bra:
            cfrac = jnp.where(cap_ci == 0, 1.0, req_c / jnp.maximum(cap_ci, 1))
            mfrac = jnp.where(cap_mi == 0, 1.0, req_m / jnp.maximum(cap_mi, 1))
            bra = jnp.where(
                (cfrac >= 1) | (mfrac >= 1),
                0,
                (10 - jnp.abs(cfrac - mfrac) * 10 + 1e-5).astype(jnp.int32),
            )
            total = total + bra * w_bra
        if w_spread:
            # Dynamic sublane indexing must be 8-aligned on TPU: load
            # the aligned 8-row band around the service's row, then
            # select the row with a sublane one-hot reduction.
            slot = jnp.maximum(svc, 0)
            base = pl.multiple_of((slot // 8) * 8, 8)
            band = counts_ref[pl.ds(base, 8), :].astype(jnp.int32)  # (8, N)
            rows = jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0)
            counts = jnp.sum(
                band * (rows == slot % 8).astype(jnp.int32),
                axis=0,
                keepdims=True,
            )
            maxc = jnp.max(counts)
            spread_raw = (10 * (maxc - counts)) // jnp.maximum(maxc, 1)
            spread = jnp.where((svc < 0) | (maxc == 0), 10, spread_raw)
            total = total + spread * w_spread

        # -- select: first max by lowest index (generic.select_host) --
        # One reduction instead of three (max, tie-break min-index,
        # feasibility test): pack (score, inverted index) into one i32.
        # Among equal scores the larger 8191-idx — i.e. the LOWEST
        # index — wins, exactly the scalar oracle's tie-break. Scores
        # are bounded (<= 30 on the default spec) and N <= 8192 is an
        # eligibility requirement, so the pack cannot overflow or
        # collide. Infeasible nodes encode as -1, strictly below every
        # feasible encoding (score >= 0 => enc >= 8191 - idx >= 0).
        enc = jnp.where(ok, total * 8192 + (8191 - iota), -1)
        m = jnp.max(enc)
        choice = jnp.where(m >= 0, 8191 - (m & 8191), jnp.int32(-1))

        # -- commit (ops/solver.py _commit) ----------------------------
        assigned = choice >= 0
        onehot_b = (iota == choice) & assigned
        onehot_f = onehot_b.astype(jnp.float32)
        cpu_fit_ref[...] = cpu_fit_ref[...] + onehot_f * cpu_f
        mem_fit_ref[...] = mem_fit_ref[...] + onehot_f * mem_f
        cpu_used_ref[...] = cpu_used_ref[...] + onehot_f * cpu_f
        mem_used_ref[...] = mem_used_ref[...] + onehot_f * mem_f
        pods_used_ref[...] = pods_used_ref[...] + onehot_f
        for w in range(PW):
            pw = row[_FIXED + SW + w]
            uport_ref[w : w + 1, :] = jnp.where(
                onehot_b, uport_ref[w : w + 1, :] | pw, uport_ref[w : w + 1, :]
            )
        for w in range(VW):
            va = row[_FIXED + SW + PW + w]
            vr = row[_FIXED + SW + PW + VW + w]
            uvola_ref[w : w + 1, :] = jnp.where(
                onehot_b, uvola_ref[w : w + 1, :] | va, uvola_ref[w : w + 1, :]
            )
            uvolr_ref[w : w + 1, :] = jnp.where(
                onehot_b, uvolr_ref[w : w + 1, :] | vr, uvolr_ref[w : w + 1, :]
            )
        onehot_i32 = onehot_b.astype(jnp.int32)
        rows8 = jax.lax.broadcasted_iota(jnp.int32, (8, 1), 0)
        for k in range(K):
            sid = row[_FIXED + SW + PW + 2 * VW + k]
            valid = (sid >= 0) & assigned
            slot = jnp.maximum(sid, 0)
            base = pl.multiple_of((slot // 8) * 8, 8)
            band = counts_ref[pl.ds(base, 8), :]  # (8, N) i16
            # Mask product in i32 (this TPU's VPU has no i16 multiply),
            # cast to i16 for the add (i16 add IS supported).
            rmask = (rows8 == slot % 8).astype(jnp.int32)  # (8, 1)
            vmask = jnp.where(valid, onehot_i32, 0)  # (1, N) i32
            counts_ref[pl.ds(base, 8), :] = band + (rmask * vmask).astype(
                jnp.int16
            )
        return jnp.where(ch_iota == j, choice, choices)

    choices = jax.lax.fori_loop(
        0, C, body, jnp.full((ch_rows, 128), -1, jnp.int32)
    )
    choice_ref[...] = choices


@traced_jit(static_argnames=("weights", "interpret"))
def _solve_packed(pods, nodes, weights, interpret=False):
    """Prep (pack/transpose/cast) + pallas_call + carry rebuild, fused
    under one jit."""
    P = pods["cpu"].shape[0]
    N = nodes["cpu_cap"].shape[0]
    S = nodes["svc_counts"].shape[1]
    SW = pods["sel"].shape[1]
    PW = pods["port"].shape[1]
    VW = pods["vol_any"].shape[1]
    K = pods["svc_ids"].shape[1]

    packed = _pack_pods(pods)  # (P, L) i32
    L = packed.shape[1]
    # Chunk size per grid step: the largest divisor of P that is a
    # multiple of 128 (choice blocks need 128 lanes) and <= 1024. The
    # pod axis is always a multiple of 128 (matrices._pod_axis_bucket),
    # so C=128 is guaranteed to exist.
    C = 128
    for cand in (1024, 896, 768, 640, 512, 384, 256, 128):
        if cand <= P and P % cand == 0:
            C = cand
            break
    assert P % C == 0 and C % 128 == 0, (P, C)
    G = P // C

    row1 = lambda a, dt=None: (a if dt is None else a.astype(dt)).reshape(1, N)
    consts = [
        row1(nodes["cpu_cap"]),
        row1(nodes["mem_cap"]),
        row1(nodes["pods_cap"]),
        row1(nodes["over"], jnp.int32),
        row1(nodes["sched"], jnp.int32),
        nodes["labels"].astype(jnp.int32).T,  # (LW, N)
    ]
    # Service axis padded to the kernel's 8-row band granularity (and a
    # floor of 8): SolverSession carries unpadded S (even S=1 with no
    # services), and a dynamic 8-row band must never clamp into a
    # NEIGHBOR service's counts.
    SP = _svc_pad(S)
    counts0 = nodes["svc_counts"].astype(jnp.int16).T  # (S, N)
    if SP != S:
        counts0 = jnp.pad(counts0, [(0, SP - S), (0, 0)])
    init = [
        row1(nodes["cpu_fit"]),
        row1(nodes["mem_fit"]),
        row1(nodes["cpu_used"]),
        row1(nodes["mem_used"]),
        row1(nodes["pods_used"]),
        nodes["uport"].astype(jnp.int32).T,  # (PW, N)
        nodes["uvol_any"].astype(jnp.int32).T,
        nodes["uvol_rw"].astype(jnp.int32).T,
        counts0,  # (SP, N)
    ]
    LW = consts[5].shape[0]

    full = lambda shape: pl.BlockSpec(shape, lambda g: (0, 0))
    out_shapes = [
        jax.ShapeDtypeStruct((P // 128, 128), jnp.int32),  # choice, flat j
        jax.ShapeDtypeStruct((1, N), jnp.float32),
        jax.ShapeDtypeStruct((1, N), jnp.float32),
        jax.ShapeDtypeStruct((1, N), jnp.float32),
        jax.ShapeDtypeStruct((1, N), jnp.float32),
        jax.ShapeDtypeStruct((1, N), jnp.float32),
        jax.ShapeDtypeStruct((PW, N), jnp.int32),
        jax.ShapeDtypeStruct((VW, N), jnp.int32),
        jax.ShapeDtypeStruct((VW, N), jnp.int32),
        jax.ShapeDtypeStruct((SP, N), jnp.int16),
    ]
    out_specs = [
        pl.BlockSpec((C // 128, 128), lambda g: (g, 0)),
        full((1, N)), full((1, N)), full((1, N)), full((1, N)), full((1, N)),
        full((PW, N)), full((VW, N)), full((VW, N)), full((SP, N)),
    ]
    in_specs = (
        [pl.BlockSpec((C, L), lambda g: (g, 0))]
        + [full((1, N))] * 5
        + [full((LW, N))]
        + [full((1, N))] * 5
        + [full((PW, N)), full((VW, N)), full((VW, N)), full((SP, N))]
    )
    kernel = functools.partial(
        _kernel, SW, PW, VW, K, N, SP, C, tuple(weights)
    )
    outs = pl.pallas_call(
        kernel,
        grid=(G,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(packed, *consts, *init)

    choice = outs[0].reshape(P)
    new_nodes = dict(nodes)
    new_nodes["cpu_fit"] = outs[1].reshape(N)
    new_nodes["mem_fit"] = outs[2].reshape(N)
    new_nodes["cpu_used"] = outs[3].reshape(N)
    new_nodes["mem_used"] = outs[4].reshape(N)
    new_nodes["pods_used"] = outs[5].reshape(N)
    new_nodes["uport"] = outs[6].T.astype(nodes["uport"].dtype)
    new_nodes["uvol_any"] = outs[7].T.astype(nodes["uvol_any"].dtype)
    new_nodes["uvol_rw"] = outs[8].T.astype(nodes["uvol_rw"].dtype)
    new_nodes["svc_counts"] = outs[9][:S].T.astype(nodes["svc_counts"].dtype)
    return choice, new_nodes


def solve_with_state_pallas(
    pods: Dict, nodes: Dict, weights=(1, 1, 1), interpret: bool = False
) -> Tuple[jnp.ndarray, Dict]:
    """Drop-in for solver.solve_with_state on the default spec."""
    return _solve_packed(pods, nodes, tuple(weights), interpret=interpret)


def solve_pallas(pods: Dict, nodes: Dict, weights=(1, 1, 1), interpret: bool = False):
    choice, _ = _solve_packed(pods, nodes, tuple(weights), interpret=interpret)
    return choice
