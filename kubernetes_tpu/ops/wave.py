"""Wave-commit solver: many pods per device step.

The sequential-parity scan (ops.solver) replicates the reference's
pod-at-a-time semantics exactly, but its 50k dependent steps are
latency-bound on a single chip and latency-DOMINATED over a mesh
(every step is an argmax + tiny all-reduce over ICI). This solver
trades exact decision-order parity for wave-level batching:

  each wave:
    1. evaluate feasibility + scores for a WINDOW of undecided pods
       against the current cluster state — one batched W x N block of
       vector ops (shards cleanly over the node axis; per-wave
       collectives instead of per-pod);
    2. every pod picks its argmax node (same masking + lowest-index
       tie-break as the scan);
    3. pods that picked the same node are packed capacity-aware in
       FIFO order — a segmented prefix-sum over the sorted (node, pod)
       pairs accepts the prefix that fits (CPU, memory, pod count);
       pods carrying hostPort/volume bits only commit one-per-node-
       per-wave (conservative: within-wave conflicts are impossible);
    4. accepted pods commit in bulk (scatter-adds); pods infeasible on
       every node are finalized unschedulable (occupancy only grows,
       so infeasible-now is infeasible-forever); conflict losers retry
       next wave.

Decision parity vs the sequential oracle is deliberately APPROXIMATE:
pods in one wave don't see each other's spreading/balance effects.
The scan remains the >=99%-parity headline path and the referee;
bench.py publishes the wave solver's measured parity and speedup next
to it. Reference framing: BASELINE.json north star (assignment-solver
scheduling); no reference code corresponds — kubernetes schedules one
pod per loop iteration (plugin/pkg/scheduler/scheduler.go:113-158).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.ops.ledger import traced_jit
from kubernetes_tpu.ops.solver import DEFAULT_WEIGHTS, _feasible, _scores

UNDECIDED = -2  # assignment sentinel: not yet finalized


def strip_assignments(dsnap, out):
    """THE authority for the padding/sentinel convention: slice off
    padding pods, fold padded-node indices to -1. Every windowed-solver
    wrapper (wave, sinkhorn) and bench must come through here."""
    import numpy as np

    a = np.asarray(out)[: dsnap.n_pods]
    return np.where(a >= dsnap.n_nodes, -1, a)


def wave_assignments(dsnap, **kw):
    """Run the wave solver and strip padding: returns (i32[n_pods]
    with -1 = unschedulable, wave count)."""
    from kubernetes_tpu.utils import flightrecorder, tracing

    # The per-wave loop itself is jitted (one device program), so the
    # span carries the wave count as the device-side breakdown; the
    # strip blocks, so this phase includes the device time.
    with tracing.phase("solve", solver="wave") as sp:
        out, waves = solve_waves(dsnap.pods, dsnap.nodes, **kw)
        stripped = strip_assignments(dsnap, out)
        waves = int(waves)
        sp.note(waves=waves)
    flightrecorder.observe_solve_telemetry("wave", waves)
    return stripped, waves

FMAX = jnp.float32(3.4e38)


def _window_rows(pods: Dict, idx: jnp.ndarray) -> Dict:
    """Gather the window's pod rows (idx may contain P = padding)."""
    safe = jnp.minimum(idx, pods["cpu"].shape[0] - 1)
    return {k: v[safe] for k, v in pods.items()}


def _batched_eval(wpods: Dict, nodes: Dict, weights, N: int):
    feas = jax.vmap(lambda p: _feasible(p, nodes, N))(wpods)
    score = jax.vmap(lambda p: _scores(p, nodes, weights))(wpods)
    return feas, score


def _pack_window(
    choice: jnp.ndarray,  # i32[W] chosen node (-1 = none feasible)
    wcpu: jnp.ndarray,
    wmem: jnp.ndarray,
    wzero: jnp.ndarray,  # bool[W] zero-request pod (count-only fit)
    has_bits: jnp.ndarray,  # bool[W] pod carries port/volume bits
    nodes: Dict,
    N: int,
    W: int,
    per_node_limit: int = 1,
) -> jnp.ndarray:
    """bool[W]: which window pods commit this wave (capacity-aware
    FIFO packing per node)."""
    pos = jnp.arange(W, dtype=jnp.int32)
    contending = choice >= 0
    # Sort by (node, window position); losers/finalized group last
    # under sentinel node N. Key fits int32: (N+1) * W < 2^31 for any
    # realistic padded shapes (5k nodes x 4k window ~ 2^25).
    key = jnp.where(contending, choice, jnp.int32(N)) * jnp.int32(W) + pos
    perm = jnp.argsort(key)
    s_choice = choice[perm]
    s_cpu = wcpu[perm]
    s_mem = wmem[perm]
    s_zero = wzero[perm]
    s_bits = has_bits[perm].astype(jnp.float32)
    s_contending = contending[perm]

    start = jnp.concatenate(
        [jnp.ones(1, bool), s_choice[1:] != s_choice[:-1]]
    )

    def seg_prefix_before(x):
        """Per-element sum of EARLIER same-segment elements."""
        cs = jnp.cumsum(x)
        seg_base = jnp.where(start, cs - x, -FMAX)
        base = jax.lax.cummax(seg_base)  # cs is nondecreasing (x >= 0)
        return cs - x - base

    cpu_before = seg_prefix_before(s_cpu)
    mem_before = seg_prefix_before(s_mem)
    rank = seg_prefix_before(jnp.ones(W, jnp.float32))
    bits_before = seg_prefix_before(s_bits)

    node = jnp.maximum(s_choice, 0)
    cap_cpu = nodes["cpu_cap"][node]
    cap_mem = nodes["mem_cap"][node]
    rem_cpu = jnp.where(cap_cpu > 0, cap_cpu - nodes["cpu_fit"][node], FMAX)
    rem_mem = jnp.where(cap_mem > 0, cap_mem - nodes["mem_fit"][node], FMAX)
    rem_count = nodes["pods_cap"][node] - nodes["pods_used"][node]

    # Zero-request pods fit by pod count alone (predicates.go:146);
    # subjecting them to the cpu/mem prefix check could wedge them
    # forever on a node whose greedy-fit sums already exceed capacity.
    resources_ok = s_zero | (
        (cpu_before + s_cpu <= rem_cpu) & (mem_before + s_mem <= rem_mem)
    )
    ok = (
        s_contending
        & resources_ok
        & (rank + 1 <= rem_count)
        # Per-node-per-wave acceptance limit: committing a whole
        # capacity prefix onto one node in a single wave tramples the
        # spreading/balance scores the losers would have reacted to.
        # Limiting acceptances keeps each wave close to one "round" of
        # the sequential cascade (measured: parity 0.05 -> ~0.9+ on
        # mixed workloads at limit=1).
        & (rank < per_node_limit)
        # Port/volume carriers: only the group's first carrier commits
        # this wave, so within-wave port/disk conflicts can't happen.
        & ((s_bits == 0) | (bits_before == 0))
    )
    # Unsort back to window order.
    accepted = jnp.zeros(W, bool).at[perm].set(ok)
    return accepted


def _commit_wave(
    nodes: Dict,
    wpods: Dict,
    choice: jnp.ndarray,
    accepted: jnp.ndarray,
    W: int,
) -> Dict:
    """Bulk commit of every accepted (pod -> node) pair."""
    j = jnp.where(accepted, choice, 0)
    f = accepted.astype(jnp.float32)
    new = dict(nodes)
    new["cpu_fit"] = nodes["cpu_fit"].at[j].add(f * wpods["cpu"], mode="drop")
    new["mem_fit"] = nodes["mem_fit"].at[j].add(f * wpods["mem"], mode="drop")
    new["cpu_used"] = nodes["cpu_used"].at[j].add(f * wpods["cpu"], mode="drop")
    new["mem_used"] = nodes["mem_used"].at[j].add(f * wpods["mem"], mode="drop")
    new["pods_used"] = nodes["pods_used"].at[j].add(f, mode="drop")
    # Bit rows: at most ONE accepted carrier per node per wave (packing
    # guarantee), so gather-OR-scatter over unique rows is exact.
    carrier = accepted & (
        jnp.any(wpods["port"] != 0, axis=1)
        | jnp.any(wpods["vol_any"] != 0, axis=1)
        | jnp.any(wpods["vol_rw"] != 0, axis=1)
    )
    cmask = carrier[:, None]
    N = nodes["cpu_cap"].shape[0]
    # Non-carriers scatter OUT OF BOUNDS (dropped): routing them to a
    # shared dummy row would create duplicate-index scatters whose
    # no-op lanes can clobber a real carrier's update to that row.
    crow = jnp.where(carrier, choice, N)
    grow = jnp.minimum(crow, N - 1)  # clamped gather (values unused)
    for field, pkey in (
        ("uport", "port"),
        ("uvol_any", "vol_any"),
        ("uvol_rw", "vol_rw"),
    ):
        add_bits = jnp.where(cmask, wpods[pkey], 0)
        gathered = new[field][grow] | add_bits
        new[field] = new[field].at[crow].set(gathered, mode="drop")
    # Service membership counts (duplicates accumulate correctly).
    ids = wpods["svc_ids"]  # i32[W, K]
    valid = (ids >= 0) & accepted[:, None]
    rows = jnp.where(accepted, choice, 0)[:, None].repeat(ids.shape[1], axis=1)
    new["svc_counts"] = nodes["svc_counts"].at[
        rows, jnp.maximum(ids, 0)
    ].add(valid.astype(jnp.float32), mode="drop")
    return new


def _tie_hash(idx: jnp.ndarray, N: int) -> jnp.ndarray:
    """u16 pod x node hash for randomized tie-breaks (the reference
    also randomizes: generic_scheduler.go:90-102 picks
    random.Int() % len(ties)). The scan uses lowest-index for oracle
    parity; a wave MUST scatter ties or every pod in the window piles
    onto the same few low-index nodes and per-wave throughput
    collapses (measured: 14 pods/wave with lowest-index, ~window with
    hashed ties on a 5k-node cluster)."""
    return (
        (idx[:, None].astype(jnp.uint32) * jnp.uint32(2654435761))
        ^ (jnp.arange(N, dtype=jnp.uint32)[None, :] * jnp.uint32(40503))
    ) & jnp.uint32(0xFFFF)


def _argmax_choose(masked, idx, valid, carry, N):
    """Plain wave choice: per-pod argmax with hashed tie-break packed
    into the low bits (scores are small ints, so << 16 is lossless).
    The zero telemetry scalars satisfy the shared choose contract
    (Sinkhorn's priced choice reports real ones)."""
    h = _tie_hash(idx, N)
    combined = (masked << 16) | h.astype(jnp.int32)
    choice = jnp.argmax(combined, axis=1).astype(jnp.int32)
    return choice, jnp.int32(0), jnp.float32(0.0)


def run_windowed(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    weights: Tuple[int, int, int],
    window: int,
    per_node_limit: int,
    choose,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], jnp.ndarray, jnp.ndarray,
           jnp.ndarray]:
    """The shared windowed-commit loop (trace-time function — callers
    jit it). Returns (assignment, post-commit occupancy carry, wave
    count, total choose iterations, last wave's residual).
    `choose(masked, idx, valid, carry, N) -> (i32[W], i32, f32)` picks
    each window pod's candidate node and reports its convergence
    telemetry (iterations executed, residual — zeros for the plain
    argmax); everything else — windowing, capacity-aware packing, bulk
    commit, finalization — is common to every wave-family solver
    (plain argmax, Sinkhorn-priced, ...), so invariants live exactly
    once. Every wave finalizes at least one pod, so the loop
    terminates."""
    P = pods["cpu"].shape[0]
    N = nodes["cpu_cap"].shape[0]
    W = min(window, P)
    assignment0 = jnp.full(P, UNDECIDED, jnp.int32)
    # Padding pods (pinned == -2) can never place: finalize them now so
    # the loop condition sees only real pods.
    assignment0 = jnp.where(pods["pinned"] == -2, -1, assignment0)

    def cond(state):
        assignment, _, waves, _, _ = state
        return jnp.any(assignment == UNDECIDED) & (waves < P)

    def body(state):
        assignment, carry, waves, titers, _ = state
        undecided = assignment == UNDECIDED
        idx = jnp.nonzero(undecided, size=W, fill_value=P)[0].astype(jnp.int32)
        valid = idx < P
        wpods = _window_rows(pods, idx)
        feas, score = _batched_eval(wpods, carry, weights, N)
        masked = jnp.where(feas, score, -1)
        best, c_iters, c_residual = choose(masked, idx, valid, carry, N)
        feasible = jnp.take_along_axis(masked, best[:, None], axis=1)[:, 0] >= 0
        choice = jnp.where(valid & feasible, best, -1)

        has_bits = (
            jnp.any(wpods["port"] != 0, axis=1)
            | jnp.any(wpods["vol_any"] != 0, axis=1)
            | jnp.any(wpods["vol_rw"] != 0, axis=1)
        )
        accepted = _pack_window(
            choice,
            wpods["cpu"],
            wpods["mem"],
            wpods["zero_req"],
            has_bits,
            carry,
            N,
            W,
            per_node_limit,
        )
        carry = _commit_wave(carry, wpods, choice, accepted, W)
        # One combined scatter: accepted pods get their node; pods with
        # no feasible node finalize -1 (occupancy only grows, so
        # infeasible-now is infeasible-forever); conflict losers stay
        # UNDECIDED and retry next wave.
        newly_unschedulable = valid & ~feasible
        # Both branches dtype-pinned: bare int literals here are WEAK-
        # typed and materialize a weak i32[W] (ktshape weak-type check)
        # whose dtype would float with downstream promotion.
        value = jnp.where(
            accepted,
            choice,
            jnp.where(
                newly_unschedulable, jnp.int32(-1), jnp.int32(UNDECIDED)
            ),
        )
        assignment = assignment.at[idx].set(value, mode="drop")
        return assignment, carry, waves + 1, titers + c_iters, c_residual

    assignment, carry, waves, titers, residual = jax.lax.while_loop(
        cond, body,
        (assignment0, dict(nodes), jnp.int32(0), jnp.int32(0),
         jnp.float32(0.0)),
    )
    # Safety valve: the wave cap (P) cannot be hit given the
    # first-undecided-pod-always-finalizes invariant, but an UNDECIDED
    # sentinel must never leak to callers.
    assignment = jnp.where(assignment == UNDECIDED, -1, assignment)
    return assignment, carry, waves, titers, residual


@traced_jit(static_argnames=("weights", "window", "per_node_limit"))
def solve_waves(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
    window: int = 4096,
    per_node_limit: int = 1,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(assignment i32[P] with -1 = unschedulable, wave count)."""
    assignment, _, waves, _, _ = run_windowed(
        pods, nodes, weights, window, per_node_limit, _argmax_choose
    )
    return assignment, waves


@traced_jit(
    static_argnames=("weights", "window", "per_node_limit"),
    donate_argnames=("nodes",),
)
def solve_waves_with_state(
    pods: Dict[str, jnp.ndarray],
    nodes: Dict[str, jnp.ndarray],
    weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
    window: int = 4096,
    per_node_limit: int = 1,
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray], jnp.ndarray]:
    """Like solve_waves, but also returns the post-commit occupancy
    carry; `nodes` is DONATED — the incremental-churn substrate, same
    contract as solver.solve_with_state."""
    assignment, carry, waves, _, _ = run_windowed(
        pods, nodes, weights, window, per_node_limit, _argmax_choose
    )
    return assignment, carry, waves
