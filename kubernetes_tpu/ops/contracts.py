"""Kernel shape/dtype/sharding contracts for every registered kernel.

The kernel layer's correctness rests on three invariants that used to
be enforced only dynamically and partially:

- **bucketed shapes** — every staged axis comes off a known lattice
  (pow2 buckets, word/service multiples), so a drifting cluster never
  triggers an XLA recompile storm (PR 7's recompilation sentinel
  watches this at runtime; the contract states it);
- **stable dtypes** — kernel results carry the exact dtypes the NumPy
  oracle twins (ops/parity.py ORACLE_TWINS) produce, with no weak-type
  or accidental f64 promotion (bit-parity with the oracles depends on
  it);
- **pod-axis coupling** — whether a kernel is independent along the
  pod axis (``shardable``: the precondition for sharding the pod axis
  over a Mesh, ROADMAP item #2), intentionally couples pods
  (``reduces``: scans/segment reductions), or never touches the pod
  axis at all (``replicated``);
- **mesh sharding + communication budget** — HOW the kernel partitions
  over a 1-D Mesh (which symbolic dim is sharded over which axis — a
  symbolic PartitionSpec per array leaf, see :func:`partition_specs`)
  and the exact collective inventory XLA's SPMD partitioner inserts
  for it (:class:`CommBudget`, pinned at the distinct-dims probe
  point). ``tools/ktlint/ktmesh.py`` VERIFIES the budget by
  partitioned-lowering under a forced multi-device CPU mesh (compile,
  never execute); the ledger joins runtime compiles against it via
  :func:`comm_verdict`.

This module DECLARES those invariants, one :class:`Contract` per
ORACLE_TWINS key; ``tools/ktlint/ktshape.py`` VERIFIES them without
executing anything (``jax.eval_shape`` + a jaxpr walk over
``ShapeDtypeStruct`` probes). The checker enforces completeness both
ways: a kernel without a contract, or a contract without a kernel, is
a finding.

It is also the single home of the **staged-shape signature**: the
compact ``f32[128],i32[128,8],...`` string the PR-13 compile ledger
keys its per-shape rows by. :func:`shape_signature` is THE
implementation (ops/ledger.py delegates here), and
:func:`contract_verdict` joins observed ledger signatures back against
the declared contracts — a drifted staged shape shows up as a CONTRACT
mismatch in ``GET /debug/kernels`` / ``ktctl profile kernels``.

No module-level jax import (ops/ledger.py rides this module at import
time and keeps the "a CPU-only control plane never loads jax" rule).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.models.columnar import SVC_K
from kubernetes_tpu.ops.parity import ORACLE_TWINS

__all__ = [
    "ArraySpec",
    "CommBudget",
    "Contract",
    "CONTRACTS",
    "DIM_LATTICES",
    "MeshSharding",
    "Static",
    "DimRef",
    "POD_AXIS_KINDS",
    "abstract_args",
    "collective_inventory",
    "comm_verdict",
    "contract_verdict",
    "declared_array_leaves",
    "leaf_signature",
    "match_signature",
    "partition_specs",
    "resolve_kernel",
    "shape_signature",
    "sharded_abstract_args",
]


# -- staged-shape signatures (canonical; the ledger delegates here) ----


def leaf_signature(leaf) -> str:
    """One pytree leaf's signature token: ``f32[128,8]`` for arrays
    (numpy dtype kind + bit width + shape), a truncated repr for
    non-array leaves (static scalars, spec namedtuple fields)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        r = repr(leaf)
        return r if len(r) <= 32 else r[:29] + "..."
    import numpy as np

    d = np.dtype(dtype)
    return f"{d.kind}{d.itemsize * 8}[{','.join(str(s) for s in shape)}]"


def shape_signature(args, kwargs=None) -> str:
    """Compact staged-shape signature of one kernel call — the ledger's
    per-bucket row key AND the string :func:`contract_verdict` checks
    against the declared contract. One implementation; the two surfaces
    can never drift."""
    import jax

    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    return ",".join(leaf_signature(leaf) for leaf in leaves)


#: Array tokens inside a signature: dtype kind letter + bits + [dims].
#: Non-array tokens (static reprs) never match — shapes are the only
#: bracketed digit lists a signature contains.
_ARRAY_TOKEN_RE = re.compile(r"\b([a-zA-Z])(\d+)\[([\d,]*)\]")


def parse_signature(signature: str) -> List[Tuple[str, Tuple[int, ...]]]:
    """[(dtype token like 'f32', shape tuple)] for every ARRAY leaf in
    a signature, in call order; static/non-array leaves are skipped."""
    out = []
    for m in _ARRAY_TOKEN_RE.finditer(signature):
        kind, bits, dims = m.group(1), m.group(2), m.group(3)
        shape = tuple(int(d) for d in dims.split(",")) if dims else ()
        out.append((f"{kind}{bits}", shape))
    return out


# -- the dim lattice ----------------------------------------------------


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


#: Symbolic dims and their bucket lattices. A concrete staged size off
#: its symbol's lattice means the staging layer's bucketing leaked — a
#: fresh XLA executable per cluster-size drift (the recompile storm the
#: pow2 helpers exist to prevent).
DIM_LATTICES: Dict[str, Tuple[str, object]] = {
    # Solver-family pod axis (matrices._pod_axis_bucket): pow2 >= 128
    # up to 8192, then 1024-multiples.
    "P": (
        "pod axis: pow2 >= 128, then 1024-multiples past 8192",
        lambda n: (_is_pow2(n) and n >= 128) or (n > 8192 and n % 1024 == 0),
    ),
    # Gang acceptance pod axis (pipeline.gang_member_counts_device).
    "PG": ("gang pod axis: pow2 >= 8", lambda n: _is_pow2(n) and n >= 8),
    "G": ("gang group axis: pow2 >= 8", lambda n: _is_pow2(n) and n >= 8),
    # Node axis: multiples of 128 (device_nodes pads to pad_to/mesh
    # multiples; sessions use pow2 >= 128, a subset).
    "N": ("node axis: multiple of 128", lambda n: n >= 128 and n % 128 == 0),
    # Bitset word axes (matrices.WORD_BUCKET): label/selector words,
    # hostPort words, volume words bucket independently.
    "LW": ("label/selector words: multiple of 2", lambda n: n >= 2 and n % 2 == 0),
    "PW": ("hostPort words: multiple of 2", lambda n: n >= 2 and n % 2 == 0),
    "VW": ("volume words: multiple of 2", lambda n: n >= 2 and n % 2 == 0),
    # Service axis: SVC_BUCKET multiples on the snapshot path; the
    # incremental session freezes the raw service count at build time
    # (fixed per session, so no recompile churn) — any size >= 1.
    "S": ("service axis: session-frozen, >= 1", lambda n: n >= 1),
    "K": (f"service top-K: exactly {SVC_K}", lambda n: n == SVC_K),
    # Preemption staging (preemption.candidate_prefixes_device).
    "V": ("victim axis: pow2 >= 8", lambda n: _is_pow2(n) and n >= 8),
    "M": ("preemption node axis: pow2 >= 8", lambda n: _is_pow2(n) and n >= 8),
    # Dirty-row scatter width (SolverSession._flush_dirty).
    "R": ("scatter width: pow2 >= 8", lambda n: _is_pow2(n) and n >= 8),
    # Capacity probe-shape axis (utils/capacity.py pads the probe set —
    # backlog quantiles + configured slice shapes — to pow2 buckets).
    "Q": ("probe-shape axis: pow2 >= 4", lambda n: _is_pow2(n) and n >= 4),
    # Rebalance movable-pod axis (utils/rebalance.py pads the sorted
    # movable worklist to pow2 buckets).
    "D": ("rebalance pod axis: pow2 >= 8", lambda n: _is_pow2(n) and n >= 8),
    # Policy-lowering minor axes: sized by the configured policy
    # (affinity label count, anti-affinity zone vocab) — static per
    # lowered spec, not bucketed.
    "A": ("policy affinity axis: >= 1", lambda n: n >= 1),
    "Z": ("policy zone axis: >= 1", lambda n: n >= 1),
    "S1": ("service axis + scratch slot: >= 2", lambda n: n >= 2),
}


def dim_ok(symbol: str, size: int) -> bool:
    entry = DIM_LATTICES.get(symbol)
    return bool(entry and entry[1](size))


# -- contract schema ----------------------------------------------------


@dataclass(frozen=True)
class ArraySpec:
    """One array leaf: symbolic dims + canonical dtype token
    (``f32``/``i32``/``u32``/``b8`` — numpy kind + bits, matching
    :func:`leaf_signature`). ``optional`` marks policy-lowering leaves
    that only exist when a policy spec adds them."""

    dims: Tuple[str, ...]
    dtype: str
    optional: bool = False


@dataclass(frozen=True)
class Static:
    """A static (non-array) argument: ``value`` is the sample the
    checker passes at trace time; a callable is resolved lazily (specs
    that would pull jax-adjacent imports at module load)."""

    value: object = None


@dataclass(frozen=True)
class DimRef:
    """A static argument whose sample value is a bound dim (e.g.
    ``num_groups=DimRef('G')``)."""

    symbol: str


@dataclass(frozen=True)
class CommBudget:
    """The exact collective set one kernel's partitioned lowering may
    emit under its declared :class:`MeshSharding`, pinned at the
    distinct-dims probe point (jax 0.4.x GSPMD on the forced 8-device
    host platform). ktmesh compares the compiled module's inventory
    against this EXACTLY — a phantom collective (sharding regression)
    and a vanished one (stale budget) are both findings."""

    all_gather: int = 0
    all_reduce: int = 0
    reduce_scatter: int = 0
    collective_permute: int = 0
    all_to_all: int = 0

    def as_dict(self) -> Dict[str, int]:
        """Sparse {HLO op name: count} — keys match the hyphenated
        names :func:`collective_inventory` counts, zero entries
        dropped so declared == observed is a plain dict compare."""
        pairs = (
            ("all-gather", self.all_gather),
            ("all-reduce", self.all_reduce),
            ("reduce-scatter", self.reduce_scatter),
            ("collective-permute", self.collective_permute),
            ("all-to-all", self.all_to_all),
        )
        return {k: v for k, v in pairs if v}

    def total(self) -> int:
        return sum(self.as_dict().values())


@dataclass(frozen=True)
class MeshSharding:
    """How one kernel partitions over a 1-D Mesh: ``dim`` is the
    symbolic dim sharded across mesh axis ``axis`` (None: every leaf
    replicated — the kernel runs identically on every device).
    The per-leaf PartitionSpec is DERIVED (:func:`partition_specs`):
    an array leaf shards exactly its ``dim`` dims, everything else
    replicates — the same layout ``matrices.shardings_for`` produces
    at runtime, so the static budget and the production staging agree
    by construction. ``lower_overrides`` pins contract kwargs for the
    mesh lowering only (e.g. the pallas kernel needs interpret=True to
    compile on the host platform)."""

    dim: Optional[str]
    axis: str  # "pods" | "nodes"
    budget: CommBudget = CommBudget()
    lower_overrides: Tuple[Tuple[str, object], ...] = ()
    notes: str = ""


@dataclass(frozen=True)
class Contract:
    """One kernel's declared interface. ``args`` are (name, spec-tree)
    in call order — spec-tree is an ArraySpec, a dict of ArraySpecs
    (sorted-key flattening, like jax), a Static, or a DimRef.
    ``results`` is the declared result pytree (tuples/dicts of
    ArraySpecs). ``pod_dim`` names which symbol is the pod axis (None:
    the kernel never sees pods); ``pod_axis`` declares its coupling
    class. ``samples`` are the bucket-lattice points the checker
    abstract-evaluates at. ``sharding`` declares the mesh partitioning
    + communication budget (ktmesh's subject; every registered kernel
    must carry one)."""

    kernel: str
    args: Tuple[Tuple[str, object], ...]
    results: object
    pod_dim: Optional[str]
    pod_axis: str  # "shardable" | "reduces" | "replicated"
    samples: Tuple[Dict[str, int], ...]
    kwargs: Tuple[Tuple[str, object], ...] = ()
    notes: str = ""
    sharding: Optional[MeshSharding] = None


POD_AXIS_KINDS = ("shardable", "reduces", "replicated")


def _f32(*dims, optional=False):
    return ArraySpec(tuple(dims), "f32", optional)


def _i32(*dims, optional=False):
    return ArraySpec(tuple(dims), "i32", optional)


def _u32(*dims, optional=False):
    return ArraySpec(tuple(dims), "u32", optional)


def _b8(*dims, optional=False):
    return ArraySpec(tuple(dims), "b8", optional)


#: The pod-column schema every solver-family kernel consumes
#: (matrices.device_pods). aff_pin rides only when ServiceAffinity is
#: lowered.
POD_SCHEMA: Dict[str, ArraySpec] = {
    "cpu": _f32("P"),
    "mem": _f32("P"),
    "zero_req": _b8("P"),
    "sel": _u32("P", "LW"),
    "port": _u32("P", "PW"),
    "vol_any": _u32("P", "VW"),
    "vol_rw": _u32("P", "VW"),
    "pinned": _i32("P"),
    "svc": _i32("P"),
    "svc_ids": _i32("P", "K"),
    "aff_pin": _i32("P", "A", optional=True),
}

#: The node-column schema (matrices.device_nodes / SolverSession
#: _empty_node_columns). Policy columns + service-affinity carries are
#: optional.
NODE_SCHEMA: Dict[str, ArraySpec] = {
    "cpu_cap": _f32("N"),
    "mem_cap": _f32("N"),
    "pods_cap": _f32("N"),
    "cpu_fit": _f32("N"),
    "mem_fit": _f32("N"),
    "over": _b8("N"),
    "cpu_used": _f32("N"),
    "mem_used": _f32("N"),
    "pods_used": _f32("N"),
    "labels": _u32("N", "LW"),
    "uport": _u32("N", "PW"),
    "uvol_any": _u32("N", "VW"),
    "uvol_rw": _u32("N", "VW"),
    "svc_counts": _f32("N", "S"),
    "sched": _b8("N"),
    "policy_ok": _b8("N", optional=True),
    "static_prio": _i32("N", optional=True),
    "aff_vid": _i32("N", "A", optional=True),
    "aa_zone": _i32("N", "Z", optional=True),
    "anchor": _i32("S1", optional=True),
    "svc_total": _f32("S1", optional=True),
}

#: The dirty-row scatter's row schema: NODE_SCHEMA's non-optional
#: leaves with the node axis narrowed to the scatter width.
ROW_SCHEMA: Dict[str, ArraySpec] = {
    k: ArraySpec(("R",) + v.dims[1:], v.dtype)
    for k, v in NODE_SCHEMA.items()
    if not v.optional
}


def _default_lowered():
    from kubernetes_tpu.models.algspec import DEFAULT_LOWERED

    return DEFAULT_LOWERED


_SOLVE_SAMPLES = (
    {"P": 128, "N": 128, "LW": 2, "PW": 2, "VW": 2, "K": SVC_K, "S": 128},
    {"P": 512, "N": 256, "LW": 4, "PW": 2, "VW": 2, "K": SVC_K, "S": 128},
)

_WAVE_TELEMETRY = (_i32(), _i32(), _f32())  # waves, iters, residual


#: The contract registry. Keys are ORACLE_TWINS keys — the checker
#: enforces completeness both ways, so a kernel lands with its oracle
#: twin AND its contract or it does not land.
CONTRACTS: Dict[str, Contract] = {
    "solver._solve_xla": Contract(
        kernel="solver._solve_xla",
        args=(
            ("pods", POD_SCHEMA),
            ("nodes", NODE_SCHEMA),
            ("weights", Static((1, 1, 1))),
            ("lspec", Static(_default_lowered)),
        ),
        results=_i32("P"),
        pod_dim="P",
        pod_axis="reduces",
        samples=_SOLVE_SAMPLES,
        notes="sequential scan over the pod axis — the parity path",
        sharding=MeshSharding(
            dim="N", axis="nodes",
            budget=CommBudget(all_gather=24, all_reduce=8),
            notes=(
                "the MULTICHIP layout: node columns sharded, pod "
                "columns replicated (the scan couples pods "
                "sequentially, so the pod axis cannot shard); the "
                "per-step argmax runs as a cross-shard reduce + "
                "node-axis gathers"
            ),
        ),
    ),
    "solver._solve_with_state_xla": Contract(
        kernel="solver._solve_with_state_xla",
        args=(
            ("pods", POD_SCHEMA),
            ("nodes", NODE_SCHEMA),
            ("weights", Static((1, 1, 1))),
            ("lspec", Static(_default_lowered)),
        ),
        results=(_i32("P"), NODE_SCHEMA),
        pod_dim="P",
        pod_axis="reduces",
        samples=_SOLVE_SAMPLES,
        notes="scan + donated occupancy carry",
        sharding=MeshSharding(
            dim="N", axis="nodes",
            budget=CommBudget(all_gather=24, all_reduce=8),
        ),
    ),
    "solver.explain_rows": Contract(
        kernel="solver.explain_rows",
        args=(("pods", POD_SCHEMA), ("nodes", NODE_SCHEMA)),
        results=(
            ArraySpec(("P", "N"), "u32"),
            ArraySpec(("P", "N"), "i32"),
            ArraySpec(("P", "N"), "i32"),
            ArraySpec(("P", "N"), "i32"),
        ),
        pod_dim="P",
        pod_axis="shardable",
        samples=_SOLVE_SAMPLES,
        notes=(
            "vmapped per-pod verdicts against FIXED occupancy — every "
            "pod independent; the proven go-case for the pod-axis Mesh"
        ),
        sharding=MeshSharding(
            dim="P", axis="pods",
            budget=CommBudget(),
            notes=(
                "THE go-case: pod columns sharded over the pod axis, "
                "node columns replicated — must lower with ZERO "
                "collectives (any collective here means the "
                "embarrassingly-parallel claim broke)"
            ),
        ),
    ),
    "wave.solve_waves": Contract(
        kernel="wave.solve_waves",
        args=(("pods", POD_SCHEMA), ("nodes", NODE_SCHEMA)),
        results=(_i32("P"), _i32()),
        pod_dim="P",
        pod_axis="reduces",
        samples=_SOLVE_SAMPLES,
        notes="windowed commit loop: waves gather/scatter the pod axis",
        sharding=MeshSharding(
            dim="N", axis="nodes",
            budget=CommBudget(all_gather=2, all_reduce=11),
            notes=(
                "per-wave feasibility scored on node shards, wave "
                "commits psum'd — a dozen windowed rounds instead of "
                "the scan's P per-pod rounds (why auto resolves to "
                "wave on a mesh)"
            ),
        ),
    ),
    "wave.solve_waves_with_state": Contract(
        kernel="wave.solve_waves_with_state",
        args=(("pods", POD_SCHEMA), ("nodes", NODE_SCHEMA)),
        results=(_i32("P"), NODE_SCHEMA, _i32()),
        pod_dim="P",
        pod_axis="reduces",
        samples=_SOLVE_SAMPLES,
        sharding=MeshSharding(
            dim="N", axis="nodes",
            budget=CommBudget(all_gather=2, all_reduce=11),
        ),
    ),
    "sinkhorn.solve_sinkhorn_stats": Contract(
        kernel="sinkhorn.solve_sinkhorn_stats",
        args=(("pods", POD_SCHEMA), ("nodes", NODE_SCHEMA)),
        results=(_i32("P"),) + _WAVE_TELEMETRY,
        pod_dim="P",
        pod_axis="reduces",
        samples=_SOLVE_SAMPLES,
        notes="Sinkhorn-priced windowed loop + convergence telemetry",
        sharding=MeshSharding(
            dim="N", axis="nodes",
            budget=CommBudget(all_gather=2, all_reduce=15),
            notes=(
                "wave's inventory + the Sinkhorn price iteration's "
                "extra node-shard psums (row/col marginals)"
            ),
        ),
    ),
    "sinkhorn.solve_sinkhorn_with_state": Contract(
        kernel="sinkhorn.solve_sinkhorn_with_state",
        args=(("pods", POD_SCHEMA), ("nodes", NODE_SCHEMA)),
        results=(_i32("P"), NODE_SCHEMA) + _WAVE_TELEMETRY,
        pod_dim="P",
        pod_axis="reduces",
        samples=_SOLVE_SAMPLES,
        sharding=MeshSharding(
            dim="N", axis="nodes",
            budget=CommBudget(all_gather=2, all_reduce=15),
        ),
    ),
    "pallas_scan._solve_packed": Contract(
        kernel="pallas_scan._solve_packed",
        args=(
            ("pods", POD_SCHEMA),
            ("nodes", NODE_SCHEMA),
            ("weights", Static((1, 1, 1))),
        ),
        results=(_i32("P"), NODE_SCHEMA),
        pod_dim="P",
        pod_axis="reduces",
        samples=_SOLVE_SAMPLES,
        kwargs=(("interpret", Static(False)),),
        notes="whole sequential solve as one pallas_call (VMEM carry)",
        sharding=MeshSharding(
            dim=None, axis="nodes",
            budget=CommBudget(),
            lower_overrides=(("interpret", True),),
            notes=(
                "single-device only by design (the VMEM carry cannot "
                "shard): fully replicated, zero collectives; Mosaic "
                "cannot lower on the host platform, so the mesh probe "
                "compiles the interpreter path"
            ),
        ),
    ),
    "matrices.gang_member_counts": Contract(
        kernel="matrices.gang_member_counts",
        args=(("placed", _b8("PG")), ("group_ids", _i32("PG"))),
        results=_i32("G"),
        pod_dim="PG",
        pod_axis="reduces",
        samples=(
            {"PG": 8, "G": 8},
            {"PG": 256, "G": 16},
        ),
        kwargs=(("num_groups", DimRef("G")),),
        notes="masked segment_sum over the pod axis — gang acceptance",
        sharding=MeshSharding(
            dim="PG", axis="pods",
            budget=CommBudget(all_reduce=1),
            notes=(
                "the canonical reduces-kernel budget: pod rows "
                "sharded, per-shard segment_sum, ONE psum over the "
                "pod axis — and nothing more"
            ),
        ),
    ),
    "incremental._scatter_rows": Contract(
        kernel="incremental._scatter_rows",
        args=(
            ("nodes", {k: v for k, v in NODE_SCHEMA.items() if not v.optional}),
            ("idx", _i32("R")),
            ("rows", ROW_SCHEMA),
        ),
        results={k: v for k, v in NODE_SCHEMA.items() if not v.optional},
        pod_dim=None,
        pod_axis="replicated",
        samples=(
            {"N": 128, "LW": 2, "PW": 2, "VW": 2, "S": 1, "R": 8},
            {"N": 256, "LW": 2, "PW": 2, "VW": 4, "S": 16, "R": 64},
        ),
        notes="node-row patch; never sees the pod axis",
        sharding=MeshSharding(
            dim=None, axis="nodes",
            budget=CommBudget(),
            notes=(
                "dirty-row scatter stays replicated: sharding the "
                "node axis would turn every row patch into a "
                "collective-permute round on the micro-tick path"
            ),
        ),
    ),
    "preemption._victim_prefix_kernel.kernel": Contract(
        kernel="preemption._victim_prefix_kernel.kernel",
        args=(
            ("v_cpu", _f32("V")),
            ("v_mem", _f32("V")),
            ("v_prio", _i32("V")),
            ("v_node", _i32("V")),
            ("v_alive", _b8("V")),
            ("free_cpu", _f32("M")),
            ("free_mem", _f32("M")),
            ("free_pods", _f32("M")),
            ("node_ok", _b8("M")),
            ("p_cpu", _f32()),
            ("p_mem", _f32()),
            ("p_prio", _i32()),
        ),
        results=(_i32("M"), _i32("M"), _i32("V"), _i32("V")),
        pod_dim="V",
        pod_axis="reduces",
        samples=(
            {"V": 8, "M": 8},
            {"V": 64, "M": 32},
        ),
        kwargs=(("num_nodes", DimRef("M")),),
        notes=(
            "victim rows ARE pods: the lexsort + per-node prefix "
            "cumsums couple them by construction"
        ),
        sharding=MeshSharding(
            dim=None, axis="nodes",
            budget=CommBudget(),
            notes=(
                "replicated: victim sets are small (pow2 >= 8, not "
                "the 500k pod axis) and the lexsort would serialize "
                "across shards anyway"
            ),
        ),
    ),
    "capacity.capacity_report": Contract(
        kernel="capacity.capacity_report",
        args=(
            ("cpu_cap", _f32("N")),
            ("mem_cap", _f32("N")),
            ("pods_cap", _f32("N")),
            ("cpu_fit", _f32("N")),
            ("mem_fit", _f32("N")),
            ("pods_used", _f32("N")),
            ("over", _b8("N")),
            ("sched", _b8("N")),
            ("probe_cpu", _f32("Q")),
            ("probe_mem", _f32("Q")),
            ("probe_min", _i32("Q")),
            ("probe_live", _b8("Q")),
        ),
        results=(
            _f32("N"),  # util_cpu
            _f32("N"),  # util_mem
            _f32("N"),  # util_pods
            ArraySpec(("Q", "N"), "i32"),  # fit_int
            _i32("Q"),  # headroom
            _f32("Q"),  # frag
            _b8("Q"),  # slice_ok
            _b8("N"),  # stranded
            _f32(),  # frag_score
            _f32(),  # stranded_cpu
            _f32(),  # stranded_mem
        ),
        pod_dim="Q",
        pod_axis="reduces",
        samples=(
            {"Q": 4, "N": 128},
            {"Q": 8, "N": 256},
        ),
        notes=(
            "probes ARE canonical pod shapes: headroom/fragmentation "
            "totals reduce over the probe axis (and stranded-node "
            "detection any()s across it)"
        ),
        sharding=MeshSharding(
            dim="N", axis="nodes",
            budget=CommBudget(all_reduce=6),
            notes=(
                "node columns sharded (the probe axis is tiny), "
                "per-probe headroom counts and the frag/stranded "
                "totals psum across node shards"
            ),
        ),
    ),
    "rebalance.plan_moves": Contract(
        kernel="rebalance.plan_moves",
        args=(
            ("cpu_cap", _f32("N")),
            ("mem_cap", _f32("N")),
            ("pods_cap", _f32("N")),
            ("cpu_fit", _f32("N")),
            ("mem_fit", _f32("N")),
            ("pods_used", _f32("N")),
            ("over", _b8("N")),
            ("sched", _b8("N")),
            ("pod_cpu", _f32("D")),
            ("pod_mem", _f32("D")),
            ("pod_node", _i32("D")),
            ("pod_live", _b8("D")),
            ("pod_force", _b8("D")),
            ("probe_cpu", _f32("Q")),
            ("probe_mem", _f32("Q")),
            ("probe_min", _i32("Q")),
            ("probe_live", _b8("Q")),
            ("move_budget", _i32()),
        ),
        results=(
            _i32("D"),  # dest
            _b8("D"),  # moved
            _i32("D"),  # gain
            _i32(),  # n_moves
            _f32(),  # score_before
            _f32(),  # score_after
        ),
        pod_dim="D",
        pod_axis="reduces",
        samples=(
            {"D": 8, "N": 128, "Q": 4},
            {"D": 64, "N": 256, "Q": 8},
        ),
        notes=(
            "best-fit-decreasing scan over the movable-pod axis with "
            "an evolving occupancy carry — later moves see earlier "
            "ones by construction"
        ),
        sharding=MeshSharding(
            dim="N", axis="nodes",
            budget=CommBudget(all_gather=12, all_reduce=5),
            notes=(
                "node occupancy sharded; each best-fit step gathers "
                "the per-shard scores and psums the move verdicts "
                "(the movable-pod scan itself is sequential)"
            ),
        ),
    ),
}


# -- contract -> abstract inputs ----------------------------------------


def _distinct_bindings(contract: Contract) -> Dict[str, int]:
    """A binding where every bound dim size is unique — the jaxpr
    walk's pod-axis tracking identifies the pod axis by its size, so
    probe sizes must not collide. Sizes still satisfy every kernel's
    trace-time requirements (e.g. the pallas pod axis is a multiple of
    128), though not necessarily the bucket lattice — tracing does not
    care, and lattice conformance is checked separately."""
    symbols = _contract_symbols(contract)
    pool = {
        "P": 384, "PG": 24, "G": 48, "N": 256, "LW": 2, "PW": 4, "VW": 6,
        "S": 640, "K": SVC_K, "V": 40, "M": 16, "R": 12,
        "A": 3, "Z": 5, "S1": 641, "Q": 32, "D": 64,
    }
    return {s: pool[s] for s in symbols if s in pool}


def _contract_symbols(contract: Contract) -> List[str]:
    syms: List[str] = []

    def scan(spec):
        if isinstance(spec, ArraySpec):
            if not spec.optional:
                for d in spec.dims:
                    if d not in syms:
                        syms.append(d)
        elif isinstance(spec, dict):
            for k in sorted(spec):
                scan(spec[k])
        elif isinstance(spec, DimRef):
            if spec.symbol not in syms:
                syms.append(spec.symbol)

    for _, spec in contract.args + contract.kwargs:
        scan(spec)
    scan(contract.results) if isinstance(contract.results, (ArraySpec, dict)) \
        else [scan(s) for s in contract.results]
    return syms


_DTYPE_OF = {
    "f32": "float32", "f64": "float64",
    "i32": "int32", "i64": "int64", "i16": "int16",
    "u32": "uint32", "b8": "bool_",
}


def _np_dtype(token: str):
    import numpy as np

    name = _DTYPE_OF.get(token)
    if name is None:
        raise ValueError(f"unknown dtype token {token!r}")
    return getattr(np, name)


def _materialize(spec, bindings: Dict[str, int], leaf_sharding=None):
    """spec-tree -> ShapeDtypeStruct pytree (statics resolve to their
    sample values). ``leaf_sharding(ArraySpec) -> jax sharding`` tags
    each array aval for partitioned lowering (the ktmesh probe)."""
    import jax

    if isinstance(spec, ArraySpec):
        if spec.optional:
            return None  # optional leaves are omitted from probes
        shape = tuple(bindings[d] for d in spec.dims)
        if leaf_sharding is not None:
            return jax.ShapeDtypeStruct(
                shape, _np_dtype(spec.dtype), sharding=leaf_sharding(spec)
            )
        return jax.ShapeDtypeStruct(shape, _np_dtype(spec.dtype))
    if isinstance(spec, dict):
        out = {}
        for k in sorted(spec):
            v = _materialize(spec[k], bindings, leaf_sharding)
            if v is not None:
                out[k] = v
        return out
    if isinstance(spec, DimRef):
        return bindings[spec.symbol]
    if isinstance(spec, Static):
        return spec.value() if callable(spec.value) else spec.value
    raise ValueError(f"unknown spec node {spec!r}")


def abstract_args(
    contract: Contract, bindings: Dict[str, int]
) -> Tuple[tuple, dict]:
    """(args, kwargs) of ShapeDtypeStructs + statics for one lattice
    point — what the checker feeds eval_shape / trace."""
    args = tuple(
        _materialize(spec, bindings) for _, spec in contract.args
    )
    kwargs = {
        name: _materialize(spec, bindings)
        for name, spec in contract.kwargs
    }
    return args, kwargs


def expected_results(contract: Contract, bindings: Dict[str, int]):
    """The declared result pytree materialized at one lattice point."""

    def mat(spec):
        if isinstance(spec, ArraySpec):
            return _materialize(spec, bindings)
        if isinstance(spec, dict):
            out = {}
            for k in sorted(spec):
                v = mat(spec[k])
                if v is not None:
                    out[k] = v
            return out
        return tuple(mat(s) for s in spec)

    return mat(contract.results)


# -- mesh shardings + collective inventories (ktmesh's substrate) ------


def partition_spec(
    leaf: ArraySpec, sharding: MeshSharding
) -> Tuple[Optional[str], ...]:
    """One array leaf's symbolic PartitionSpec under the contract's
    sharding: the sharded dim carries the mesh axis name, everything
    else replicates. ``('nodes', None)`` for an (N, S) leaf sharded
    over dim 'N' on axis 'nodes'."""
    return tuple(
        sharding.axis if d == sharding.dim else None for d in leaf.dims
    )


def partition_specs(contract: Contract) -> Dict[str, object]:
    """The whole contract's symbolic PartitionSpecs, arguments and
    results — the declarative sharding surface tests and docs quote.
    Array leaves map to axis tuples, statics/DimRefs to None."""
    sh = contract.sharding
    if sh is None:
        raise ValueError(f"{contract.kernel}: no sharding leaf declared")

    def mat(spec):
        if isinstance(spec, ArraySpec):
            return partition_spec(spec, sh)
        if isinstance(spec, dict):
            return {k: mat(spec[k]) for k in sorted(spec)}
        if isinstance(spec, (Static, DimRef)):
            return None
        return tuple(mat(s) for s in spec)

    return {
        "args": {name: mat(spec) for name, spec in contract.args},
        "results": mat(contract.results),
    }


def sharded_abstract_args(
    contract: Contract, bindings: Dict[str, int], mesh
) -> Tuple[tuple, dict]:
    """:func:`abstract_args` with every array aval tagged with the
    NamedSharding its symbolic PartitionSpec implies on `mesh`, and
    the sharding leaf's lower_overrides applied to the kwargs — the
    exact input ktmesh partitioned-lowers (and the runtime cross-check
    in tests/test_multichip.py re-lowers)."""
    import jax  # noqa: F401  (NamedSharding needs an initialized jax)
    from jax.sharding import NamedSharding, PartitionSpec

    sh = contract.sharding
    if sh is None:
        raise ValueError(f"{contract.kernel}: no sharding leaf declared")

    def leaf_sharding(spec: ArraySpec):
        return NamedSharding(mesh, PartitionSpec(*partition_spec(spec, sh)))

    args = tuple(
        _materialize(spec, bindings, leaf_sharding)
        for _, spec in contract.args
    )
    kwargs = {
        name: _materialize(spec, bindings, leaf_sharding)
        for name, spec in contract.kwargs
    }
    for name, value in sh.lower_overrides:
        kwargs[name] = value
    return args, kwargs


#: One partitioned-HLO collective op: result dtype, result dims, kind.
#: Matched per line so the all-gather `dimensions={d}` attribute (the
#: gathered dim — what the pod-axis full-gather check needs) can be
#: read off the same line.
_COLLECTIVE_LINE_RE = re.compile(
    r"= (?P<dtype>[a-z]+\d*)\[(?P<dims>[\d,]*)\][^ ]* "
    r"(?P<kind>all-gather|all-reduce|reduce-scatter|collective-permute"
    r"|all-to-all)\("
)
_GATHER_DIM_RE = re.compile(r"dimensions=\{(\d+)\}")

_HLO_DTYPE_BYTES = {
    "f64": 8, "s64": 8, "u64": 8, "c64": 8,
    "f32": 4, "s32": 4, "u32": 4,
    "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
    "f8": 1, "s8": 1, "u8": 1, "pred": 1,
}


def collective_inventory(hlo_text: str) -> Dict[str, object]:
    """Walk one compiled/partitioned HLO module's text for collective
    ops. Returns {"counts": {kind: n}, "bytes": {kind: result bytes},
    "total": n, "ops": [per-op dicts]} — each op carries kind, dtype,
    shape, bytes, and (all-gather/all-to-all) the gathered dim index.
    Pure regex over ``Compiled.as_text()``: no jax import, so the
    ledger's harvest thread and ktmesh share THIS implementation
    without the control plane loading anything."""
    counts: Dict[str, int] = {}
    byte_volume: Dict[str, int] = {}
    ops: List[Dict[str, object]] = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_LINE_RE.search(line)
        if m is None:
            continue
        kind = m.group("kind")
        dims = (
            tuple(int(d) for d in m.group("dims").split(","))
            if m.group("dims")
            else ()
        )
        width = _HLO_DTYPE_BYTES.get(m.group("dtype"), 4)
        n_elem = 1
        for d in dims:
            n_elem *= d
        counts[kind] = counts.get(kind, 0) + 1
        byte_volume[kind] = byte_volume.get(kind, 0) + n_elem * width
        op: Dict[str, object] = {
            "kind": kind,
            "dtype": m.group("dtype"),
            "shape": list(dims),
            "bytes": n_elem * width,
        }
        if kind in ("all-gather", "all-to-all"):
            gm = _GATHER_DIM_RE.search(line)
            if gm is not None:
                op["gather_dim"] = int(gm.group(1))
        ops.append(op)
    return {
        "counts": counts,
        "bytes": byte_volume,
        "total": sum(counts.values()),
        "ops": ops,
    }


def comm_verdict(kernel: str, counts: Dict[str, int]) -> str:
    """The COMM column for one ledger shape row: the collective KINDS
    a runtime compile emitted, joined against the declared budget.
    Lenient on counts — runtime buckets differ from the pinned probe
    point, and ktmesh owns the exact-count gate there — but strict on
    kinds: a collective kind outside the declared budget is sharding
    drift no matter the shape. Single-device compiles have empty
    inventories and are trivially 'ok'."""
    contract = CONTRACTS.get(kernel)
    if contract is None or contract.sharding is None:
        return "uncontracted"
    if not counts:
        return "ok"
    declared = set(contract.sharding.budget.as_dict())
    extra = sorted(set(counts) - declared)
    if extra:
        return f"drift: undeclared {','.join(extra)}"
    return "ok"


def resolve_kernel(key: str):
    """The live TracedJit object for one registry key (imports the ops
    module; the preemption kernel builds lazily through its factory)."""
    import importlib

    mod_name, _, path = key.partition(".")
    mod = importlib.import_module(f"kubernetes_tpu.ops.{mod_name}")
    if key == "preemption._victim_prefix_kernel.kernel":
        return mod._victim_prefix_kernel()
    obj = mod
    for part in path.split("."):
        obj = getattr(obj, part)
    return obj


# -- observed-signature matching ---------------------------------------


def declared_array_leaves(
    contract: Contract,
) -> List[Tuple[str, ArraySpec]]:
    """The contract's array leaves in jax flattening order — args in
    call order, dict schemas by sorted key, kwargs after args (the
    order :func:`shape_signature` emits). Optional leaves keep their
    slot and may be skipped by the matcher."""
    out: List[Tuple[str, ArraySpec]] = []

    def scan(name, spec):
        if isinstance(spec, ArraySpec):
            out.append((name, spec))
        elif isinstance(spec, dict):
            for k in sorted(spec):
                scan(f"{name}.{k}", spec[k])

    for name, spec in contract.args:
        scan(name, spec)
    for name in sorted(dict(contract.kwargs)):
        scan(name, dict(contract.kwargs)[name])
    return out


def _match_leaves(
    observed: Sequence[Tuple[str, Tuple[int, ...]]],
    declared: Sequence[Tuple[str, ArraySpec]],
    bindings: Dict[str, int],
) -> Optional[str]:
    """Unify observed array tokens against declared leaves (optional
    leaves may be absent). Returns an error string or None on success;
    `bindings` accumulates dim assignments."""
    if not declared:
        if observed:
            tok = observed[0]
            return f"unexpected extra array leaf {tok[0]}{list(tok[1])}"
        return None
    name, spec = declared[0]
    # Try consuming one observed token with this leaf.
    if observed:
        dtype, shape = observed[0]
        if dtype == spec.dtype and len(shape) == len(spec.dims):
            trial = dict(bindings)
            ok = True
            for sym, size in zip(spec.dims, shape):
                if trial.setdefault(sym, size) != size:
                    ok = False
                    break
            if ok:
                err = _match_leaves(observed[1:], declared[1:], trial)
                if err is None:
                    bindings.clear()
                    bindings.update(trial)
                    return None
        if not spec.optional:
            want = f"{spec.dtype}[{','.join(spec.dims)}]"
            return (
                f"leaf {name}: observed {dtype}{list(shape)}, "
                f"declared {want}"
            )
    elif not spec.optional:
        return f"leaf {name}: missing (declared {spec.dtype})"
    # Skip an optional leaf.
    return _match_leaves(observed, declared[1:], bindings)


def match_signature(kernel: str, signature: str) -> Tuple[bool, str]:
    """(ok, detail): does one observed staged-shape signature satisfy
    the kernel's contract — dtypes and dim symbols unify, and every
    bound dim sits on its declared bucket lattice?"""
    contract = CONTRACTS.get(kernel)
    if contract is None:
        return False, "no contract declared"
    observed = parse_signature(signature)
    declared = declared_array_leaves(contract)
    bindings: Dict[str, int] = {}
    err = _match_leaves(observed, declared, bindings)
    if err is not None:
        return False, err
    for sym, size in sorted(bindings.items()):
        if not dim_ok(sym, size):
            desc = DIM_LATTICES.get(sym, ("?", None))[0]
            return False, (
                f"dim {sym}={size} is off its bucket lattice ({desc})"
            )
    return True, ",".join(f"{s}={v}" for s, v in sorted(bindings.items()))


def contract_verdict(kernel: str, signature: str) -> str:
    """The CONTRACT column for one ledger shape row: 'ok' when the
    observed staged shapes unify with the declared contract on-lattice,
    else 'mismatch: ...' (or 'uncontracted' for a kernel outside the
    registry)."""
    if kernel not in CONTRACTS:
        return "uncontracted"
    ok, detail = match_signature(kernel, signature)
    return "ok" if ok else f"mismatch: {detail}"


def registry_keys() -> List[str]:
    """Sorted ORACLE_TWINS keys (the completeness yardstick)."""
    return sorted(ORACLE_TWINS)
