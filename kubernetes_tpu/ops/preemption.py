"""Device-side preemption: minimal-victim selection as masked matrices.

Victim selection is itself an assignment problem — "which minimal,
lowest-priority set of running pods frees enough capacity on some node
for this unschedulable pod" — and it lowers onto the same machinery as
the batch solve: per-(pod, node) eviction-cost arrays, masked by
`victim.priority < preemptor.priority`, reduced per node-segment.

Canonical selection rule (shared verbatim by the scalar fallback in
scheduler/batch.py — the parity yardstick):

- candidate victims on a node are its live, non-terminating assigned
  pods with strictly lower priority, ordered (priority asc, arrival
  idx asc) — evict the least important, oldest-listed first;
- a node's victim set is the shortest prefix of that order whose freed
  cpu+mem (plus a pod slot) lets the preemptor fit; a node where the
  preemptor already fits with zero evictions is NOT a preemption
  candidate (capacity isn't its blocker, so eviction can't help);
- among feasible nodes the winner minimizes, lexicographically,
  (priority of the highest-priority victim, victim count, node index)
  — disturb the least important workloads, then the fewest, then
  deterministically.

The device path stages victims/nodes as padded arrays (pow2 bucketing
on BOTH axes, mirroring gang_member_counts — padded victims carry
node=-1 and mask out; padded nodes are never ok) and runs ONE jitted
kernel per preemptor: lexsort by (node, priority, idx), per-node
prefix sums via cumsum minus segment offsets, and a masked segment_min
over the first fitting prefix length. Preemptors are processed
highest-priority-first on the host, each one's chosen victims leaving
the alive mask and its own request charged against the node — so two
preemptors in one tick never double-spend the same victim's capacity.

Resource model deliberately matches what eviction can actually fix:
cpu/mem/pod-slot capacity plus node readiness and the preemptor's
nodeSelector. Port/volume/service conflicts are left to the real solve
after victims exit — a nomination is a reservation, not a binding.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.models.columnar import (
    mem_to_mib_ceil,
    node_is_ready,
    pod_resource_limits,
)
from kubernetes_tpu.models.objects import (
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    Node,
    Pod,
    pod_can_preempt,
    pod_full_key,
    pod_is_terminating,
    pod_priority,
)
from kubernetes_tpu.ops.ledger import traced_jit
from kubernetes_tpu.ops.matrices import pow2_bucket

#: Sentinel "no feasible victim prefix" for per-node k arrays.
INFEASIBLE = np.int32(2**31 - 1)

#: Canonical rejection reason the flight recorder records for a
#: preemptor no node could be freed for — the preemption face of the
#: per-predicate "why not" surface (shared by both solve paths).
REASON_INFEASIBLE = (
    "no node can free enough capacity by evicting strictly "
    "lower-priority pods"
)


@dataclass
class PreemptionDecision:
    """One granted preemption: evict `victims` (pod keys, eviction
    order) on `node`, then nominate `key` there."""

    key: str  # preemptor pod key "ns/name"
    node: str
    victims: Tuple[str, ...]

    def to_wire(self) -> dict:
        """The /debug/decisions shape of a granted preemption."""
        return {
            "pod": self.key,
            "node": self.node,
            "victims": list(self.victims),
        }


@dataclass
class PreemptionProblem:
    """Host-lowered cluster state for one preemption pass."""

    node_names: List[str]
    node_labels: List[Dict[str, str]]
    node_ready: np.ndarray  # bool[N]
    free_cpu: np.ndarray  # f64[N], +inf = unlimited
    free_mem: np.ndarray
    free_pods: np.ndarray
    victim_keys: List[str]
    v_cpu: np.ndarray  # f64[V] milli-cores
    v_mem: np.ndarray  # f64[V] MiB
    v_prio: np.ndarray  # i64[V]
    v_node: np.ndarray  # i32[V]


def _pod_request(pod: Pod) -> Tuple[float, float]:
    cpu, mem = pod_resource_limits(pod)
    return float(cpu), float(mem_to_mib_ceil(mem))


def build_preemption_problem(
    nodes: Sequence[Node], assigned: Sequence[Pod]
) -> PreemptionProblem:
    """Lower nodes + assigned pods into the preemption arrays. ALL
    assigned pods charge node usage (a Terminating victim still holds
    its capacity until it actually exits); only live, non-terminating
    pods become victim rows."""
    nodes = list(nodes)
    index = {n.metadata.name: j for j, n in enumerate(nodes)}
    N = len(nodes)
    free_cpu = np.full(N, np.inf)
    free_mem = np.full(N, np.inf)
    free_pods = np.full(N, np.inf)
    ready = np.zeros(N, bool)
    labels: List[Dict[str, str]] = []
    for j, node in enumerate(nodes):
        cap = node.status.capacity or {}
        if RESOURCE_CPU in cap and cap[RESOURCE_CPU].milli_value() > 0:
            free_cpu[j] = cap[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in cap and cap[RESOURCE_MEMORY].value() > 0:
            free_mem[j] = cap[RESOURCE_MEMORY].value() // (1024**2)
        if RESOURCE_PODS in cap and cap[RESOURCE_PODS].value() > 0:
            free_pods[j] = cap[RESOURCE_PODS].value()
        ready[j] = node_is_ready(node) and not node.spec.unschedulable
        labels.append(node.metadata.labels or {})
    keys: List[str] = []
    v_cpu: List[float] = []
    v_mem: List[float] = []
    v_prio: List[int] = []
    v_node: List[int] = []
    for pod in assigned:
        j = index.get(pod.spec.node_name, -1)
        if j < 0:
            continue
        cpu, mem = _pod_request(pod)
        free_cpu[j] -= cpu
        free_mem[j] -= mem
        free_pods[j] -= 1
        if pod.status.phase in ("Succeeded", "Failed") or pod_is_terminating(pod):
            continue  # occupies, but is not (or no longer) a candidate
        keys.append(pod_full_key(pod))
        v_cpu.append(cpu)
        v_mem.append(mem)
        v_prio.append(pod_priority(pod))
        v_node.append(j)
    return PreemptionProblem(
        node_names=[n.metadata.name for n in nodes],
        node_labels=labels,
        node_ready=ready,
        free_cpu=free_cpu,
        free_mem=free_mem,
        free_pods=free_pods,
        victim_keys=keys,
        v_cpu=np.asarray(v_cpu, np.float64),
        v_mem=np.asarray(v_mem, np.float64),
        v_prio=np.asarray(v_prio, np.int64),
        v_node=np.asarray(v_node, np.int32),
    )


def _selector_ok(problem: PreemptionProblem, pod: Pod) -> np.ndarray:
    """bool[N]: node ready AND labels satisfy the pod's nodeSelector."""
    sel = pod.spec.node_selector or {}
    ok = problem.node_ready.copy()
    if sel:
        for j, labels in enumerate(problem.node_labels):
            if ok[j] and any(labels.get(k) != v for k, v in sel.items()):
                ok[j] = False
    return ok


# -- device kernel ----------------------------------------------------


def _victim_prefix_kernel():
    """Build (lazily, so a CPU-only host without jax configured never
    imports it at module load) the jitted per-preemptor kernel.

    Returns per-node minimal victim counts and the priority of each
    prefix's last (= highest-priority) victim, via one lexsort + masked
    segment reductions over static, pow2-bucketed shapes.
    """
    import jax
    import jax.numpy as jnp

    @traced_jit(static_argnames=("num_nodes",))
    def kernel(
        v_cpu, v_mem, v_prio, v_node, v_alive,
        free_cpu, free_mem, free_pods, node_ok,
        p_cpu, p_mem, p_prio,
        num_nodes: int,
    ):
        V = v_cpu.shape[0]
        # Eligibility mask: alive, on a real node, strictly dominated.
        mask = v_alive & (v_node >= 0) & (v_prio < p_prio)
        # Masked-out rows sort into a trailing dummy segment.
        seg = jnp.where(mask, v_node, num_nodes).astype(jnp.int32)
        idx = jnp.arange(V, dtype=jnp.int32)
        order = jnp.lexsort((idx, v_prio, seg))
        seg_s = seg[order]
        cpu_s = jnp.where(mask, v_cpu, 0.0)[order]
        mem_s = jnp.where(mask, v_mem, 0.0)[order]
        prio_s = v_prio[order]
        one_s = mask[order].astype(jnp.float32)
        S = num_nodes + 1
        # Per-node prefix sums: global cumsum minus each segment's
        # starting offset (segments are contiguous after the sort).
        tot_cpu = jax.ops.segment_sum(cpu_s, seg_s, num_segments=S)
        tot_mem = jax.ops.segment_sum(mem_s, seg_s, num_segments=S)
        tot_cnt = jax.ops.segment_sum(one_s, seg_s, num_segments=S)
        off_cpu = jnp.cumsum(tot_cpu) - tot_cpu
        off_mem = jnp.cumsum(tot_mem) - tot_mem
        off_cnt = jnp.cumsum(tot_cnt) - tot_cnt
        freed_cpu = jnp.cumsum(cpu_s) - off_cpu[seg_s]
        freed_mem = jnp.cumsum(mem_s) - off_mem[seg_s]
        rank = jnp.cumsum(one_s) - off_cnt[seg_s]  # 1-based within node
        on_node = seg_s < num_nodes
        fits = (
            on_node
            & node_ok[jnp.clip(seg_s, 0, num_nodes - 1)]
            & (free_cpu[jnp.clip(seg_s, 0, num_nodes - 1)] + freed_cpu >= p_cpu)
            & (free_mem[jnp.clip(seg_s, 0, num_nodes - 1)] + freed_mem >= p_mem)
            & (free_pods[jnp.clip(seg_s, 0, num_nodes - 1)] + rank >= 1.0)
        )
        big = jnp.int32(INFEASIBLE)
        cand = jnp.where(fits, rank.astype(jnp.int32), big)
        k_min = jax.ops.segment_min(cand, seg_s, num_segments=S)[:num_nodes]
        # Nodes where the preemptor fits with ZERO evictions: capacity
        # is not the blocker there — preemption cannot help.
        fits0 = (
            node_ok
            & (free_cpu >= p_cpu)
            & (free_mem >= p_mem)
            & (free_pods >= 1.0)
        )
        k_min = jnp.where(fits0, big, k_min)
        # Priority of each feasible prefix's last victim.
        pos = jnp.clip(
            off_cnt[jnp.arange(num_nodes)].astype(jnp.int32)
            + jnp.minimum(k_min, jnp.int32(V)) - 1,
            0, V - 1,
        )
        maxp = jnp.where(k_min < big, prio_s[pos], jnp.int32(0))
        return k_min, maxp, order, seg_s

    return kernel


_KERNEL = None


def candidate_prefixes_device(
    v_cpu, v_mem, v_prio, v_node, v_alive,
    free_cpu, free_mem, free_pods, node_ok,
    p_cpu: float, p_mem: float, p_prio: int,
):
    """Stage one preemptor's problem onto the device and run the
    prefix kernel. Both axes pad to pow2 buckets (padded victims:
    node=-1, dead; padded nodes: never ok) so per-tick drift in either
    count reuses the compiled executable instead of recompiling."""
    global _KERNEL
    import jax.numpy as jnp

    if _KERNEL is None:
        _KERNEL = _victim_prefix_kernel()
    V = int(v_cpu.shape[0])
    N = int(free_cpu.shape[0])
    VP = pow2_bucket(max(V, 1), minimum=8)
    NP = pow2_bucket(max(N, 1), minimum=8)
    if VP != V:
        pad = VP - V
        v_cpu = np.pad(v_cpu, (0, pad))
        v_mem = np.pad(v_mem, (0, pad))
        v_prio = np.pad(v_prio, (0, pad))
        v_node = np.pad(v_node, (0, pad), constant_values=-1)
        v_alive = np.pad(v_alive, (0, pad))
    if NP != N:
        pad = NP - N
        free_cpu = np.pad(free_cpu, (0, pad))
        free_mem = np.pad(free_mem, (0, pad))
        free_pods = np.pad(free_pods, (0, pad))
        node_ok = np.pad(node_ok, (0, pad))
    k_min, maxp, order, seg_s = _KERNEL(
        jnp.asarray(v_cpu, jnp.float32),
        jnp.asarray(v_mem, jnp.float32),
        jnp.asarray(v_prio, jnp.int32),
        jnp.asarray(v_node, jnp.int32),
        jnp.asarray(v_alive, bool),
        jnp.asarray(free_cpu, jnp.float32),
        jnp.asarray(free_mem, jnp.float32),
        jnp.asarray(free_pods, jnp.float32),
        jnp.asarray(node_ok, bool),
        jnp.float32(p_cpu),
        jnp.float32(p_mem),
        jnp.int32(p_prio),
        num_nodes=NP,
    )
    return (
        np.asarray(k_min)[:N],
        np.asarray(maxp)[:N],
        np.asarray(order),
        np.asarray(seg_s),
    )


def solve_preemption_device(
    problem: PreemptionProblem, preemptors: Sequence[Pod]
) -> List[Optional[PreemptionDecision]]:
    """Victim selection for each preemptor (device path). Preemptors
    run highest-priority-first; each grant marks its victims dead and
    charges the preemptor's request onto the node (net of the freed
    capacity) so later preemptors see the post-preemption cluster.
    Returns decisions aligned with `preemptors` (None = no feasible
    node / pod may not preempt / dominates no victim)."""
    out: List[Optional[PreemptionDecision]] = [None] * len(preemptors)
    alive = np.ones(len(problem.victim_keys), bool)
    free_cpu = problem.free_cpu.copy()
    free_mem = problem.free_mem.copy()
    free_pods = problem.free_pods.copy()
    order_p = sorted(
        range(len(preemptors)),
        key=lambda i: (-pod_priority(preemptors[i]), i),
    )
    for i in order_p:
        pod = preemptors[i]
        prio = pod_priority(pod)
        if prio <= 0 or not pod_can_preempt(pod):
            continue
        cpu, mem = _pod_request(pod)
        node_ok = _selector_ok(problem, pod)
        k_min, maxp, order, seg_s = candidate_prefixes_device(
            problem.v_cpu, problem.v_mem, problem.v_prio, problem.v_node,
            alive, free_cpu, free_mem, free_pods, node_ok,
            cpu, mem, prio,
        )
        best = None
        for j in range(len(problem.node_names)):
            k = int(k_min[j])
            if k >= int(INFEASIBLE):
                continue
            score = (int(maxp[j]), k, j)
            if best is None or score < best[0]:
                best = (score, j, k)
        if best is None:
            continue
        _, j, k = best
        chosen = [
            int(order[t])
            for t in range(len(order))
            if int(seg_s[t]) == j
        ][:k]
        alive[chosen] = False
        freed_cpu = float(problem.v_cpu[chosen].sum())
        freed_mem = float(problem.v_mem[chosen].sum())
        free_cpu[j] += freed_cpu - cpu
        free_mem[j] += freed_mem - mem
        free_pods[j] += k - 1
        out[i] = PreemptionDecision(
            key=pod_full_key(pod),
            node=problem.node_names[j],
            victims=tuple(problem.victim_keys[t] for t in chosen),
        )
    return out
