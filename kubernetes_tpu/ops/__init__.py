"""Device ops: the TPU scheduling solver.

The reference's per-pod Go loops (generic_scheduler.go:106-171) become
a jitted lax.scan whose carry is the cluster occupancy state and whose
per-step body evaluates every predicate and priority for one pod
against ALL nodes as vector ops. Node-axis arrays shard over a
jax.sharding.Mesh for multi-chip scale-out.
"""

# NOTE on the persistent XLA compilation cache: deliberately NOT
# enabled here. Measured on this image, the cache never captured the
# big solver executables (only trivial jit_broadcast-type entries), and
# loading its AOT artifacts on a different host than compiled them
# trips XLA's machine-feature mismatch path (cpu_aot_loader: "could
# lead to SIGILL"). Shape-bucketing (matrices._pod_axis_bucket) is the
# mechanism that actually bounds recompiles. Operators who want the
# cache can set JAX_COMPILATION_CACHE_DIR themselves.

from kubernetes_tpu.ops.matrices import DeviceSnapshot, device_snapshot
from kubernetes_tpu.ops.pipeline import solve_backlog_pipelined
from kubernetes_tpu.ops.preemption import (
    PreemptionDecision,
    build_preemption_problem,
    solve_preemption_device,
)
from kubernetes_tpu.ops.solver import solve, solve_assignments, solve_with_state
from kubernetes_tpu.ops.incremental import (
    RebuildRequired,
    SessionGang,
    SolverSession,
)
from kubernetes_tpu.ops.wave import solve_waves

__all__ = [
    "DeviceSnapshot",
    "PreemptionDecision",
    "RebuildRequired",
    "SessionGang",
    "SolverSession",
    "build_preemption_problem",
    "device_snapshot",
    "solve",
    "solve_assignments",
    "solve_backlog_pipelined",
    "solve_preemption_device",
    "solve_waves",
    "solve_with_state",
]
