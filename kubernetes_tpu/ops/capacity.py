"""Capacity & fragmentation kernels: cluster headroom, stranded
capacity, and slice allocatability as one dense reduction.

Roadmap item 5 (descheduler/defragmenter + autoscaler) needs fleet
capacity signals that the dense pod x node formulation makes nearly
free: the node occupancy columns are already staged (device-resident
in the incremental session's carry, host-mirrored in ``session.h``),
so one extra jitted reduction per resolved micro-tick yields the full
vocabulary — per-node free vectors, utilization ratios, and for a set
of canonical **probe pod shapes** (the backlog's observed shape
quantiles plus configured slice shapes):

- ``headroom[q]``: how many pods of probe shape ``q`` still fit —
  per-node integral fit (greedy: a node hosts ``floor(free/request)``
  probes per resource, min across resources and the pods allowance),
  mask-reduced over live nodes. For identical-shape members this IS
  the gang bound: the largest all-or-nothing group of shape ``q``
  placeable right now is ``headroom[q]`` (per-node integral fits are
  independent), so slice allocatability reuses the gang acceptance
  predicate ``headroom >= minMember`` (``gang_member_counts`` vs
  minMember, scheduler/gang.py).

- ``frag[q]``: the stranded-capacity fraction — of the aggregate free
  capacity measured in probe-``q`` units (the FRACTIONAL fit, free
  capacity divided by the probe's bottleneck request, no floor), the
  share no single node can actually host: ``1 - usable/potential``.
  A fleet that could hold 40 probes if free capacity were contiguous
  but fits only 10 scores 0.75 for that shape.

- ``frag_score``: the capacity-weighted aggregate over live probes —
  ``1 - sum_q(headroom) / sum_q(potential)`` — the single always-on
  ``cluster_fragmentation_score`` series.

Integer-exactness discipline: every cross-node/cross-probe reduction
sums **int32** (integral fits; fractional fits quantized to 1/FRAC_Q
probe units, per-node fits clipped to FIT_CAP) so results are
independent of XLA's reduction order and the KT006 NumPy twin
(``ops.oracle.capacity_report_numpy``) matches bit-for-bit — the same
trick the solver's parity chain leans on. The remaining float work is
elementwise (divisions, comparisons), where IEEE f32 agrees between
XLA:CPU/TPU and NumPy. Overflow budget: N * FIT_CAP * FRAC_Q = 2^30
at N=8192 fully saturated nodes — and real clusters sit far below the
clip (FIT_CAP is ~75x the kubelet's default 110-pod allowance).

Probe semantics: a probe is (cpu milli, mem MiB, minMember) in the
same units as the NODE_SCHEMA columns. ``probe_live`` masks padding
rows (probe count pads to a pow2 bucket so the executable is reused
across backlog-quantile churn). Zero-request probes fit wherever the
pods allowance allows, mirroring the solver's zero_req rule.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubernetes_tpu.ops.ledger import traced_jit

#: Fractional fits are quantized to 1/FRAC_Q probe units (int32) so
#: cross-node sums are reduction-order independent and the NumPy twin
#: is bit-exact; 1/16 of one probe is far below fragmentation signal.
FRAC_Q = 16

#: Per-node fit clip: keeps the quantized cross-node sums inside int32
#: (see module docstring's overflow budget) while sitting far above any
#: real kubelet pods allowance.
FIT_CAP = 2.0**13

#: Stand-in for "unconstrained" per-resource fits (zero-request
#: probes) before the min with the pods allowance and FIT_CAP.
BIG_FIT = 2.0**20


@traced_jit
def capacity_report(
    cpu_cap,
    mem_cap,
    pods_cap,
    cpu_fit,
    mem_fit,
    pods_used,
    over,
    sched,
    probe_cpu,
    probe_mem,
    probe_min,
    probe_live,
):
    """The capacity plane's one dense pass: free vectors, utilization
    ratios, per-probe headroom/fragmentation, slice allocatability,
    and per-node stranded flags.

    Node columns are the NODE_SCHEMA occupancy view (the solver's
    greedy-fit charge ``cpu_fit``/``mem_fit``, which excludes
    terminal-phase and Terminating pods upstream); ``over`` marks
    overcommitted nodes (unplaceable, like the solver treats them),
    ``sched`` readiness. Returns a flat tuple:

    ``(util_cpu f32[N], util_mem f32[N], util_pods f32[N],
    fit_int i32[Q,N], headroom i32[Q], frag f32[Q], slice_ok b8[Q],
    stranded b8[N], frag_score f32[], stranded_cpu f32[],
    stranded_mem f32[])``
    """
    f0 = jnp.float32(0.0)
    f1 = jnp.float32(1.0)
    big = jnp.float32(BIG_FIT)
    live = sched & ~over
    livef = live.astype(jnp.float32)

    free_cpu = jnp.maximum(cpu_cap - cpu_fit, f0) * livef
    free_mem = jnp.maximum(mem_cap - mem_fit, f0) * livef
    free_pods = jnp.maximum(pods_cap - pods_used, f0) * livef

    # Utilization = charged/capacity, clamped (overcommit reads 1.0).
    # Dead/padding nodes read 0 here and carry live=False in
    # `stranded`'s mask; the host side filters on the same columns.
    def util(used_part, cap):
        return jnp.where(
            (cap > f0) & live,
            jnp.clip(used_part / jnp.maximum(cap, f1), f0, f1),
            f0,
        )

    util_cpu = util(cpu_fit, cpu_cap)
    util_mem = util(mem_fit, mem_cap)
    util_pods = util(pods_used, pods_cap)

    # Per-(probe, node) fits. Fractional fit = free capacity in probe
    # units, bottlenecked across resources (no floor); integral fit
    # floors per resource (floor of a min == min of floors).
    pc = probe_cpu[:, None]
    pm = probe_mem[:, None]
    per_cpu = jnp.where(pc > f0, free_cpu[None, :] / jnp.maximum(pc, f1), big)
    per_mem = jnp.where(pm > f0, free_mem[None, :] / jnp.maximum(pm, f1), big)
    fit_frac = jnp.minimum(
        jnp.minimum(per_cpu, per_mem), free_pods[None, :]
    )
    fit_frac = jnp.clip(fit_frac, f0, jnp.float32(FIT_CAP))
    fit_int = jnp.floor(fit_frac).astype(jnp.int32)
    frac_milli = jnp.floor(fit_frac * jnp.float32(FRAC_Q)).astype(jnp.int32)

    plive = probe_live.astype(jnp.int32)
    usable = jnp.sum(fit_int, axis=1) * plive  # i32[Q]
    potential = jnp.sum(frac_milli, axis=1) * plive  # i32[Q], 1/FRAC_Q units
    headroom = usable
    frag = jnp.where(
        potential > jnp.int32(0),
        f1
        - (usable.astype(jnp.float32) * jnp.float32(FRAC_Q))
        / potential.astype(jnp.float32),
        f0,
    )
    frag = jnp.clip(frag, f0, f1) * probe_live.astype(jnp.float32)
    slice_ok = probe_live & (headroom >= jnp.maximum(probe_min, jnp.int32(1)))

    # Capacity-weighted aggregate over live probes (reduces over the
    # probe axis): integer totals keep it reduction-order exact.
    total_usable = jnp.sum(usable)
    total_potential = jnp.sum(potential)
    frag_score = jnp.where(
        total_potential > jnp.int32(0),
        f1
        - (total_usable.astype(jnp.float32) * jnp.float32(FRAC_Q))
        / total_potential.astype(jnp.float32),
        f0,
    )
    frag_score = jnp.clip(frag_score, f0, f1)

    # Stranded node: live, has leftover cpu/mem, hosts ZERO probes of
    # every live shape (its free capacity is unusable as probes see it).
    hosts_any = jnp.any((fit_int > jnp.int32(0)) & probe_live[:, None], axis=0)
    any_live_probe = jnp.any(probe_live)
    stranded = (
        live
        & ((free_cpu > f0) | (free_mem > f0))
        & ~hosts_any
        & any_live_probe
    )

    # Stranded share of aggregate free capacity, per resource —
    # int32-summed (the columns hold integral milli/MiB values).
    def stranded_frac(free):
        free_i = free.astype(jnp.int32)
        tot = jnp.sum(free_i)
        strand = jnp.sum(free_i * stranded.astype(jnp.int32))
        return jnp.where(
            tot > jnp.int32(0),
            strand.astype(jnp.float32) / tot.astype(jnp.float32),
            f0,
        )

    stranded_cpu = stranded_frac(free_cpu)
    stranded_mem = stranded_frac(free_mem)

    return (
        util_cpu,
        util_mem,
        util_pods,
        fit_int,
        headroom,
        frag,
        slice_ok,
        stranded,
        frag_score,
        stranded_cpu,
        stranded_mem,
    )
