"""Sequential NumPy oracle: the reference's scheduleOne semantics
replayed pod-at-a-time in exact host arithmetic (int64 / float64).

Role in the parity chain (BASELINE.md >=99% target):
- The scalar object-graph oracle (scheduler.batch.schedule_backlog_scalar)
  IS the reference semantics (plugin/pkg/scheduler/generic_scheduler.go:
  60-171), but it is O(P^2 * N) Python — unusable beyond ~1k pods.
- This oracle replays the same decisions over the columnar Snapshot with
  one batch of NumPy N-vector ops per pod, so parity can be MEASURED at
  the full 50k x 5k scale instead of asserted from toy runs.
- Equivalence scalar-oracle == numpy-oracle is itself tested at fuzz
  scale and at BASELINE config 2 (tests/test_solver_parity.py), so
  device-vs-numpy parity at 50k is evidence about the device scan, and
  scalar-vs-device parity at 1k is evidence about the lowering.

Arithmetic notes: LeastRequested uses int64 // (Go int64 truncation,
priorities.go:31-40); BalancedResourceAllocation and ServiceSpreading
use float64 then int-truncate exactly like the scalar path
(priorities.go:146-205, spreading.go:38-87). This intentionally does
NOT reproduce the device's f32-reciprocal epsilon hack — divergence
there is precisely what the parity number is meant to expose.
"""

from __future__ import annotations

import numpy as np

from kubernetes_tpu.models.columnar import Snapshot
from kubernetes_tpu.models.columnar import SVC_K  # noqa: F401


def solve_sequential_numpy(snap: Snapshot) -> np.ndarray:
    """i32[P] node indices (-1 = unschedulable), in pod order."""
    out, _ = _replay(snap, forced=None)
    return out


def explain_bits_numpy(snap: Snapshot):
    """The explain readback's scalar twin: per-(pod, node) packed
    predicate-failure bits plus the default priority components, in
    host arithmetic over the FIXED snapshot state (no sequential
    commit — every pod sees the same occupancy, exactly like the
    device readback in ops.solver.explain_rows evaluates it).

    Bit layout is ops.matrices.EXPLAIN_PREDICATES. Returns
    (bits u32[P, N], lr i64[P, N], bra i64[P, N], spread i64[P, N]).

    Unlike the solve oracle above, BalancedResourceAllocation here
    reproduces the device's float32 + epsilon recipe on purpose: this
    twin certifies the READBACK bit-for-bit (tests/test_solver_parity
    TestExplainParity demands 100%), while Go-semantics divergence
    remains the solve-parity suite's business."""
    p, n = snap.pods, snap.nodes
    P, N = p.count, n.count
    bits = np.zeros((P, N), np.uint32)
    lr = np.zeros((P, N), np.int64)
    bra = np.zeros((P, N), np.int64)
    spread = np.zeros((P, N), np.int64)
    if P == 0 or N == 0:
        return bits, lr, bra, spread

    cpu_cap = n.cpu_cap.astype(np.int64)
    mem_cap = n.mem_cap.astype(np.int64)
    pods_cap = n.pods_cap.astype(np.int64)
    cpu_fit = n.cpu_fit_used.astype(np.int64)
    mem_fit = n.mem_fit_used.astype(np.int64)
    over = n.overcommitted
    cpu_used = n.cpu_used.astype(np.int64)
    mem_used = n.mem_used.astype(np.int64)
    pods_used = n.pods_used.astype(np.int64)
    labels = n.label_bits
    uport = n.used_port_bits
    uvol_any = n.used_vol_any_bits
    uvol_rw = n.used_vol_rw_bits
    svc_counts = n.service_counts.astype(np.int64)
    sched = n.schedulable
    idx = np.arange(N, dtype=np.int64)
    pod_cpu = p.cpu_milli.astype(np.int64)
    pod_mem = p.mem_mib.astype(np.int64)
    sel_rows = p.sel_bits[p.selector_id]

    for i in range(P):
        # -- predicates, one bit each (solver._pred_* formulas) --
        fits_cpu = (cpu_cap == 0) | (cpu_fit + pod_cpu[i] <= cpu_cap)
        fits_mem = (mem_cap == 0) | (mem_fit + pod_mem[i] <= mem_cap)
        fits_count = pods_used + 1 <= pods_cap
        if p.zero_req[i]:
            res_ok = pods_used < pods_cap
        else:
            res_ok = (~over) & fits_cpu & fits_mem & fits_count
        sel = sel_rows[i]
        sel_ok = ((sel[None, :] & labels) == sel[None, :]).all(axis=1)
        port_ok = ~(p.port_bits[i][None, :] & uport).any(axis=1)
        vol_ok = ~(
            (p.vol_rw_bits[i][None, :] & uvol_any)
            | (p.vol_any_bits[i][None, :] & uvol_rw)
        ).any(axis=1)
        pin = int(p.pinned_node[i])
        host_ok = np.ones(N, bool) if pin == -1 else (idx == pin)
        for b, ok in enumerate(
            (sched, res_ok, sel_ok, port_ok, vol_ok, host_ok)
        ):
            bits[i] |= (~ok).astype(np.uint32) << b

        # -- components --
        creq = cpu_used + pod_cpu[i]
        mreq = mem_used + pod_mem[i]
        lr_c = np.where(
            (cpu_cap == 0) | (creq > cpu_cap),
            0,
            ((cpu_cap - creq) * 10) // np.maximum(cpu_cap, 1),
        )
        lr_m = np.where(
            (mem_cap == 0) | (mreq > mem_cap),
            0,
            ((mem_cap - mreq) * 10) // np.maximum(mem_cap, 1),
        )
        lr[i] = (lr_c + lr_m) // 2
        # float32 on the host is IEEE round-to-nearest — identical to
        # the CPU jax backend the parity suite runs on.
        cfrac = np.where(
            cpu_cap == 0,
            np.float32(1.0),
            creq.astype(np.float32) / np.maximum(cpu_cap, 1).astype(np.float32),
        ).astype(np.float32)
        mfrac = np.where(
            mem_cap == 0,
            np.float32(1.0),
            mreq.astype(np.float32) / np.maximum(mem_cap, 1).astype(np.float32),
        ).astype(np.float32)
        bra[i] = np.where(
            (cfrac >= 1) | (mfrac >= 1),
            0,
            (
                np.float32(10)
                - np.abs(cfrac - mfrac) * np.float32(10)
                + np.float32(1e-5)
            ).astype(np.int64),
        )
        svc = int(p.service_id[i])
        if svc < 0:
            spread[i] = 10
        else:
            counts = svc_counts[:, svc]
            maxc = int(counts.max())
            if maxc == 0:
                spread[i] = 10
            else:
                spread[i] = (10 * (maxc - counts)) // maxc
    return bits, lr, bra, spread


def assignment_quality(snap: Snapshot, assignment: np.ndarray) -> dict:
    """Score an APPROXIMATE solver's assignment against the greedy
    oracle (VERDICT r2 Weak #2: wave/sinkhorn quality must be a
    number, not a claim). Replays the backlog in pod order committing
    each pod to its ASSIGNED node, and at each step measures the score
    gap to the oracle's best feasible node at that state:

      regret_i = max feasible score - score(assigned node)

    Returns mean/p99 regret (0 = every placement was greedy-optimal in
    order), the fraction of placements that were exactly greedy-best,
    and the fraction feasible under pod-order replay (wave commits in
    a different order, so a valid wave placement can transiently look
    infeasible here; regret is measured over the feasible ones)."""
    _, stats = _replay(snap, forced=np.asarray(assignment, dtype=np.int32))
    return stats


def _replay(snap: Snapshot, forced):
    p, n = snap.pods, snap.nodes
    P, N = p.count, n.count
    out = np.full(P, -1, dtype=np.int32)
    regrets = []
    greedy_hits = 0
    placed = 0
    infeasible_in_order = 0
    if P == 0 or N == 0:
        return out, {
            "mean_regret": 0.0,
            "p99_regret": 0.0,
            "greedy_match": 1.0,
            "feasible_in_order": 1.0,
            "placed": 0,
        }

    cpu_cap = n.cpu_cap.astype(np.int64)
    mem_cap = n.mem_cap.astype(np.int64)
    pods_cap = n.pods_cap.astype(np.int64)
    cpu_fit = n.cpu_fit_used.astype(np.int64).copy()
    mem_fit = n.mem_fit_used.astype(np.int64).copy()
    over = n.overcommitted.copy()
    cpu_used = n.cpu_used.astype(np.int64).copy()
    mem_used = n.mem_used.astype(np.int64).copy()
    pods_used = n.pods_used.astype(np.int64).copy()
    labels = n.label_bits
    uport = n.used_port_bits.copy()
    uvol_any = n.used_vol_any_bits.copy()
    uvol_rw = n.used_vol_rw_bits.copy()
    svc_counts = n.service_counts.astype(np.int64).copy()
    sched = n.schedulable
    idx = np.arange(N, dtype=np.int64)

    pod_cpu = p.cpu_milli.astype(np.int64)
    pod_mem = p.mem_mib.astype(np.int64)
    sel_rows = p.sel_bits[p.selector_id]
    # Same top-K membership truncation the device path commits with.
    svc_ids = p.svc_topk

    for i in range(P):
        # -- predicates (solver.py _feasible formulas) --
        fits_cpu = (cpu_cap == 0) | (cpu_fit + pod_cpu[i] <= cpu_cap)
        fits_mem = (mem_cap == 0) | (mem_fit + pod_mem[i] <= mem_cap)
        fits_count = pods_used + 1 <= pods_cap
        if p.zero_req[i]:
            res_ok = pods_used < pods_cap
        else:
            res_ok = (~over) & fits_cpu & fits_mem & fits_count
        sel = sel_rows[i]
        sel_ok = ((sel[None, :] & labels) == sel[None, :]).all(axis=1)
        port_ok = ~(p.port_bits[i][None, :] & uport).any(axis=1)
        vol_bad = (
            (p.vol_rw_bits[i][None, :] & uvol_any)
            | (p.vol_any_bits[i][None, :] & uvol_rw)
        ).any(axis=1)
        pin = int(p.pinned_node[i])
        host_ok = True if pin == -1 else (idx == pin)
        feas = res_ok & sel_ok & port_ok & ~vol_bad & host_ok & sched

        # -- priorities (exact host arithmetic) --
        creq = cpu_used + pod_cpu[i]
        mreq = mem_used + pod_mem[i]
        lr_c = np.where(
            (cpu_cap == 0) | (creq > cpu_cap),
            0,
            ((cpu_cap - creq) * 10) // np.maximum(cpu_cap, 1),
        )
        lr_m = np.where(
            (mem_cap == 0) | (mreq > mem_cap),
            0,
            ((mem_cap - mreq) * 10) // np.maximum(mem_cap, 1),
        )
        lr = (lr_c + lr_m) // 2
        cfrac = np.where(cpu_cap == 0, 1.0, creq / np.maximum(cpu_cap, 1))
        mfrac = np.where(mem_cap == 0, 1.0, mreq / np.maximum(mem_cap, 1))
        bra = np.where(
            (cfrac >= 1) | (mfrac >= 1),
            0,
            (10.0 - np.abs(cfrac - mfrac) * 10.0).astype(np.int64),
        )
        svc = int(p.service_id[i])
        if svc < 0:
            spread = np.full(N, 10, dtype=np.int64)
        else:
            counts = svc_counts[:, svc]
            maxc = int(counts.max())
            if maxc == 0:
                spread = np.full(N, 10, dtype=np.int64)
            else:
                spread = (10.0 * ((maxc - counts) / maxc)).astype(np.int64)
        score = lr + bra + spread

        masked = np.where(feas, score, -1)
        best = int(np.argmax(masked))  # first max = lowest node index
        if forced is None:
            if masked[best] < 0:
                continue
            out[i] = best
        else:
            chosen = int(forced[i])
            if chosen < 0:
                continue  # the approximate solver left it unplaced
            placed += 1
            if masked[best] >= 0 and feas[chosen]:
                regrets.append(int(masked[best]) - int(score[chosen]))
                if int(score[chosen]) == int(masked[best]):
                    greedy_hits += 1
            else:
                infeasible_in_order += 1
            out[i] = best = chosen

        # -- commit (AssumePod analog) --
        cpu_fit[best] += pod_cpu[i]
        mem_fit[best] += pod_mem[i]
        cpu_used[best] += pod_cpu[i]
        mem_used[best] += pod_mem[i]
        pods_used[best] += 1
        uport[best] |= p.port_bits[i]
        uvol_any[best] |= p.vol_any_bits[i]
        uvol_rw[best] |= p.vol_rw_bits[i]
        ids = svc_ids[i]
        ids = ids[ids >= 0]
        if len(ids):
            svc_counts[best, ids] += 1

    stats = None
    if forced is not None:
        r = np.asarray(regrets, dtype=np.float64)
        stats = {
            "mean_regret": float(r.mean()) if len(r) else 0.0,
            "p99_regret": float(np.percentile(r, 99)) if len(r) else 0.0,
            "greedy_match": greedy_hits / max(placed, 1),
            "feasible_in_order": 1.0 - infeasible_in_order / max(placed, 1),
            "placed": placed,
        }
    return out, stats


def scatter_rows_numpy(
    nodes: dict, idx: np.ndarray, rows: dict
) -> dict:
    """NumPy twin of ops.incremental._scatter_rows (the session's
    dirty-row commit): out-of-place fancy-index row replacement over a
    dict-of-arrays. Registered in ops/parity.py; parity pinned by
    tests/test_ktsan.py."""
    out = {}
    for k, arr in nodes.items():
        a = np.array(arr, copy=True)
        a[np.asarray(idx)] = np.asarray(rows[k])
        out[k] = a
    return out


def validate_assignment_numpy(snap: Snapshot, assignment) -> None:
    """Replay every placement against the snapshot's own predicate
    semantics in NumPy; raises AssertionError on any capacity /
    selector / port / volume / pin violation.

    This is the NumPy oracle twin for the approximate wave-family
    kernels (ops.wave.solve_waves, ops.sinkhorn.solve_sinkhorn_stats):
    they trade decision-ORDER parity for batching, so their invariant
    is placement VALIDITY, not destination equality — see
    tests/test_wave.py / tests/test_sinkhorn.py, which drive every
    fuzz case through this checker."""
    n = snap.nodes
    cpu_fit = n.cpu_fit_used.copy()
    mem_fit = n.mem_fit_used.copy()
    pods_used = n.pods_used.copy()
    uport = n.used_port_bits.copy()
    uvol_any = n.used_vol_any_bits.copy()
    uvol_rw = n.used_vol_rw_bits.copy()
    p = snap.pods
    sel_rows = p.sel_bits[p.selector_id]
    for i, j in enumerate(assignment):
        if j < 0:
            continue
        assert n.schedulable[j], f"pod {i} on unschedulable node {j}"
        assert not n.overcommitted[j], f"pod {i} on overcommitted node {j}"
        if p.zero_req[i]:
            assert pods_used[j] < n.pods_cap[j], f"pod {i}: count overflow"
        else:
            if n.cpu_cap[j] > 0:
                assert cpu_fit[j] + p.cpu_milli[i] <= n.cpu_cap[j], (
                    f"pod {i}: cpu overflow on node {j}"
                )
            if n.mem_cap[j] > 0:
                assert mem_fit[j] + p.mem_mib[i] <= n.mem_cap[j], (
                    f"pod {i}: mem overflow on node {j}"
                )
            assert pods_used[j] + 1 <= n.pods_cap[j], f"pod {i}: count"
        sel = sel_rows[i]
        assert ((sel & n.label_bits[j]) == sel).all(), f"pod {i}: selector"
        assert not (p.port_bits[i] & uport[j]).any(), f"pod {i}: port clash"
        assert not (
            (p.vol_rw_bits[i] & uvol_any[j]) | (p.vol_any_bits[i] & uvol_rw[j])
        ).any(), f"pod {i}: volume clash"
        pin = p.pinned_node[i]
        assert pin in (-1, j), f"pod {i}: pinned to {pin}, placed on {j}"
        cpu_fit[j] += p.cpu_milli[i]
        mem_fit[j] += p.mem_mib[i]
        pods_used[j] += 1
        uport[j] |= p.port_bits[i]
        uvol_any[j] |= p.vol_any_bits[i]
        uvol_rw[j] |= p.vol_rw_bits[i]

def capacity_report_numpy(
    cpu_cap,
    mem_cap,
    pods_cap,
    cpu_fit,
    mem_fit,
    pods_used,
    over,
    sched,
    probe_cpu,
    probe_mem,
    probe_min,
    probe_live,
):
    """Exact host twin of ops.capacity.capacity_report (KT006).

    Same float32 elementwise arithmetic, same int32-quantized
    reductions — cross-node/cross-probe sums are integer, so this twin
    matches the device kernel BIT-FOR-BIT (no tolerance), unlike the
    Go-semantics solve oracle above whose divergence is the signal.
    See tests/test_solver_parity.py TestCapacityParity."""
    from kubernetes_tpu.ops.capacity import BIG_FIT, FIT_CAP, FRAC_Q

    f32 = np.float32
    cpu_cap = np.asarray(cpu_cap, f32)
    mem_cap = np.asarray(mem_cap, f32)
    pods_cap = np.asarray(pods_cap, f32)
    cpu_fit = np.asarray(cpu_fit, f32)
    mem_fit = np.asarray(mem_fit, f32)
    pods_used = np.asarray(pods_used, f32)
    over = np.asarray(over, bool)
    sched = np.asarray(sched, bool)
    probe_cpu = np.asarray(probe_cpu, f32)
    probe_mem = np.asarray(probe_mem, f32)
    probe_min = np.asarray(probe_min, np.int32)
    probe_live = np.asarray(probe_live, bool)

    f0, f1, big = f32(0.0), f32(1.0), f32(BIG_FIT)
    live = sched & ~over
    livef = live.astype(f32)

    free_cpu = np.maximum(cpu_cap - cpu_fit, f0) * livef
    free_mem = np.maximum(mem_cap - mem_fit, f0) * livef
    free_pods = np.maximum(pods_cap - pods_used, f0) * livef

    def util(used_part, cap):
        return np.where(
            (cap > f0) & live,
            np.clip(used_part / np.maximum(cap, f1), f0, f1),
            f0,
        ).astype(f32)

    util_cpu = util(cpu_fit, cpu_cap)
    util_mem = util(mem_fit, mem_cap)
    util_pods = util(pods_used, pods_cap)

    pc = probe_cpu[:, None]
    pm = probe_mem[:, None]
    per_cpu = np.where(pc > f0, free_cpu[None, :] / np.maximum(pc, f1), big)
    per_mem = np.where(pm > f0, free_mem[None, :] / np.maximum(pm, f1), big)
    fit_frac = np.minimum(np.minimum(per_cpu, per_mem), free_pods[None, :])
    fit_frac = np.clip(fit_frac, f0, f32(FIT_CAP)).astype(f32)
    fit_int = np.floor(fit_frac).astype(np.int32)
    frac_milli = np.floor(fit_frac * f32(FRAC_Q)).astype(np.int32)

    plive = probe_live.astype(np.int32)
    usable = (fit_int.sum(axis=1, dtype=np.int32) * plive).astype(np.int32)
    potential = (
        frac_milli.sum(axis=1, dtype=np.int32) * plive
    ).astype(np.int32)
    headroom = usable
    frag = np.where(
        potential > 0,
        f1
        - (usable.astype(f32) * f32(FRAC_Q))
        / np.maximum(potential, 1).astype(f32),
        f0,
    ).astype(f32)
    frag = (np.clip(frag, f0, f1) * probe_live.astype(f32)).astype(f32)
    slice_ok = probe_live & (
        headroom >= np.maximum(probe_min, np.int32(1))
    )

    total_usable = np.int32(usable.sum(dtype=np.int32))
    total_potential = np.int32(potential.sum(dtype=np.int32))
    if total_potential > 0:
        frag_score = f32(
            f1 - (f32(total_usable) * f32(FRAC_Q)) / f32(total_potential)
        )
    else:
        frag_score = f0
    frag_score = f32(np.clip(frag_score, f0, f1))

    hosts_any = ((fit_int > 0) & probe_live[:, None]).any(axis=0)
    any_live_probe = bool(probe_live.any())
    stranded = (
        live
        & ((free_cpu > f0) | (free_mem > f0))
        & ~hosts_any
        & any_live_probe
    )

    def stranded_frac(free):
        free_i = free.astype(np.int32)
        tot = np.int32(free_i.sum(dtype=np.int32))
        strand = np.int32(
            (free_i * stranded.astype(np.int32)).sum(dtype=np.int32)
        )
        return f32(f32(strand) / f32(tot)) if tot > 0 else f0

    stranded_cpu = stranded_frac(free_cpu)
    stranded_mem = stranded_frac(free_mem)

    return (
        util_cpu,
        util_mem,
        util_pods,
        fit_int,
        headroom,
        frag,
        slice_ok,
        stranded,
        np.float32(frag_score),
        np.float32(stranded_cpu),
        np.float32(stranded_mem),
    )


def plan_moves_numpy(
    cpu_cap,
    mem_cap,
    pods_cap,
    cpu_fit,
    mem_fit,
    pods_used,
    over,
    sched,
    pod_cpu,
    pod_mem,
    pod_node,
    pod_live,
    pod_force,
    probe_cpu,
    probe_mem,
    probe_min,
    probe_live,
    move_budget,
):
    """Exact host twin of ops.rebalance.plan_moves (KT006).

    The device kernel's lax.scan written as the Python loop it is:
    same f32 elementwise arithmetic, same int32-quantized fits, same
    first-minimum argmin tie-break — bit-for-bit, no tolerance. See
    tests/test_solver_parity.py TestRebalanceParity."""
    from kubernetes_tpu.ops.capacity import BIG_FIT, FIT_CAP, FRAC_Q
    from kubernetes_tpu.ops.rebalance import NO_FIT_KEY

    f32 = np.float32
    cpu_cap = np.asarray(cpu_cap, f32)
    mem_cap = np.asarray(mem_cap, f32)
    pods_cap = np.asarray(pods_cap, f32)
    cf = np.asarray(cpu_fit, f32).copy()
    mf = np.asarray(mem_fit, f32).copy()
    pu = np.asarray(pods_used, f32).copy()
    over = np.asarray(over, bool)
    sched = np.asarray(sched, bool)
    pod_cpu = np.asarray(pod_cpu, f32)
    pod_mem = np.asarray(pod_mem, f32)
    pod_node = np.asarray(pod_node, np.int32)
    pod_live = np.asarray(pod_live, bool)
    pod_force = np.asarray(pod_force, bool)
    probe_cpu = np.asarray(probe_cpu, f32)
    probe_mem = np.asarray(probe_mem, f32)
    probe_live = np.asarray(probe_live, bool)
    budget = np.int32(np.asarray(move_budget))

    f0, f1, big = f32(0.0), f32(1.0), f32(BIG_FIT)
    live = sched & ~over
    livef = live.astype(f32)
    n = cpu_cap.shape[0]
    d = pod_cpu.shape[0]
    plive_i = probe_live.astype(np.int32)

    def free_vectors(cf, mf, pu):
        return (
            np.maximum(cpu_cap - cf, f0) * livef,
            np.maximum(mem_cap - mf, f0) * livef,
            np.maximum(pods_cap - pu, f0) * livef,
        )

    def frag_score(cf, mf, pu):
        free_cpu, free_mem, free_pods = free_vectors(cf, mf, pu)
        pc = probe_cpu[:, None]
        pm = probe_mem[:, None]
        per_cpu = np.where(pc > f0, free_cpu[None, :] / np.maximum(pc, f1), big)
        per_mem = np.where(pm > f0, free_mem[None, :] / np.maximum(pm, f1), big)
        fit_frac = np.minimum(np.minimum(per_cpu, per_mem), free_pods[None, :])
        fit_frac = np.clip(fit_frac, f0, f32(FIT_CAP)).astype(f32)
        fit_int = np.floor(fit_frac).astype(np.int32)
        frac_q = np.floor(fit_frac * f32(FRAC_Q)).astype(np.int32)
        usable = np.int32(
            (fit_int.sum(axis=1, dtype=np.int32) * plive_i).sum(dtype=np.int32)
        )
        potential = np.int32(
            (frac_q.sum(axis=1, dtype=np.int32) * plive_i).sum(dtype=np.int32)
        )
        if potential > 0:
            score = f32(f1 - (f32(usable) * f32(FRAC_Q)) / f32(potential))
        else:
            score = f0
        return f32(np.clip(score, f0, f1))

    def node_usable(fc, fm, fp):
        pcu = np.where(probe_cpu > f0, f32(fc) / np.maximum(probe_cpu, f1), big)
        pme = np.where(probe_mem > f0, f32(fm) / np.maximum(probe_mem, f1), big)
        ff = np.clip(np.minimum(np.minimum(pcu, pme), f32(fp)), f0, f32(FIT_CAP))
        return np.int32(
            (np.floor(ff).astype(np.int32) * plive_i).sum(dtype=np.int32)
        )

    score_before = frag_score(cf, mf, pu)

    dest = np.full(d, -1, np.int32)
    moved = np.zeros(d, bool)
    gain_out = np.zeros(d, np.int32)
    moves = np.int32(0)
    arange_n = np.arange(n, dtype=np.int32)
    for i in range(d):
        cpu, mem = pod_cpu[i], pod_mem[i]
        src = pod_node[i]
        free_cpu, free_mem, free_pods = free_vectors(cf, mf, pu)

        src_c = int(np.clip(src, 0, n - 1))
        src_valid = bool(0 <= src < n)
        is_src = (arange_n == np.int32(src_c)) & src_valid

        feasible = (
            live
            & (free_cpu >= cpu)
            & (free_mem >= mem)
            & (free_pods >= f1)
            & ~is_src
        )

        kc = np.where(cpu > f0, (free_cpu - cpu) / np.maximum(cpu, f1), big)
        km = np.where(mem > f0, (free_mem - mem) / np.maximum(mem, f1), big)
        key_frac = np.clip(np.minimum(kc, km), f0, f32(FIT_CAP)).astype(f32)
        key = np.floor(key_frac * f32(FRAC_Q)).astype(np.int32)
        key = np.where(feasible, key, np.int32(NO_FIT_KEY))
        dst = int(np.argmin(key))
        any_feasible = bool(feasible.any())

        src_live = src_valid and bool(live[src_c])
        if src_live:
            u_src_before = node_usable(
                free_cpu[src_c], free_mem[src_c], free_pods[src_c]
            )
            u_src_after = node_usable(
                max(f32(cpu_cap[src_c] - (cf[src_c] - cpu)), f0),
                max(f32(mem_cap[src_c] - (mf[src_c] - mem)), f0),
                max(f32(pods_cap[src_c] - (pu[src_c] - f1)), f0),
            )
        else:
            u_src_before = np.int32(0)
            u_src_after = np.int32(0)
        u_dst_before = node_usable(free_cpu[dst], free_mem[dst], free_pods[dst])
        u_dst_after = node_usable(
            max(f32(cpu_cap[dst] - (cf[dst] + cpu)), f0),
            max(f32(mem_cap[dst] - (mf[dst] + mem)), f0),
            max(f32(pods_cap[dst] - (pu[dst] + f1)), f0),
        )
        gain = np.int32(
            (u_src_after + u_dst_after) - (u_src_before + u_dst_before)
        )

        commit = bool(
            pod_live[i]
            and any_feasible
            and moves < budget
            and (gain > 0 or bool(pod_force[i]))
        )
        if commit:
            cf[dst] = f32(cf[dst] + cpu)
            mf[dst] = f32(mf[dst] + mem)
            pu[dst] = f32(pu[dst] + f1)
            if src_valid:
                cf[src_c] = f32(cf[src_c] - cpu)
                mf[src_c] = f32(mf[src_c] - mem)
                pu[src_c] = f32(pu[src_c] - f1)
            moves = np.int32(moves + 1)
            dest[i] = dst
            moved[i] = True
            gain_out[i] = gain

    score_after = frag_score(cf, mf, pu)
    return (
        dest,
        moved,
        gain_out,
        np.int32(moves),
        np.float32(score_before),
        np.float32(score_after),
    )
