"""Host snapshot -> device arrays.

Uploads the columnar Snapshot (kubernetes_tpu.models.columnar) to the
accelerator, optionally sharding every node-axis array over a
jax.sharding.Mesh axis ("nodes"). Pod-axis arrays are replicated: the
solver scans over pods, so each step broadcasts one pod against the
sharded node state (the TPU analog of the reference's
pod-at-a-time loop against the full cluster).

Shapes are padded to multiples of `pad_to` so repeated solves with
slightly different cluster sizes reuse the compiled executable
(XLA static-shape requirement; SURVEY.md hard part (d)).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.models.columnar import Snapshot

# Services a single pod can belong to on device (top-K id list; the
# dense membership row stays host-side). Pods matching more than
# SVC_K services contribute only their first SVC_K — far beyond any
# realistic overlap.
SVC_K = 8


def member_rows_to_ids(member: np.ndarray, k: int = SVC_K) -> np.ndarray:
    """Dense multi-hot f32[P, S] -> first-k indices i32[P, k], -1 pad."""
    P = member.shape[0]
    ids = np.full((P, k), -1, dtype=np.int32)
    if P == 0:
        return ids
    rows, cols = np.nonzero(member)
    if len(rows) == 0:
        return ids
    first = np.searchsorted(rows, np.arange(P), side="left")
    pos = np.arange(len(rows)) - first[rows]
    keep = pos < k
    ids[rows[keep], pos[keep]] = cols[keep]
    return ids


def _pad(arr: np.ndarray, n: int, fill=0) -> np.ndarray:
    """Pad axis 0 to length n."""
    if arr.shape[0] == n:
        return arr
    pad_width = [(0, n - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m if x > 0 else m


@dataclass
class DeviceSnapshot:
    """Device-resident scheduling problem. `pods`/`nodes` are dicts of
    jnp arrays; padded entries are masked off (pods: pinned == -2 never
    fits anywhere; nodes: schedulable == False)."""

    pods: Dict[str, jnp.ndarray]
    nodes: Dict[str, jnp.ndarray]
    n_pods: int  # real (unpadded) counts
    n_nodes: int

    @property
    def pod_count_padded(self) -> int:
        return int(self.pods["cpu"].shape[0])

    @property
    def node_count_padded(self) -> int:
        return int(self.nodes["cpu_cap"].shape[0])


def device_snapshot(
    snap: Snapshot,
    mesh: Optional[jax.sharding.Mesh] = None,
    node_axis: str = "nodes",
    pad_to: int = 128,
) -> DeviceSnapshot:
    P, N = snap.pods.count, snap.nodes.count
    PP = _round_up(P, pad_to)
    # The node axis must divide evenly across mesh shards.
    node_mult = pad_to
    if mesh is not None:
        node_mult = max(pad_to, int(np.prod([mesh.shape[a] for a in mesh.axis_names])))
    NP = _round_up(N, node_mult)

    p = snap.pods
    sel_rows = p.sel_bits[p.selector_id] if P else np.zeros((0, p.sel_bits.shape[1]), np.uint32)
    pods = {
        "cpu": _pad(p.cpu_milli, PP),
        "mem": _pad(p.mem_mib, PP),
        "zero_req": _pad(p.zero_req, PP, fill=False),
        "sel": _pad(sel_rows, PP),
        "port": _pad(p.port_bits, PP),
        "vol_any": _pad(p.vol_any_bits, PP),
        "vol_rw": _pad(p.vol_rw_bits, PP),
        # Padding pods are pinned to -2 (an impossible node) so they
        # always come back unassigned.
        "pinned": _pad(p.pinned_node, PP, fill=-2),
        "svc": _pad(p.service_id, PP, fill=-1),
        "svc_ids": _pad(member_rows_to_ids(p.svc_member), PP, fill=-1),
    }
    n = snap.nodes
    nodes = {
        "cpu_cap": _pad(n.cpu_cap, NP),
        "mem_cap": _pad(n.mem_cap, NP),
        "pods_cap": _pad(n.pods_cap, NP),
        "cpu_fit": _pad(n.cpu_fit_used, NP),
        "mem_fit": _pad(n.mem_fit_used, NP),
        "over": _pad(n.overcommitted, NP, fill=False),
        "cpu_used": _pad(n.cpu_used, NP),
        "mem_used": _pad(n.mem_used, NP),
        "pods_used": _pad(n.pods_used, NP),
        "labels": _pad(n.label_bits, NP),
        "uport": _pad(n.used_port_bits, NP),
        "uvol_any": _pad(n.used_vol_any_bits, NP),
        "uvol_rw": _pad(n.used_vol_rw_bits, NP),
        "svc_counts": _pad(n.service_counts, NP),
        # Padding nodes are unschedulable -> never chosen.
        "sched": _pad(n.schedulable, NP, fill=False),
    }

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as PS

        node_sharding = NamedSharding(mesh, PS(node_axis))
        repl = NamedSharding(mesh, PS())
        nodes = {
            k: jax.device_put(v, node_sharding) for k, v in nodes.items()
        }
        pods = {k: jax.device_put(v, repl) for k, v in pods.items()}
    else:
        nodes = {k: jnp.asarray(v) for k, v in nodes.items()}
        pods = {k: jnp.asarray(v) for k, v in pods.items()}

    return DeviceSnapshot(pods=pods, nodes=nodes, n_pods=P, n_nodes=N)
