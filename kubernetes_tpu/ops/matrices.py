"""Host snapshot -> device arrays.

Uploads the columnar Snapshot (kubernetes_tpu.models.columnar) to the
accelerator, optionally sharding every node-axis array over a
jax.sharding.Mesh axis ("nodes"). Pod-axis arrays are replicated: the
solver scans over pods, so each step broadcasts one pod against the
sharded node state (the TPU analog of the reference's
pod-at-a-time loop against the full cluster).

Shapes are padded to multiples of `pad_to` so repeated solves with
slightly different cluster sizes reuse the compiled executable
(XLA static-shape requirement; SURVEY.md hard part (d)).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.models.algspec import DEFAULT_LOWERED, LoweredSpec
from kubernetes_tpu.models.columnar import SVC_K, Snapshot  # noqa: F401
from kubernetes_tpu.ops.ledger import traced_jit
# (SVC_K re-exported: device consumers import it from here.)


def _pad(arr: np.ndarray, n: int, fill=0) -> np.ndarray:
    """Pad axis 0 to length n."""
    if arr.shape[0] == n:
        return arr
    pad_width = [(0, n - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad_width, constant_values=fill)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m if x > 0 else m


def pow2_bucket(n: int, minimum: int = 128) -> int:
    """Next power-of-two bucket >= n (>= minimum). Canonical copy —
    incremental.py's session sizing uses this same helper."""
    b = minimum
    while b < n:
        b *= 2
    return b


def _pod_axis_bucket(n: int, minimum: int) -> int:
    """Pod-axis padding target: power-of-two buckets up to 8192, then
    multiples of 1024. A scheduler daemon's drain sizes vary with
    arrival timing, and every distinct padded shape is a fresh XLA
    compile (seconds each) — pow2 bucketing caps the daemon at ~7
    executables total, while huge offline solves (50k backlog) stay
    within ~2% padding waste on the scan's sequential steps."""
    if n <= 8192:
        return pow2_bucket(n, minimum)
    return _round_up(n, 1024)


def _pad_cols(arr: np.ndarray, m: int) -> np.ndarray:
    """Pad axis 1 up to a multiple of m (shape-bucketing for the minor
    dims: bitset word counts and the service axis drift with snapshot
    vocabularies, and every distinct shape is a fresh XLA executable)."""
    cols = arr.shape[1]
    target = _round_up(cols, m)
    if cols == target:
        return arr
    return np.pad(arr, [(0, 0), (0, target - cols)])


def _put_tree(arrs: Dict[str, np.ndarray], sharding) -> Dict[str, jnp.ndarray]:
    """ONE device_put for a whole dict of arrays, not one per array:
    each call pays a dispatch round-trip, and on a tunneled device
    10-16 small transfers per upload put that many RTTs on the
    pipelined solve's critical path. All-zero leaves (a fresh
    backlog's occupancy matrices — svc_counts alone is N x S f32
    ~10 MB at 5k x 500) materialize directly on device instead of
    shipping zeros through the tunnel."""
    zeros = {k: v for k, v in arrs.items() if v.size > 4096 and not v.any()}
    rest = {k: v for k, v in arrs.items() if k not in zeros}
    if rest:
        # Transfer SLI (utils/sli.py): what actually ships host->device
        # — the all-zero leaves materialize on device and move nothing.
        from kubernetes_tpu.utils import sli

        sli.note_transfer("h2d", sli.nbytes_of(rest))
    out = dict(jax.device_put(rest, sharding)) if rest else {}
    for k, v in zeros.items():
        out[k] = jnp.zeros(v.shape, dtype=v.dtype, device=sharding)
    return out


@dataclass
class DeviceSnapshot:
    """Device-resident scheduling problem. `pods`/`nodes` are dicts of
    jnp arrays; padded entries are masked off (pods: pinned == -2 never
    fits anywhere; nodes: schedulable == False)."""

    pods: Dict[str, jnp.ndarray]
    nodes: Dict[str, jnp.ndarray]
    n_pods: int  # real (unpadded) counts
    n_nodes: int
    # Policy lowering riding along (defaults = the stock pipeline).
    lowered: LoweredSpec = DEFAULT_LOWERED
    weights: Tuple[int, int, int] = (1, 1, 1)

    @property
    def pod_count_padded(self) -> int:
        return int(self.pods["cpu"].shape[0])

    @property
    def node_count_padded(self) -> int:
        return int(self.nodes["cpu_cap"].shape[0])


# Bucket minor dims: bitset widths to pairs of u32 words, the service
# axis to 128 — so vocab drift between snapshots reuses the compiled
# executable instead of triggering a fresh XLA compile.
WORD_BUCKET, SVC_BUCKET = 2, 128


def device_pods(
    p,
    sharding,
    pad_to: int = 128,
) -> Dict[str, jnp.ndarray]:
    """PodColumns -> device dict (padded axis 0 to a pad_to multiple)."""
    P = p.count
    PP = _pod_axis_bucket(P, pad_to)
    sel_rows = (
        p.sel_bits[p.selector_id]
        if P
        else np.zeros((0, p.sel_bits.shape[1]), np.uint32)
    )
    pods = {
        "cpu": _pad(p.cpu_milli, PP),
        "mem": _pad(p.mem_mib, PP),
        "zero_req": _pad(p.zero_req, PP, fill=False),
        "sel": _pad(_pad_cols(sel_rows, WORD_BUCKET), PP),
        "port": _pad(_pad_cols(p.port_bits, WORD_BUCKET), PP),
        "vol_any": _pad(_pad_cols(p.vol_any_bits, WORD_BUCKET), PP),
        "vol_rw": _pad(_pad_cols(p.vol_rw_bits, WORD_BUCKET), PP),
        # Padding pods are pinned to -2 (an impossible node) so they
        # always come back unassigned.
        "pinned": _pad(p.pinned_node, PP, fill=-2),
        "svc": _pad(p.service_id, PP, fill=-1),
        "svc_ids": _pad(p.svc_topk, PP, fill=-1),
    }
    if p.aff_pin is not None:
        # Padded pods are already pinned to -2 (never placed); -1 here
        # just means "no pinned affinity value".
        pods["aff_pin"] = _pad(p.aff_pin, PP, fill=-1)
    return _put_tree(pods, sharding)


def device_nodes(
    n,
    sharding,
    pad_to: int = 128,
    node_mult: Optional[int] = None,
) -> Dict[str, jnp.ndarray]:
    """NodeColumns -> device dict (padded so the node axis divides
    evenly across mesh shards)."""
    N = n.count
    NP = _round_up(N, node_mult or pad_to)
    nodes = {
        "cpu_cap": _pad(n.cpu_cap, NP),
        "mem_cap": _pad(n.mem_cap, NP),
        "pods_cap": _pad(n.pods_cap, NP),
        "cpu_fit": _pad(n.cpu_fit_used, NP),
        "mem_fit": _pad(n.mem_fit_used, NP),
        "over": _pad(n.overcommitted, NP, fill=False),
        "cpu_used": _pad(n.cpu_used, NP),
        "mem_used": _pad(n.mem_used, NP),
        "pods_used": _pad(n.pods_used, NP),
        "labels": _pad(_pad_cols(n.label_bits, WORD_BUCKET), NP),
        "uport": _pad(_pad_cols(n.used_port_bits, WORD_BUCKET), NP),
        "uvol_any": _pad(_pad_cols(n.used_vol_any_bits, WORD_BUCKET), NP),
        "uvol_rw": _pad(_pad_cols(n.used_vol_rw_bits, WORD_BUCKET), NP),
        "svc_counts": _pad(_pad_cols(n.service_counts, SVC_BUCKET), NP),
        # Padding nodes are unschedulable -> never chosen.
        "sched": _pad(n.schedulable, NP, fill=False),
    }
    # Policy-spec columns (padding nodes are unschedulable, so fills
    # only need to be type-safe, not semantically meaningful).
    if n.policy_ok is not None:
        nodes["policy_ok"] = _pad(n.policy_ok, NP, fill=False)
    if n.static_prio is not None:
        nodes["static_prio"] = _pad(n.static_prio, NP)
    if n.aff_vid is not None:
        nodes["aff_vid"] = _pad(n.aff_vid, NP, fill=-1)
    if n.aa_zone is not None:
        nodes["aa_zone"] = _pad(n.aa_zone, NP, fill=-1)
    return _put_tree(nodes, sharding)


#: Predicate bit positions in the explain readback's packed per-node
#: failure mask (ops.solver.explain_rows; bit set = the predicate
#: REJECTED the node). Order is the solver's evaluation order; names
#: match the reference FitPredicate names operators already know from
#: FailedScheduling events (plugin/pkg/scheduler/factory/plugins.go) —
#: plus NodeSchedulable, the reference's ready/unschedulable node
#: filter that runs before predicates (factory.go:166,209).
EXPLAIN_PREDICATES = (
    "NodeSchedulable",
    "PodFitsResources",
    "MatchNodeSelector",
    "PodFitsPorts",
    "NoDiskConflict",
    "HostName",
)


def decode_predicate_bits(bits: int) -> list:
    """Failed-predicate names for one node's packed verdict mask."""
    return [
        name
        for i, name in enumerate(EXPLAIN_PREDICATES)
        if bits & (1 << i)
    ]


@traced_jit(static_argnames=("num_groups",))
def gang_member_counts(
    placed: jnp.ndarray, group_ids: jnp.ndarray, num_groups: int
) -> jnp.ndarray:
    """Per-group placed-member counts as a MASKED segment reduction —
    the gang-acceptance primitive. `placed` is bool[P] (pod i received
    a feasible assignment), `group_ids` int32[P] with -1 for ungrouped
    and padding rows. Ungrouped/padded rows are masked out of the sum
    rather than filtered (static shapes: the solver's pod axis is
    padded, and XLA recompiles on any shape change). Callers bucket
    num_groups (it is a static arg) so group-count drift between
    batches reuses the compiled executable."""
    mask = placed & (group_ids >= 0)
    idx = jnp.clip(group_ids, 0, num_groups - 1)
    return jax.ops.segment_sum(
        mask.astype(jnp.int32), idx, num_segments=num_groups
    )


def node_axis_multiple(
    mesh: Optional[jax.sharding.Mesh], pad_to: int = 128
) -> int:
    """Node-axis padding multiple: must divide evenly across mesh shards."""
    if mesh is None:
        return pad_to
    return max(pad_to, int(np.prod([mesh.shape[a] for a in mesh.axis_names])))


def host_mesh(
    n: int, axis: str = "nodes"
) -> Optional[jax.sharding.Mesh]:
    """The sanctioned mesh constructor for the kernel layer: a 1-D mesh
    over the first `n` visible devices, or None when a mesh is not
    viable (n < 2, or fewer than n devices — e.g. a host platform that
    was not forced to multiple CPU devices). Sessions, the
    KT_MESH_DEVICES escape hatch, and test fixtures all route through
    here so ops/ shares one topology (KT009 flags ad-hoc Mesh
    construction elsewhere in the package)."""
    if n < 2:
        return None
    devices = jax.devices()
    if len(devices) < n:
        return None
    return jax.sharding.Mesh(np.asarray(devices[:n]), axis_names=(axis,))


def shardings_for(mesh: Optional[jax.sharding.Mesh], node_axis: str = "nodes"):
    """(node_sharding, pod_sharding) for a mesh (or the default device)."""
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as PS

        return NamedSharding(mesh, PS(node_axis)), NamedSharding(mesh, PS())
    # The ONE sanctioned default-device read in ops/ (no-mesh staging).
    # ktlint: disable=KT009
    device = jax.devices()[0]
    return device, device


def device_snapshot(
    snap: Snapshot,
    mesh: Optional[jax.sharding.Mesh] = None,
    node_axis: str = "nodes",
    pad_to: int = 128,
) -> DeviceSnapshot:
    node_mult = node_axis_multiple(mesh, pad_to)
    node_sharding, pod_sharding = shardings_for(mesh, node_axis)
    nodes = device_nodes(
        snap.nodes, node_sharding, pad_to=pad_to, node_mult=node_mult
    )
    if snap.anchor_init is not None:
        # ServiceAffinity/AntiAffinity carry seeds: service-axis state
        # sized to the padded svc_counts column count PLUS one scratch
        # slot (the last index), which absorbs -1-padded svc_ids
        # scatters in the solver commit. Replicated, not node-sharded.
        SP = _round_up(max(snap.anchor_init.shape[0], 1), SVC_BUCKET)
        anchor = np.full(SP + 1, -1, dtype=np.int32)
        anchor[: snap.anchor_init.shape[0]] = snap.anchor_init
        total = np.zeros(SP + 1, dtype=np.float32)
        total[: snap.svc_total_init.shape[0]] = snap.svc_total_init
        nodes["anchor"] = jax.device_put(anchor, pod_sharding)
        nodes["svc_total"] = jax.device_put(total, pod_sharding)
    return DeviceSnapshot(
        pods=device_pods(snap.pods, pod_sharding, pad_to=pad_to),
        nodes=nodes,
        n_pods=snap.pods.count,
        n_nodes=snap.nodes.count,
        lowered=snap.lowered or DEFAULT_LOWERED,
        weights=snap.weights or (1, 1, 1),
    )
