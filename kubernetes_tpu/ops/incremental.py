"""Incremental solver session: device-resident cluster state + churn.

BASELINE.md config 5 (50k-pod churn replay at 1k pods/s) cannot afford
re-lowering and re-uploading the full pod x node problem every tick.
This session keeps the NODE state (occupancy, bitsets, service counts
— the big, long-lived half of the problem) resident on the
accelerator:

- solve() feeds the pending backlog through solve_with_state, whose
  DONATED node carry becomes the next tick's device state — bindings
  commit on device with zero host round-trip of node columns;
- pod deletions touch one node row each: the host mirror recomputes
  that row (greedy-fit order, reference MapPodsToMachines semantics)
  and a jitted scatter patches just those rows on device;
- pending pods are transient per tick and upload as small bucketed
  arrays (bucket sizes limit XLA recompiles; SURVEY.md hard part (d)).

Vocabularies (labels / hostPorts / volumes) and the service set are
frozen at session start with headroom; overflow raises RebuildRequired
and the owner builds a fresh session (cheap resync — the host store
stays the source of truth, SURVEY.md §5 checkpoint model).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.models.columnar import (
    MIB,
    ServiceMatcher,
    Vocab,
    bitset,
    mem_to_mib_ceil,
    node_is_ready,
    pod_host_ports,
    pod_key,
    pod_resource_limits,
    pod_volumes,
)
from kubernetes_tpu.models.objects import (
    REBALANCE_DEST_ANNOTATION,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    Node,
    Pod,
    Service,
)
from kubernetes_tpu.ops.ledger import traced_jit
from kubernetes_tpu.ops.matrices import SVC_K
from kubernetes_tpu.ops.solver import DEFAULT_WEIGHTS, solve_with_state


class RebuildRequired(Exception):
    """Capacity (vocab words / node slots / services) exhausted — build
    a fresh session from the authoritative host store."""


from kubernetes_tpu.ops.matrices import pow2_bucket as _bucket  # noqa: E402


@traced_jit(donate_argnames=("nodes",))
def _scatter_rows(nodes: Dict[str, jnp.ndarray], idx: jnp.ndarray, rows: Dict):
    return {k: nodes[k].at[idx].set(rows[k]) for k in nodes}


@dataclass
class SessionGang:
    """One PodGroup's stake in a session tick (ops-layer mirror of
    scheduler.gang.GangGroup, keyed by pod keys instead of backlog
    indices — the session addresses pods by key)."""

    key: str  # "namespace/name"
    min_member: int
    bound: int  # members already bound before this tick
    pod_keys: frozenset  # this tick's pending members


class PendingSolve:
    """One in-flight session tick: the jitted solve has been DISPATCHED
    (async — no host sync) and the assignment's device->host copy is
    already streaming (`copy_to_host_async`). ``result()`` blocks on
    the readback, applies the host-mirror commits, and returns the
    same ``[(pod_key, node_name | None)]`` list ``solve()`` does.

    The overlap contract: between dispatch and ``result()`` the owner
    may freely ``add_pending`` (next tick's staging), apply node/pod
    deltas (``upsert_node``/``delete_assigned``/``add_assigned`` — row
    recomputes miss the in-flight placements, but ``result()`` re-adds
    them incrementally, so recompute-then-apply converges to the same
    rows), and do arbitrary host work (bind commits, HTTP). Only the
    next dirty-row flush / solve dispatch requires the tick to finish
    first — ``solve_async`` resolves any outstanding handle itself."""

    __slots__ = (
        "_session", "pending", "assignment", "tele",
        "dispatch_s", "block_s", "dispatched_mono", "resolved_mono",
        "_done", "_result",
    )

    def __init__(self, session, pending, assignment, tele, dispatch_s):
        self._session = session
        self.pending = pending
        self.assignment = assignment
        self.tele = tele  # (waves, sinkhorn_iters, sinkhorn_residual)
        self.dispatch_s = dispatch_s
        self.block_s = 0.0
        # Duty-cycle accounting (utils/profiler.py): the in-flight
        # window is dispatched_mono -> resolved_mono; block_s of it is
        # host time spent blocked in result().
        self.dispatched_mono = time.monotonic()
        self.resolved_mono = 0.0
        self._done = assignment is None
        self._result: List[Tuple[str, Optional[str]]] = []

    @property
    def keys(self) -> List[str]:
        """Pod keys of the in-flight tick (placement unknown until
        result()): owners use these to avoid re-staging a pod whose
        first solve has not landed yet."""
        return [lp.key for lp in self.pending]

    def done(self) -> bool:
        return self._done

    def result(self) -> List[Tuple[str, Optional[str]]]:
        if not self._done:
            self._session._finish_solve(self)
        return self._result


@dataclass
class _LoweredPod:
    """Host-side lowered pod row (everything solve() needs)."""

    key: str
    cpu: float
    mem_mib: float
    zero_req: bool
    sel_ids: List[int]
    port_ids: List[int]
    vol_any_ids: List[int]
    vol_rw_ids: List[int]
    # Pinned NODE NAME ("" = unpinned): resolved to a slot index at
    # solve() time — slot indices are recycled across node churn, so an
    # index resolved at add time could point at a different node.
    pinned_name: str
    svc: int
    # Top-SVC_K matching service ids — the exact set the device commit
    # scatters (solver._commit). Host mirrors MUST use this truncated
    # set, not the dense membership row: a pod matching > SVC_K
    # services would otherwise diverge host vs device (advisor r1).
    svc_topk: np.ndarray = field(default_factory=lambda: np.zeros(0, np.int64))
    # Soft pin (rebalance nomination, not spec.nodeName): an unknown
    # destination resolves to UNPINNED (-1) instead of infeasible (-2)
    # — a dest node that vanished mid-move must not strand the pod.
    pin_soft: bool = False


class SolverSession:
    """Long-lived incremental scheduling session over one cluster."""

    def __init__(
        self,
        nodes: Sequence[Node],
        services: Sequence[Service] = (),
        assigned: Sequence[Pod] = (),
        label_words: int = 4,
        port_words: int = 4,
        vol_words: int = 4,
        node_capacity: int = 0,
        weights: Tuple[int, int, int] = DEFAULT_WEIGHTS,
        mesh=None,
        mode: str = "scan",
        pod_bucket: int = 0,
    ):
        nodes = list(nodes)
        self.services = list(services)
        self.weights = tuple(weights)
        self.mesh = mesh
        # Tick solver: "scan" replays the sequential-parity policy;
        # "wave"/"sinkhorn" batch each tick's backlog (same windowed
        # commit machinery as the batch modes — ops.wave/ops.sinkhorn).
        if mode not in ("scan", "wave", "sinkhorn"):
            raise ValueError(f"unknown session mode {mode!r}")
        self.mode = mode
        # pod_bucket > 0 pads every tick's pending upload to AT LEAST
        # this bucket: ONE compiled executable instead of one per
        # power-of-2 batch size. Long-lived daemons under churn want
        # this — a fresh pow2 bucket mid-workload stalls the tick for
        # a full XLA compile (minutes on CPU hosts).
        self.pod_bucket = pod_bucket
        self.LW, self.PW, self.VW = label_words, port_words, vol_words
        self.S = max(1, len(self.services))
        self._matcher = ServiceMatcher(self.services)
        self.N_cap = _bucket(max(node_capacity, len(nodes), 1))
        self.label_vocab, self.port_vocab, self.vol_vocab = Vocab(), Vocab(), Vocab()

        self.node_names: List[Optional[str]] = [None] * self.N_cap
        self.node_index: Dict[str, int] = {}
        # Assigned pods per node slot, in arrival order (greedy-fit
        # recompute on delete follows this order, as the reference's
        # MapPodsToMachines list order does).
        self._assigned: List[List[_LoweredPod]] = [[] for _ in range(self.N_cap)]
        self._pod_node: Dict[str, int] = {}
        self._node_specs: List[Optional[Node]] = [None] * self.N_cap

        self.h = self._empty_node_columns()
        for node in nodes:
            self._admit_node(node)
        for pod in assigned:
            lp = self._lower_pod(pod)
            j = self.node_index.get(pod.spec.node_name)
            if j is None:
                continue
            self._assigned[j].append(lp)
            self._pod_node[lp.key] = j
        for j in range(self.N_cap):
            if self.node_names[j] is not None:
                self._recompute_node_row(j)

        self._pending: List[_LoweredPod] = []
        self.dev = self._upload_all()
        self._dirty: set = set()
        # Convergence telemetry of the most recent solve() tick — the
        # incremental daemon folds this into its SolveRecord.
        self.last_stats: Dict[str, float] = {}
        # Pipelined dispatch state: the (at most one) in-flight tick,
        # plus double-buffered host staging arrays — tick k+1's pod
        # staging must never overwrite buffers whose device transfer
        # for tick k may still be draining (device_put is async).
        self._inflight: Optional[PendingSolve] = None
        self._stage_bufs: Tuple[Dict, Dict] = ({}, {})
        self._stage_flip = 0

    # -- lowering -----------------------------------------------------

    def _vocab_id(self, vocab: Vocab, words: int, token: str) -> int:
        i = vocab.id(token)
        if i >= words * 32:
            raise RebuildRequired(f"vocab overflow: {token!r}")
        return i

    def _lower_pod(self, pod: Pod) -> _LoweredPod:
        cpu, mem = pod_resource_limits(pod)
        sel_ids = [
            self._vocab_id(self.label_vocab, self.LW, f"{k}={v}")
            for k, v in sorted((pod.spec.node_selector or {}).items())
        ]
        port_ids = [
            self._vocab_id(self.port_vocab, self.PW, str(p))
            for p in pod_host_ports(pod)
        ]
        vols = pod_volumes(pod)
        vol_any = [self._vocab_id(self.vol_vocab, self.VW, v) for v, _ in vols]
        vol_rw = [self._vocab_id(self.vol_vocab, self.VW, v) for v, rw in vols if rw]
        ids, first = self._matcher.membership_ids(pod)
        # Rebalance nomination: mirror models/columnar.py — a pod the
        # descheduler recreated after a defrag eviction carries its
        # planned destination as an annotation; honor it as a soft
        # HostName pin so the incremental daemon rebinds it there
        # (without this, the solver happily re-packs the mover onto
        # the very node the defrag cycle just drained).
        pinned_name = pod.spec.node_name or ""
        pin_soft = False
        if not pinned_name:
            pinned_name = (pod.metadata.annotations or {}).get(
                REBALANCE_DEST_ANNOTATION, ""
            )
            pin_soft = bool(pinned_name)
        return _LoweredPod(
            svc_topk=ids[:SVC_K],
            key=pod_key(pod),
            cpu=float(cpu),
            mem_mib=float(mem_to_mib_ceil(mem)),
            zero_req=(cpu == 0 and mem == 0),
            sel_ids=sel_ids,
            port_ids=port_ids,
            vol_any_ids=vol_any,
            vol_rw_ids=vol_rw,
            pinned_name=pinned_name,
            pin_soft=pin_soft,
            svc=first,
        )

    # -- node columns (host mirror) -----------------------------------

    def _empty_node_columns(self) -> Dict[str, np.ndarray]:
        N = self.N_cap
        return {
            "cpu_cap": np.zeros(N, np.float32),
            "mem_cap": np.zeros(N, np.float32),
            "pods_cap": np.zeros(N, np.float32),
            "cpu_fit": np.zeros(N, np.float32),
            "mem_fit": np.zeros(N, np.float32),
            "over": np.zeros(N, bool),
            "cpu_used": np.zeros(N, np.float32),
            "mem_used": np.zeros(N, np.float32),
            "pods_used": np.zeros(N, np.float32),
            "labels": np.zeros((N, self.LW), np.uint32),
            "uport": np.zeros((N, self.PW), np.uint32),
            "uvol_any": np.zeros((N, self.VW), np.uint32),
            "uvol_rw": np.zeros((N, self.VW), np.uint32),
            "svc_counts": np.zeros((N, self.S), np.float32),
            "sched": np.zeros(N, bool),
        }

    def _admit_node(self, node: Node) -> int:
        name = node.metadata.name
        j = self.node_index.get(name)
        if j is None:
            try:
                j = self.node_names.index(None)
            except ValueError:
                raise RebuildRequired("node slots exhausted")
            self.node_names[j] = name
            self.node_index[name] = j
        self._node_specs[j] = node
        return j

    def _recompute_node_row(self, j: int) -> None:
        """Rebuild slot j's full row from spec + assigned pods (the
        only non-monotonic operation: deletes can't be expressed as
        bitset increments)."""
        node = self._node_specs[j]
        h = self.h
        for k in h:
            h[k][j] = 0
        if node is None:
            return
        cap = node.status.capacity or {}
        if RESOURCE_CPU in cap:
            h["cpu_cap"][j] = cap[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in cap:
            h["mem_cap"][j] = cap[RESOURCE_MEMORY].value() // MIB
        if RESOURCE_PODS in cap:
            h["pods_cap"][j] = cap[RESOURCE_PODS].value()
        h["labels"][j] = bitset(
            [
                self._vocab_id(self.label_vocab, self.LW, f"{k}={v}")
                for k, v in (node.metadata.labels or {}).items()
            ],
            self.LW,
        )
        h["sched"][j] = node_is_ready(node)
        for lp in self._assigned[j]:
            # Greedy-fit order = arrival order (reference semantics).
            fits_cpu = h["cpu_cap"][j] == 0 or (
                h["cpu_fit"][j] + lp.cpu <= h["cpu_cap"][j]
            )
            fits_mem = h["mem_cap"][j] == 0 or (
                h["mem_fit"][j] + lp.mem_mib <= h["mem_cap"][j]
            )
            if fits_cpu and fits_mem:
                h["cpu_fit"][j] += lp.cpu
                h["mem_fit"][j] += lp.mem_mib
            else:
                h["over"][j] = True
            h["cpu_used"][j] += lp.cpu
            h["mem_used"][j] += lp.mem_mib
            h["pods_used"][j] += 1
            h["uport"][j] |= bitset(lp.port_ids, self.PW)
            h["uvol_any"][j] |= bitset(lp.vol_any_ids, self.VW)
            h["uvol_rw"][j] |= bitset(lp.vol_rw_ids, self.VW)
            if len(lp.svc_topk):
                h["svc_counts"][j, lp.svc_topk] += 1.0

    def _apply_commit_host(self, j: int, lp: _LoweredPod) -> None:
        """Mirror of solver._commit — keeps host state bit-identical to
        the device carry for nodes untouched by deletes."""
        h = self.h
        h["cpu_fit"][j] += lp.cpu
        h["mem_fit"][j] += lp.mem_mib
        h["cpu_used"][j] += lp.cpu
        h["mem_used"][j] += lp.mem_mib
        h["pods_used"][j] += 1
        h["uport"][j] |= bitset(lp.port_ids, self.PW)
        h["uvol_any"][j] |= bitset(lp.vol_any_ids, self.VW)
        h["uvol_rw"][j] |= bitset(lp.vol_rw_ids, self.VW)
        if len(lp.svc_topk):
            h["svc_counts"][j, lp.svc_topk] += 1.0

    # -- device transfer ----------------------------------------------

    def _upload_all(self) -> Dict[str, jnp.ndarray]:
        from kubernetes_tpu.utils import sli

        sli.note_transfer("h2d", sli.nbytes_of(self.h))
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as PS

            sharding = NamedSharding(self.mesh, PS("nodes"))
            return {k: jax.device_put(v, sharding) for k, v in self.h.items()}
        return {k: jnp.asarray(v) for k, v in self.h.items()}

    def _flush_dirty(self) -> None:
        if not self._dirty:
            return
        idx = sorted(self._dirty)
        self._dirty.clear()
        # Bucket the scatter width: pad by repeating the last index
        # (identical row, harmless duplicate) so recompiles are rare.
        width = _bucket(len(idx), minimum=8)
        padded = idx + [idx[-1]] * (width - len(idx))
        rows = {k: self.h[k][padded] for k in self.h}
        from kubernetes_tpu.utils import sli

        sli.note_transfer("h2d", sli.nbytes_of(rows))
        self.dev = _scatter_rows(
            self.dev, jnp.asarray(padded, dtype=jnp.int32), rows
        )

    # -- public API ---------------------------------------------------

    def add_pending(self, pod: Pod) -> None:
        self._pending.append(self._lower_pod(pod))

    def pending_count(self) -> int:
        return len(self._pending)

    def upsert_node(self, node: Node) -> None:
        j = self._admit_node(node)
        self._recompute_node_row(j)
        self._dirty.add(j)

    def remove_node(self, name: str) -> None:
        j = self.node_index.pop(name, None)
        if j is None:
            return
        self.node_names[j] = None
        self._node_specs[j] = None
        for lp in self._assigned[j]:
            self._pod_node.pop(lp.key, None)
        self._assigned[j] = []
        self._recompute_node_row(j)  # zeroes the row; sched stays False
        self._dirty.add(j)

    def add_assigned(self, pod: Pod) -> bool:
        """An already-bound pod appeared from outside this session
        (bound by another scheduler, a static pod, resync replay):
        charge its occupancy to its node's row. Greedy-fit replay via
        the full row recompute — foreign pods may overcommit, which
        _apply_commit_host (the mirror of a solver commit, which only
        places fitting pods) cannot express. Idempotent per pod key."""
        if not pod.spec.node_name:
            return False
        lp = self._lower_pod(pod)
        if lp.key in self._pod_node:
            return False
        j = self.node_index.get(pod.spec.node_name)
        if j is None:
            return False
        self._assigned[j].append(lp)
        self._pod_node[lp.key] = j
        self._recompute_node_row(j)
        self._dirty.add(j)
        return True

    def has_assigned(self, key: str) -> bool:
        return key in self._pod_node

    def delete_assigned(self, key: str) -> bool:
        """A running pod vanished: free its occupancy (one node row)."""
        j = self._pod_node.pop(key, None)
        if j is None:
            return False
        self._assigned[j] = [lp for lp in self._assigned[j] if lp.key != key]
        self._recompute_node_row(j)
        self._dirty.add(j)
        return True

    def _dispatch(self, pods, carry):
        """Enqueue one tick's jitted solve for the session mode. Pure
        dispatch — JAX returns immediately; nothing here syncs the
        host. Returns (assignment, new_carry, (waves, iters, res)) with
        the telemetry entries still device scalars (or None)."""
        waves = s_iters = s_res = None
        if self.mode == "wave":
            from kubernetes_tpu.ops.wave import solve_waves_with_state

            assignment, carry, waves = solve_waves_with_state(
                pods, carry, self.weights
            )
        elif self.mode == "sinkhorn":
            from kubernetes_tpu.ops.sinkhorn import solve_sinkhorn_with_state

            assignment, carry, waves, s_iters, s_res = (
                solve_sinkhorn_with_state(pods, carry, self.weights)
            )
        else:
            assignment, carry = solve_with_state(pods, carry, self.weights)
        return assignment, carry, (waves, s_iters, s_res)

    def solve_async(self) -> PendingSolve:
        """Pipelined tick: stage the pending backlog, dispatch the
        jitted solve, start the assignment's device->host copy, and
        return WITHOUT a blocking host sync. The returned handle's
        ``result()`` performs the readback and host-mirror commits;
        until then the caller overlaps the device time with the next
        tick's staging (``add_pending``), watch-delta application, and
        its own commit I/O. At most one tick is in flight: a second
        ``solve_async`` resolves the first before dispatching (the
        donated carry and the dirty-row flush both require it)."""
        from kubernetes_tpu.utils import tracing

        self._finish_inflight()
        pending, self._pending = self._pending, []
        if not pending:
            self._flush_dirty()
            return PendingSolve(self, [], None, (None, None, None), 0.0)
        t0 = time.monotonic()
        # Phase spans cover the session tick's segments: "upload" is
        # the dirty-row scatter plus staging this tick's pod arrays
        # onto the device, "solve" the async dispatch, "readback" the
        # blocking copy-out (which therefore absorbs the device time).
        # The "lower" phase is the per-pod _lower_pod work, observed at
        # the daemon's add_pending loop — NOT here, so each tick
        # contributes exactly one observation per phase.
        with tracing.phase(
            "upload", dirty=len(self._dirty), pods=len(pending)
        ):
            self._flush_dirty()
            pods = self._pod_arrays(pending)
        with tracing.phase("solve", mode=self.mode, incremental=True):
            assignment, self.dev, tele = self._dispatch(pods, self.dev)
            # Start the device->host copy NOW: it streams behind the
            # solve, so result() finds the bytes (mostly) local.
            if hasattr(assignment, "copy_to_host_async"):
                assignment.copy_to_host_async()
        handle = PendingSolve(
            self, pending, assignment, tele, time.monotonic() - t0
        )
        self._inflight = handle
        return handle

    def _finish_inflight(self) -> None:
        if self._inflight is not None:
            self._inflight.result()

    def _finish_solve(self, handle: PendingSolve) -> None:
        """Blocking half of a pipelined tick: copy the assignment out,
        record telemetry, and mirror the device commits into the host
        rows. Called (once) from PendingSolve.result()."""
        from kubernetes_tpu.utils import tracing

        if self._inflight is handle:
            self._inflight = None
        t0 = time.monotonic()
        waves, s_iters, s_res = handle.tele
        pending = handle.pending
        with tracing.phase("readback"):
            from kubernetes_tpu.utils import sli

            full = np.asarray(handle.assignment)
            sli.note_transfer("d2h", full.nbytes)
            picks = full[: len(pending)]
            # Telemetry scalars convert AFTER the assignment copy
            # blocked — no extra device sync on the tick path.
            self.last_stats = {}
            if waves is not None:
                self.last_stats["waves"] = int(waves)
            if s_iters is not None:
                from kubernetes_tpu.utils import flightrecorder

                self.last_stats["sinkhorn_iters"] = int(s_iters)
                self.last_stats["sinkhorn_residual"] = float(s_res)
                flightrecorder.observe_solve_telemetry(
                    "sinkhorn", int(s_iters), residual=float(s_res),
                    waves=int(waves),
                )
            elif waves is not None:
                from kubernetes_tpu.utils import flightrecorder

                flightrecorder.observe_solve_telemetry("wave", int(waves))
        out: List[Tuple[str, Optional[str]]] = []
        for lp, j in zip(pending, picks.tolist()):
            if j < 0 or j >= self.N_cap or self.node_names[j] is None:
                out.append((lp.key, None))
                continue
            self._assigned[j].append(lp)
            self._pod_node[lp.key] = j
            self._apply_commit_host(j, lp)
            out.append((lp.key, self.node_names[j]))
        handle.resolved_mono = time.monotonic()
        handle.block_s = handle.resolved_mono - t0
        handle._result = out
        handle._done = True

    def solve(self) -> List[Tuple[str, Optional[str]]]:
        """Schedule the pending backlog against the device-resident
        cluster state; commits ride the donated carry. Returns
        [(pod_key, node_name | None)] and clears the backlog. The
        synchronous shape of solve_async() — dispatch + immediate
        readback."""
        return self.solve_async().result()

    def prewarm(
        self, max_pod_bucket: int = 0, max_scatter_width: int = 512
    ) -> int:
        """Compile every executable a live tick can hit — the solve at
        each pow2 pod bucket up to max_pod_bucket and the dirty-row
        scatter at each pow2 width — against THROWAWAY copies of the
        node state, so the process-global XLA cache is hot before the
        first real pod arrives (a fresh bucket mid-workload otherwise
        stalls that tick for a full compile: seconds on TPU, minutes on
        CPU hosts). Returns the number of warm dispatches issued."""
        compiled = 0
        bucket = max(_bucket(1), self.pod_bucket)
        top = max(bucket, _bucket(max_pod_bucket)) if max_pod_bucket else 0
        while bucket <= top:
            pods = self._stage_arrays([], bucket, reuse=False)
            # Throwaway carries go through _upload_all: identical
            # sharding (mesh sessions included) to the live self.dev —
            # a differently-placed warm carry would compile a cache
            # entry the real ticks never hit.
            carry = self._upload_all()
            assignment, carry, _tele = self._dispatch(pods, carry)
            jax.block_until_ready(assignment)
            del carry
            compiled += 1
            bucket *= 2
        width = 8
        idx_max = max(
            (j for j, n in enumerate(self.node_names) if n is not None),
            default=0,
        )
        while width <= min(max_scatter_width, self.N_cap):
            idx = np.full(width, idx_max, np.int32)
            rows = {k: self.h[k][idx] for k in self.h}
            carry = self._upload_all()
            out = _scatter_rows(carry, jnp.asarray(idx), rows)
            jax.block_until_ready(jax.tree_util.tree_leaves(out))
            del carry, out
            compiled += 1
            width *= 2
        return compiled

    def solve_gang(
        self, gangs: Sequence[SessionGang]
    ) -> Tuple[List[Tuple[str, Optional[str]]], List[str]]:
        """solve() with group-level all-or-nothing acceptance, session-
        aware: a rejected group's tentative placements were already
        committed into the DONATED device carry by the tick's solve, so
        releasing them goes through delete_assigned — the host mirror
        recomputes the touched node rows and the next solve's dirty
        flush scatters them back onto the device. Each rejection round
        releases EVERY placement made this tick and re-solves the
        surviving backlog, so the freed capacity is usable immediately
        and the accepted-group set matches the batch paths' (same
        fixed-point loop as scheduler.gang.gang_solve). Acceptance
        counts run through the same masked segment reduction as the
        batch device path."""
        from kubernetes_tpu.ops.pipeline import gang_member_counts_device

        tick = list(self._pending)
        if not gangs:
            return self.solve(), []
        gangs = list(gangs)
        gi_of_key: Dict[str, int] = {}
        for gi, g in enumerate(gangs):
            for k in g.pod_keys:
                gi_of_key[k] = gi
        results: Dict[str, Optional[str]] = {}
        rejected: set = set()
        while True:
            for key, dest in self.solve():
                results[key] = dest
            placed = np.fromiter(
                (results.get(lp.key) is not None for lp in tick),
                bool, count=len(tick),
            )
            gids = np.fromiter(
                (gi_of_key.get(lp.key, -1) for lp in tick),
                np.int32, count=len(tick),
            )
            counts = gang_member_counts_device(placed, gids, len(gangs))
            newly = [
                gi
                for gi, g in enumerate(gangs)
                if gi not in rejected
                and int(counts[gi]) + g.bound < g.min_member
            ]
            if not newly:
                break
            rejected.update(newly)
            # Release the whole tick's tentative placements (device rows
            # restore via the dirty scatter) and re-solve the survivors
            # in original arrival order.
            for lp in tick:
                if results.get(lp.key) is not None:
                    self.delete_assigned(lp.key)
                results[lp.key] = None
            self._pending = [
                lp for lp in tick
                if gi_of_key.get(lp.key, -1) not in rejected
            ]
        return (
            [(lp.key, results.get(lp.key)) for lp in tick],
            [gangs[gi].key for gi in sorted(rejected)],
        )

    def _pod_arrays(self, pending: List[_LoweredPod]) -> Dict[str, jnp.ndarray]:
        PP = max(_bucket(len(pending)), self.pod_bucket)
        return self._stage_arrays(pending, PP)

    #: (key, pad value) layout of the staged pod columns; padding slots
    #: are pinned to -2 (never placeable).
    _STAGE_FILL = (
        ("cpu", 0), ("mem", 0), ("zero_req", 0), ("sel", 0), ("port", 0),
        ("vol_any", 0), ("vol_rw", 0), ("pinned", -2), ("svc", -1),
        ("svc_ids", -1),
    )

    def _stage_arrays(
        self, pending: List[_LoweredPod], PP: int, reuse: bool = True
    ) -> Dict[str, jnp.ndarray]:
        """Host staging buffers for one tick's pod upload. Buffers are
        DOUBLE-buffered per bucket size: device_put may still be
        draining tick k's transfer when tick k+1 stages, so k+1 always
        writes the other slot (at most one solve is in flight — two
        slots suffice). reuse=False (prewarm) allocates throwaway
        arrays instead."""
        arr = None
        if reuse:
            slot = self._stage_bufs[self._stage_flip]
            self._stage_flip ^= 1
            arr = slot.get(PP)
            if arr is not None:
                for key, fill in self._STAGE_FILL:
                    arr[key].fill(fill)
        if arr is None:
            arr = {
                "cpu": np.zeros(PP, np.float32),
                "mem": np.zeros(PP, np.float32),
                "zero_req": np.zeros(PP, bool),
                "sel": np.zeros((PP, self.LW), np.uint32),
                "port": np.zeros((PP, self.PW), np.uint32),
                "vol_any": np.zeros((PP, self.VW), np.uint32),
                "vol_rw": np.zeros((PP, self.VW), np.uint32),
                # Padding slots pinned to -2: never placeable.
                "pinned": np.full(PP, -2, np.int32),
                "svc": np.full(PP, -1, np.int32),
                "svc_ids": np.full((PP, SVC_K), -1, np.int32),
            }
            if reuse:
                slot[PP] = arr
        for i, lp in enumerate(pending):
            arr["cpu"][i] = lp.cpu
            arr["mem"][i] = lp.mem_mib
            arr["zero_req"][i] = lp.zero_req
            arr["sel"][i] = bitset(lp.sel_ids, self.LW)
            arr["port"][i] = bitset(lp.port_ids, self.PW)
            arr["vol_any"][i] = bitset(lp.vol_any_ids, self.VW)
            arr["vol_rw"][i] = bitset(lp.vol_rw_ids, self.VW)
            if lp.pinned_name:
                arr["pinned"][i] = self.node_index.get(
                    lp.pinned_name, -1 if lp.pin_soft else -2
                )
            else:
                arr["pinned"][i] = -1
            arr["svc"][i] = lp.svc
            arr["svc_ids"][i, : len(lp.svc_topk)] = lp.svc_topk
        from kubernetes_tpu.utils import sli

        sli.note_transfer("h2d", sli.nbytes_of(arr))
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as PS

            repl = NamedSharding(self.mesh, PS())
            return {k: jax.device_put(v, repl) for k, v in arr.items()}
        return {k: jnp.asarray(v) for k, v in arr.items()}
