"""The XLA compile/cost ledger: every jitted kernel's compile history.

After PR 12 the daemon is an always-on pipelined device program, yet
nothing could say where device time goes: which kernel compiled when,
at what wall cost, and what the compiled executable actually costs to
run (FLOPs, HBM bytes, temp allocation). This module closes that gap
with a ``traced_jit`` wrapper adopted at every ``jax.jit`` site under
``ops/`` — the SAME inventory ktlint's KT006 pass cross-checks against
``ops/parity.py`` ORACLE_TWINS, so ledger kernel names and registry
keys are one namespace (``solver._solve_xla``,
``preemption._victim_prefix_kernel.kernel``, ...).

What gets recorded, per (kernel, staged-shape signature):

- **compile events**: detected via the jit dispatch cache sentinel the
  PR-7 recompilation test already watches (``_cache_size()`` growth
  around a dispatch); the dispatch wall of a growing call ~= trace +
  lower + XLA compile, because jit dispatch is async — execution does
  not block it. Re-compiles after ``jax.clear_caches()`` count again
  (they ARE new compiles); cache hits never do.
- **cost/memory analysis**: ``Compiled.cost_analysis()`` /
  ``memory_analysis()`` (FLOPs, bytes accessed, derived arithmetic
  intensity, temp/arg/output bytes) harvested on a BACKGROUND thread
  via an avals-only ``.lower().compile()`` — the AOT compile does not
  share the dispatch cache in this jax, so harvesting inline would
  double every compile stall on the tick path. Rows show
  ``cost_status: pending`` until the harvest lands (tests and bench
  block on ``wait_pending``).
- **collective inventory**: the compiled module's collective op counts
  (ops/contracts.py ``collective_inventory`` — the same parser ktmesh
  pins budgets with) plus a ``collectives_verdict`` joining them
  against the kernel's declared CommBudget: an undeclared collective
  KIND at any staged shape is sharding drift (``drift: ...``), shown
  as the COMM column in ``ktctl profile kernels``.

Surfaces: ``GET /debug/kernels`` (server/httpserver.py), ``ktctl
profile kernels`` (exit 1 + "no compiles recorded" on a cold process),
the ``solver_compile_seconds_total{kernel}`` counter, and bench.py's
profiler summary.

No module-level jax import — ops/preemption.py keeps its "a CPU-only
host without jax configured never imports it at module load" contract
and this module rides the same rule (jax loads at first TracedJit
construction, which IS a jit construction).
"""

from __future__ import annotations

import functools
import logging
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.utils import metrics, sanitizer

_LOG = logging.getLogger("kubernetes_tpu.ledger")

#: Wall seconds spent compiling, by kernel — the counter bench.py and
#: the SLO plane read next to solver_xla_compiles_total (which counts
#: events; this one carries the time).
COMPILE_SECONDS = metrics.DEFAULT.counter(
    "solver_compile_seconds_total",
    "Wall seconds spent in XLA solver compiles, by kernel",
    ("kernel",),
)

#: KT_LEDGER_HARVEST=0 disables the background cost harvest (the
#: second, avals-only compile per unique shape). The compile-event half
#: of the ledger — names, shapes, wall times, counts — stays on.
_HARVEST_ENABLED = os.environ.get("KT_LEDGER_HARVEST", "1") != "0"


def _derive_kernel_name(fn) -> str:
    """Registry-keyed kernel name: '<ops module>.<dotted def path>' —
    the exact ORACLE_TWINS key format (nested jits keep their enclosing
    function, '<locals>' stripped)."""
    mod = (getattr(fn, "__module__", "") or "").rsplit(".", 1)[-1]
    qual = (getattr(fn, "__qualname__", "") or getattr(fn, "__name__", "?"))
    return f"{mod}.{qual.replace('.<locals>', '')}"


def _signature(args, kwargs) -> str:
    """Compact staged-shape signature of one call — the ledger's
    per-bucket key. THE implementation lives in ops/contracts.py
    (shape_signature) so the ledger's observed rows and the contract
    checker's declared shapes are one string format; only computed on
    compile events (tree-flattening every call would tax the
    micro-tick path for nothing)."""
    from kubernetes_tpu.ops.contracts import shape_signature

    return shape_signature(args, kwargs)


def _avalize(args, kwargs):
    """(args, kwargs) with array leaves replaced by ShapeDtypeStructs,
    so the background harvest can re-lower WITHOUT touching live (or
    donated-and-deleted) buffers — avals survive donation."""
    import jax

    def conv(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return x
        sharding = None
        try:
            sharding = x.sharding
        except Exception:
            sharding = None
        try:
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)
        except TypeError:
            return jax.ShapeDtypeStruct(shape, dtype)

    return jax.tree_util.tree_map(
        conv, (args, kwargs), is_leaf=lambda x: hasattr(x, "shape")
    )


def _normalize_cost(analysis) -> Dict[str, float]:
    """Compiled.cost_analysis() returns a dict (or a 1-list of dicts,
    depending on jax version); keep the headline figures + the derived
    arithmetic intensity."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not isinstance(analysis, dict):
        return {}
    flops = float(analysis.get("flops", 0.0) or 0.0)
    nbytes = float(analysis.get("bytes accessed", 0.0) or 0.0)
    out = {"flops": flops, "bytes_accessed": nbytes}
    if nbytes > 0:
        out["arithmetic_intensity"] = round(flops / nbytes, 4)
    return out


class CompileLedger:
    """Thread-safe per-kernel compile/cost rows. One instance per
    process (``DEFAULT``); daemons and tests share it the way they
    share the metrics registry."""

    def __init__(self):
        self._lock = sanitizer.lock("ledger.rows")
        # kernel -> {"calls", "compiles", "compile_seconds",
        #            "shapes": {signature -> shape row dict}}
        self._rows: Dict[str, dict] = {}

    # -- hot path ------------------------------------------------------

    def note_call(self, kernel: str) -> None:
        with self._lock:
            row = self._rows.get(kernel)
            if row is None:
                row = self._rows[kernel] = {
                    "calls": 0, "compiles": 0,
                    "compile_seconds": 0.0, "shapes": {},
                }
            row["calls"] += 1

    def record_compile(
        self, kernel: str, signature: str, compile_s: float
    ) -> None:
        """One observed XLA compile (dispatch-cache growth). Repeat
        compiles of a signature (jax.clear_caches) accumulate; cache
        hits never reach here."""
        COMPILE_SECONDS.inc(compile_s, kernel=kernel)
        with self._lock:
            row = self._rows.setdefault(
                kernel,
                {"calls": 0, "compiles": 0,
                 "compile_seconds": 0.0, "shapes": {}},
            )
            row["calls"] += 1
            row["compiles"] += 1
            row["compile_seconds"] += compile_s
            shape = row["shapes"].get(signature)
            if shape is None:
                shape = row["shapes"][signature] = {
                    "signature": signature,
                    "compiles": 0,
                    "compile_seconds": 0.0,
                    "first_compiled_unix": time.time(),
                    "cost_status": "pending",
                }
            shape["compiles"] += 1
            shape["compile_seconds"] += compile_s

    # -- harvest results -----------------------------------------------

    def attach_cost(
        self, kernel: str, signature: str,
        cost: Dict[str, float], memory: Dict[str, int],
    ) -> None:
        with self._lock:
            shape = (
                self._rows.get(kernel, {}).get("shapes", {}).get(signature)
            )
            if shape is None:
                return
            shape.update(cost)
            shape.update(memory)
            shape["cost_status"] = "ok"

    def attach_error(self, kernel: str, signature: str, err: str) -> None:
        with self._lock:
            shape = (
                self._rows.get(kernel, {}).get("shapes", {}).get(signature)
            )
            if shape is not None:
                shape["cost_status"] = f"error: {err}"

    # -- reads ---------------------------------------------------------

    def kernels(self) -> List[str]:
        with self._lock:
            return sorted(self._rows)

    def rows(self) -> List[dict]:
        """Per-kernel rows (shape sub-rows sorted by signature), deep
        enough a caller can mutate its copy. Every shape sub-row
        carries a ``contract`` verdict — the observed staged-shape
        signature joined against the kernel's declared contract
        (ops/contracts.py), so a drifted shape shows up as a CONTRACT
        mismatch in ``GET /debug/kernels`` / ``ktctl profile
        kernels``. The join runs OUTSIDE the lock: it is pure string
        work, but it is also not the hot path's business."""
        with self._lock:
            out = []
            for kernel in sorted(self._rows):
                row = self._rows[kernel]
                out.append(
                    {
                        "kernel": kernel,
                        "calls": row["calls"],
                        "compiles": row["compiles"],
                        "compile_seconds": round(row["compile_seconds"], 6),
                        "shapes": [
                            dict(row["shapes"][sig])
                            for sig in sorted(row["shapes"])
                        ],
                    }
                )
        try:
            from kubernetes_tpu.ops.contracts import contract_verdict

            for r in out:
                for s in r["shapes"]:
                    s["contract"] = contract_verdict(
                        r["kernel"], s.get("signature", "")
                    )
        except Exception:  # pragma: no cover - contracts must never
            pass  # sink a ledger read
        return out

    def summary(self, rows: Optional[List[dict]] = None) -> dict:
        rows = self.rows() if rows is None else rows
        compiles = sum(r["compiles"] for r in rows)

        def best(metric: str) -> List[dict]:
            ranked = sorted(
                (
                    (
                        max(
                            (s.get(metric, 0.0) or 0.0)
                            for s in r["shapes"]
                        ) if r["shapes"] else 0.0,
                        r["kernel"],
                    )
                    for r in rows
                ),
                reverse=True,
            )
            return [
                {"kernel": k, metric: v} for v, k in ranked[:3] if v > 0
            ]

        return {
            "kernels": len(rows),
            "compiles": compiles,
            "calls_total": sum(r["calls"] for r in rows),
            "compile_seconds_total": round(
                sum(r["compile_seconds"] for r in rows), 6
            ),
            "pending_cost_rows": sum(
                1
                for r in rows
                for s in r["shapes"]
                if s.get("cost_status") == "pending"
            ),
            "top_flops": best("flops"),
            "top_bytes": best("bytes_accessed"),
        }

    def to_dict(self) -> dict:
        # One rows() pass (the contract-verdict join rides it) shared
        # by both halves of the payload.
        rows = self.rows()
        return {"kernels": rows, "summary": self.summary(rows)}

    def wait_pending(self, timeout: float = 30.0) -> bool:
        """Block until no shape row's cost_status is 'pending' (tests
        + bench read the ledger after this). True = drained."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                pending = any(
                    s.get("cost_status") == "pending"
                    for r in self._rows.values()
                    for s in r["shapes"].values()
                )
            if not pending:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()


DEFAULT = CompileLedger()


# -- background cost harvest -------------------------------------------

_HARVEST_Q: "queue.Queue" = queue.Queue()
_HARVEST_STARTED = threading.Event()
#: Interpreter shutdown in progress: stop compiling (an XLA compile
#: running on the (daemon) harvest thread while CPython tears down
#: aborts the process with "terminate called without an active
#: exception"), mark queued rows instead, and let the worker drain.
_SHUTDOWN = threading.Event()


def _shutdown_harvest() -> None:
    """Pre-teardown drain: flag shutdown (queued items resolve to an
    error marker instead of compiling), post the exit sentinel, and
    join the worker — it finishes at most the ONE compile already in
    flight. Registered via threading._register_atexit so it runs
    before CPython starts destroying thread states. The join is
    BOUNDED: a pathological native compile must not pin interpreter
    exit for minutes — past the cap we accept the (rare) residual risk
    of tearing down under it rather than hanging a Ctrl-C."""
    _SHUTDOWN.set()
    if not _HARVEST_STARTED.is_set():
        return
    thread = _HARVEST_THREAD[0]
    _HARVEST_Q.put(None)
    if thread is not None and thread.is_alive():
        thread.join(timeout=60.0)
        if thread.is_alive():  # pragma: no cover - pathological compile
            _LOG.warning(
                "ledger cost harvest still compiling after 60s at "
                "interpreter exit; abandoning it"
            )


_HARVEST_THREAD: List[Optional[threading.Thread]] = [None]
# concurrent.futures' trick: threading._register_atexit callbacks run
# BEFORE threading._shutdown joins/freezes threads (plain atexit runs
# too late to stop a native compile cleanly on every CPython).
_register = getattr(threading, "_register_atexit", None)
if _register is not None:
    _register(_shutdown_harvest)
else:  # pragma: no cover - very old CPython
    import atexit

    atexit.register(_shutdown_harvest)


def _harvest_worker() -> None:
    while True:
        item = _HARVEST_Q.get()
        if item is None:
            _HARVEST_Q.task_done()
            return
        led, jitfn, aval_args, aval_kwargs, kernel, signature = item
        if _SHUTDOWN.is_set():
            led.attach_error(kernel, signature, "interpreter shutdown")
            _HARVEST_Q.task_done()
            continue
        try:
            compiled = jitfn.lower(*aval_args, **aval_kwargs).compile()
            cost = _normalize_cost(compiled.cost_analysis())
            ma = compiled.memory_analysis()
            memory = {
                "temp_bytes": int(
                    getattr(ma, "temp_size_in_bytes", 0) or 0
                ),
                "argument_bytes": int(
                    getattr(ma, "argument_size_in_bytes", 0) or 0
                ),
                "output_bytes": int(
                    getattr(ma, "output_size_in_bytes", 0) or 0
                ),
                "generated_code_bytes": int(
                    getattr(ma, "generated_code_size_in_bytes", 0) or 0
                ),
            }
            # Collective inventory + COMM verdict: the harvest is the
            # ONE place the compiled/partitioned module exists, so the
            # sharding story rides the same row as cost/memory. The
            # shared parser lives in ops/contracts.py (pure regex —
            # ktmesh pins exact budgets at its probe point; the
            # runtime verdict only flags UNDECLARED collective kinds,
            # because staged bucket sizes vary the counts).
            try:
                from kubernetes_tpu.ops.contracts import (
                    collective_inventory, comm_verdict,
                )

                inv = collective_inventory(compiled.as_text())
                memory["collectives"] = inv["counts"]
                memory["collectives_verdict"] = comm_verdict(
                    kernel, inv["counts"]
                )
            except Exception:  # pragma: no cover - inventory must
                pass  # never sink a cost harvest
            led.attach_cost(kernel, signature, cost, memory)
        except Exception as e:
            _LOG.debug(
                "cost harvest for %s failed", kernel, exc_info=True
            )
            led.attach_error(kernel, signature, repr(e))
        finally:
            _HARVEST_Q.task_done()


def _schedule_harvest(led, jitfn, args, kwargs, kernel, signature) -> None:
    """Queue a cost/memory harvest for `led`'s (kernel, signature) row.
    The TARGET ledger rides the queue item: the row must resolve on
    whichever ledger recorded the compile, not whatever DEFAULT points
    at when the worker gets around to it."""
    if not _HARVEST_ENABLED:
        led.attach_error(kernel, signature, "harvest disabled")
        return
    try:
        aval_args, aval_kwargs = _avalize(args, kwargs)
    except Exception as e:
        led.attach_error(kernel, signature, f"avalize: {e!r}")
        return
    if _SHUTDOWN.is_set():
        led.attach_error(kernel, signature, "interpreter shutdown")
        return
    if not _HARVEST_STARTED.is_set():
        _HARVEST_STARTED.set()
        t = threading.Thread(
            target=_harvest_worker, name="kt-ledger-harvest", daemon=True
        )
        _HARVEST_THREAD[0] = t
        t.start()
    _HARVEST_Q.put((led, jitfn, aval_args, aval_kwargs, kernel, signature))


# -- the wrapper -------------------------------------------------------


class TracedJit:
    """``jax.jit`` with a compile ledger. Call-compatible with the
    wrapped pjit function and forwards its introspection surface —
    ``_cache_size()`` (the PR-7 sentinel tests and utils/sli.py read),
    ``clear_cache()``, ``lower()`` — so adopting the wrapper changes
    observability, never behavior."""

    def __init__(self, fn, jit_kwargs: dict, kernel: Optional[str] = None):
        import jax

        self._fn = fn
        # Retained for introspection: ktmesh's runtime<->static
        # cross-check rebuilds this jit (same static/donate argnames)
        # to lower the kernel under probe shardings.
        self.jit_kwargs = dict(jit_kwargs)
        self._jit = jax.jit(fn, **jit_kwargs)
        self.kernel = kernel or _derive_kernel_name(fn)
        functools.update_wrapper(self, fn)

    def __call__(self, *args, **kwargs):
        jfn = self._jit
        try:
            before = jfn._cache_size()
        except Exception:
            before = None
        t0 = time.perf_counter()
        out = jfn(*args, **kwargs)
        if before is None:
            DEFAULT.note_call(self.kernel)
            return out
        try:
            grew = jfn._cache_size() > before
        except Exception:
            grew = False
        if not grew:
            DEFAULT.note_call(self.kernel)
            return out
        # Dispatch is async, so a growing call's wall ~= trace + lower
        # + XLA compile (execution doesn't block the return). Two
        # threads racing the same wrapper could misattribute ONE event
        # — tolerated: the bookkeeping must never serialize solves.
        compile_s = time.perf_counter() - t0
        try:
            signature = _signature(args, kwargs)
        except Exception:
            signature = "?"
        led = DEFAULT
        led.record_compile(self.kernel, signature, compile_s)
        _schedule_harvest(
            led, self._jit, args, kwargs, self.kernel, signature
        )
        return out

    # -- forwarded pjit surface ---------------------------------------

    def _cache_size(self) -> int:
        return self._jit._cache_size()

    def clear_cache(self) -> None:
        clear = getattr(self._jit, "clear_cache", None)
        if clear is not None:
            clear()

    def lower(self, *args, **kwargs):
        return self._jit.lower(*args, **kwargs)

    def eval_shape(self, *args, **kwargs):
        return self._jit.eval_shape(*args, **kwargs)

    def trace(self, *args, **kwargs):
        """Abstract trace (jaxpr, no compile, no execution) — the
        contract checker's jaxpr-walk entry point."""
        return self._jit.trace(*args, **kwargs)


def traced_jit(fn=None, *, kernel: Optional[str] = None, **jit_kwargs):
    """Drop-in ``jax.jit`` replacement for ops/ kernels: identical
    static_argnames/donate_argnames semantics, plus ledger accounting.
    Usable bare (``@traced_jit``) or as a factory
    (``@traced_jit(static_argnames=(...))``); ktlint's KT001/KT006
    passes recognize both shapes as jit decoration."""
    if fn is not None:
        return TracedJit(fn, jit_kwargs, kernel)
    return lambda f: TracedJit(f, jit_kwargs, kernel)
