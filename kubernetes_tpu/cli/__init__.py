"""kubectl-style CLI (`ktctl`).

Reference: pkg/kubectl/ — command tree (get, create, delete, describe,
scale, label, expose, config), resource builder over files/stdin,
printers. Entry point: kubernetes_tpu.cli.main.
"""

from kubernetes_tpu.cli.ktctl import main

__all__ = ["main"]
