"""ktctl — the CLI.

Reference: pkg/kubectl/cmd/ (cobra command tree), pkg/kubectl/resource
(builder: files/stdin -> objects), resource_printer.go (table/json/yaml
printers), describe.go, scale.go, expose.go.

Usage:
    ktctl [--server URL] [-n NAMESPACE] [-o table|json|yaml|name] CMD ...

Commands: get (incl. -w watch), create, apply, update, delete,
describe, scale, label, expose, run, rolling-update, stop (reaper),
logs (incl. -f follow), exec, port-forward, proxy, top, namespace,
config, api-resources, api-versions, cluster-info, version.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

import yaml

from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.models import serde
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.server.registry import RESOURCES

# Short aliases (reference: kubectl.go resource shortcuts).
ALIASES = {
    "po": "pods",
    "pod": "pods",
    "no": "nodes",
    "node": "nodes",
    "svc": "services",
    "service": "services",
    "rc": "replicationcontrollers",
    "ep": "endpoints",
    "ev": "events",
    "ns": "namespaces",
    "namespace": "namespaces",
    "secret": "secrets",
    "event": "events",
    "pg": "podgroups",
    "podgroup": "podgroups",
    "pc": "priorityclasses",
    "priorityclass": "priorityclasses",
}


def resolve_resource(name: str) -> str:
    name = name.lower()
    name = ALIASES.get(name, name)
    if name not in RESOURCES:
        raise SystemExit(f"error: unknown resource type {name!r}")
    return RESOURCES[name].name


# ---------------------------------------------------------------------------
# Printers (reference: resource_printer.go)
# ---------------------------------------------------------------------------


def _age(ts: str) -> str:
    import time
    from datetime import datetime, timezone

    if not ts:
        return "<none>"
    try:
        then = datetime.strptime(ts, "%Y-%m-%dT%H:%M:%SZ").replace(
            tzinfo=timezone.utc
        )
    except ValueError:
        return "<none>"
    secs = int(time.time() - then.timestamp())
    if secs < 120:
        return f"{secs}s"
    if secs < 7200:
        return f"{secs // 60}m"
    if secs < 172800:
        return f"{secs // 3600}h"
    return f"{secs // 86400}d"


def _pod_row(o) -> List[str]:
    statuses = o.status.container_statuses
    ready = sum(1 for c in statuses if c.ready)
    restarts = sum(c.restart_count for c in statuses)
    return [
        o.metadata.name,
        f"{ready}/{max(len(statuses), len(o.spec.containers))}",
        o.status.phase,
        str(restarts),
        o.spec.node_name or "<none>",
        _age(o.metadata.creation_timestamp),
    ]


def _node_row(o) -> List[str]:
    ready = "Unknown"
    for c in o.status.conditions:
        if c.type == "Ready":
            ready = {"True": "Ready", "False": "NotReady"}.get(c.status, "Unknown")
    if o.spec.unschedulable:
        ready += ",SchedulingDisabled"
    cap = o.status.capacity
    return [
        o.metadata.name,
        ready,
        str(cap.get("cpu", "")),
        str(cap.get("memory", "")),
        _age(o.metadata.creation_timestamp),
    ]


def _svc_row(o) -> List[str]:
    ports = ",".join(f"{p.port}/{p.protocol}" for p in o.spec.ports)
    return [
        o.metadata.name,
        o.spec.type,
        o.spec.cluster_ip or "<none>",
        ports or "<none>",
        _age(o.metadata.creation_timestamp),
    ]


def _rc_row(o) -> List[str]:
    return [
        o.metadata.name,
        str(o.spec.replicas),
        str(o.status.replicas),
        ",".join(f"{k}={v}" for k, v in o.spec.selector.items()),
        _age(o.metadata.creation_timestamp),
    ]


def _ep_row(o) -> List[str]:
    addrs = []
    for s in o.subsets:
        for a in s.addresses:
            for p in s.ports:
                addrs.append(f"{a.ip}:{p.port}")
    return [o.metadata.name, ",".join(addrs[:4]) + ("..." if len(addrs) > 4 else "") or "<none>", _age(o.metadata.creation_timestamp)]


def _event_row(o) -> List[str]:
    return [
        _age(o.last_timestamp or o.first_timestamp),
        o.reason,
        f"{o.involved_object.kind}/{o.involved_object.name}",
        (o.source or {}).get("component", ""),
        o.message[:80],
    ]


def _podgroup_row(o) -> List[str]:
    return [
        o.metadata.name,
        str(o.spec.min_member),
        o.status.phase or "Pending",
        f"{o.status.bound}/{o.status.members}",
        _age(o.metadata.creation_timestamp),
    ]


def _priorityclass_row(o) -> List[str]:
    return [
        o.metadata.name,
        str(o.value),
        "true" if o.global_default else "false",
        o.preemption_policy or "PreemptLowerPriority",
        _age(o.metadata.creation_timestamp),
    ]


TABLE_COLUMNS = {
    "pods": (["NAME", "READY", "STATUS", "RESTARTS", "NODE", "AGE"], _pod_row),
    "nodes": (["NAME", "STATUS", "CPU", "MEMORY", "AGE"], _node_row),
    "services": (["NAME", "TYPE", "CLUSTER-IP", "PORTS", "AGE"], _svc_row),
    "replicationcontrollers": (
        ["NAME", "DESIRED", "CURRENT", "SELECTOR", "AGE"],
        _rc_row,
    ),
    "endpoints": (["NAME", "ENDPOINTS", "AGE"], _ep_row),
    "events": (["AGE", "REASON", "OBJECT", "SOURCE", "MESSAGE"], _event_row),
    "podgroups": (
        ["NAME", "MIN-MEMBER", "PHASE", "BOUND", "AGE"],
        _podgroup_row,
    ),
    "priorityclasses": (
        ["NAME", "VALUE", "GLOBAL-DEFAULT", "PREEMPTION-POLICY", "AGE"],
        _priorityclass_row,
    ),
}


def _generic_row(o) -> List[str]:
    return [o.metadata.name, _age(o.metadata.creation_timestamp)]


def print_table(
    resource: str, objs: List[Any], out=None, header: bool = True
) -> None:
    out = out or sys.stdout
    headers, row_fn = TABLE_COLUMNS.get(resource, (["NAME", "AGE"], _generic_row))
    rows = [headers] + [row_fn(o) for o in objs]
    widths = [max(len(r[i]) for r in rows) for i in range(len(headers))]
    for r in rows if header else rows[1:]:
        out.write("   ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip() + "\n")


def print_objs(resource: str, objs: List[Any], fmt: str, out=None) -> None:
    out = out or sys.stdout
    if fmt == "table":
        print_table(resource, objs, out)
    elif fmt == "name":
        for o in objs:
            out.write(f"{resource}/{o.metadata.name}\n")
    else:
        wires = [serde.to_wire(o) for o in objs]
        payload = wires[0] if len(wires) == 1 else {"kind": "List", "items": wires}
        if fmt == "json":
            out.write(json.dumps(payload, indent=2) + "\n")
        else:
            out.write(yaml.safe_dump(payload, sort_keys=False))


# ---------------------------------------------------------------------------
# Resource builder (reference: resource/builder.go — files/stdin -> objects)
# ---------------------------------------------------------------------------


def load_manifests(filename: str) -> List[Dict]:
    """Files, directories, stdin ('-'), or URLs — the reference
    resource builder's input surface (builder.go:77-126; directories
    visit every .json/.yaml/.yml inside, sorted)."""
    import os

    if filename == "-":
        texts = [sys.stdin.read()]
    elif filename.startswith(("http://", "https://")):
        import urllib.request

        with urllib.request.urlopen(filename, timeout=30) as resp:
            texts = [resp.read().decode()]
    elif os.path.isdir(filename):
        texts = []
        for entry in sorted(os.listdir(filename)):
            if not entry.endswith((".json", ".yaml", ".yml")):
                continue
            with open(os.path.join(filename, entry)) as f:
                texts.append(f.read())
        if not texts:
            raise SystemExit(f"error: no manifests in directory {filename!r}")
    else:
        with open(filename) as f:
            texts = [f.read()]
    docs: List[Dict] = []
    for text in texts:
        for doc in yaml.safe_load_all(text):
            if not doc:
                continue
            if doc.get("kind") == "List":
                docs.extend(doc.get("items", []))
            else:
                docs.append(doc)
    return docs


def resource_for_kind(kind: str) -> str:
    for name, info in RESOURCES.items():
        if info.kind == kind and name == info.name:
            return name
    raise SystemExit(f"error: no resource for kind {kind!r}")


# ---------------------------------------------------------------------------
# Commands
# ---------------------------------------------------------------------------


def cmd_get(client: Client, args) -> int:
    resource = resolve_resource(args.resource)
    watching = getattr(args, "watch", False) or getattr(args, "watch_only", False)
    ns = "" if args.all_namespaces else args.namespace
    # A named get narrows both the list and the watch server-side.
    fsel = f"metadata.name={args.name}" if args.name else ""
    version = 0
    printed_header = False
    if args.name and not watching:
        obj = client.get(resource, args.name, namespace=args.namespace)
        print_objs(resource, [obj], args.output)
        return 0
    if not getattr(args, "watch_only", False):
        objs, version = client.list(
            resource,
            namespace=ns,
            label_selector=args.selector or "",
            field_selector=fsel,
        )
        print_objs(resource, objs, args.output)
        printed_header = bool(objs)
    if not watching:
        return 0
    # --watch / --watch-only (reference: get.go:79-143 WatchLoop):
    # stream changes after the listed resourceVersion, one row per
    # event. Ctrl-C ends the loop.
    stream = client.watch(
        resource,
        namespace=ns,
        since=int(version or 0),
        label_selector=args.selector or "",
        field_selector=fsel,
    )
    limit = getattr(args, "watch_events", None)  # test hook
    seen = 0
    try:
        for event in stream:
            wire = event.object
            if not isinstance(wire, dict) or event.type == "ERROR":
                continue
            obj = serde.from_wire(RESOURCES[resource].cls, wire)
            if args.output == "table":
                # One header for the whole stream (kubectl appends
                # rows, it doesn't reprint the header per event).
                print_table(resource, [obj], header=not printed_header)
                printed_header = True
            else:
                print_objs(resource, [obj], args.output)
            sys.stdout.flush()
            seen += 1
            if limit is not None and seen >= limit:
                break
    except KeyboardInterrupt:
        pass
    finally:
        stream.close()
    return 0


def cmd_create(client: Client, args) -> int:
    count = 0
    for wire in load_manifests(args.filename):
        resource = resource_for_kind(wire.get("kind", ""))
        out = client.create(resource, wire, namespace=args.namespace)
        print(f"{resource}/{out.metadata.name} created")
        count += 1
    if count == 0:
        print("error: no objects in input", file=sys.stderr)
        return 1
    return 0


def cmd_apply(client: Client, args) -> int:
    """Create-or-update (kubectl apply shape)."""
    for wire in load_manifests(args.filename):
        resource = resource_for_kind(wire.get("kind", ""))
        name = wire.get("metadata", {}).get("name", "")
        try:
            client.create(resource, wire, namespace=args.namespace)
            print(f"{resource}/{name} created")
        except APIError as e:
            if e.code != 409:
                raise
            wire.setdefault("metadata", {}).pop("resourceVersion", None)
            client.update(resource, wire, namespace=args.namespace)
            print(f"{resource}/{name} configured")
    return 0


def cmd_delete(client: Client, args) -> int:
    grace = getattr(args, "grace_period", None)
    if args.filename:
        for wire in load_manifests(args.filename):
            resource = resource_for_kind(wire.get("kind", ""))
            name = wire.get("metadata", {}).get("name", "")
            client.delete(
                resource, name, namespace=args.namespace,
                grace_period_seconds=grace,
            )
            print(f"{resource}/{name} deleted")
        return 0
    if args.resource and args.name and getattr(args, "selector", None):
        # kubectl errors on NAME + -l: a selector meant as a safety
        # scope must never be silently ignored.
        raise SystemExit("error: delete takes a NAME or -l SELECTOR, not both")
    if args.resource and not args.name and getattr(args, "selector", None):
        # Selector-based delete (reference: delete.go over the
        # builder's selector path).
        resource = resolve_resource(args.resource)
        objs, _ = client.list(
            resource, namespace=args.namespace, label_selector=args.selector
        )
        if not objs:
            print(f"No resources found matching -l {args.selector}")
            return 0
        for o in objs:
            client.delete(
                resource, o.metadata.name, namespace=args.namespace,
                grace_period_seconds=grace,
            )
            print(f"{resource}/{o.metadata.name} deleted")
        return 0
    if not args.resource or not args.name:
        raise SystemExit(
            "error: delete requires RESOURCE NAME, RESOURCE -l SELECTOR, "
            "or -f FILE"
        )
    resource = resolve_resource(args.resource)
    client.delete(
        resource, args.name, namespace=args.namespace,
        grace_period_seconds=grace,
    )
    print(f"{resource}/{args.name} deleted")
    return 0


def cmd_describe(client: Client, args) -> int:
    """reference: describe.go — object + its events."""
    resource = resolve_resource(args.resource)
    obj = client.get(resource, args.name, namespace=args.namespace)
    wire = serde.to_wire(obj)
    print(yaml.safe_dump(wire, sort_keys=False).rstrip())
    try:
        events, _ = client.list(
            "events",
            namespace=args.namespace,
            field_selector=f"involvedObject.name={args.name}",
        )
    except APIError:
        events = []
    if events:
        print("\nEvents:")
        for e in events[-10:]:
            print(f"  {e.reason}\t{e.message}")
    return 0


def cmd_scale(client: Client, args) -> int:
    """reference: scale.go (conflict-retrying Scaler)."""
    from kubernetes_tpu.cli.updater import Scaler

    resource = resolve_resource(args.resource)
    if resource != "replicationcontrollers":
        raise SystemExit("error: scale only supports replicationcontrollers")
    Scaler(client).scale(args.name, args.replicas, namespace=args.namespace)
    print(f"replicationcontroller/{args.name} scaled to {args.replicas}")
    return 0


def cmd_rolling_update(client: Client, args) -> int:
    """reference: pkg/kubectl/cmd/rollingupdate.go + rolling_updater.go.

    Two modes, like the reference: `-f new-rc.json` (explicit new RC
    with a different selector) or `--image` (derive the new RC from the
    old one, adding a deployment-key label to keep selectors disjoint).
    """
    import hashlib

    from kubernetes_tpu.cli.updater import RollingUpdater, UpdateTimeout
    from kubernetes_tpu.models.objects import ReplicationController

    if bool(args.filename) == bool(args.image):
        raise SystemExit("error: exactly one of -f or --image is required")
    if args.filename:
        manifests = load_manifests(args.filename)
        if len(manifests) != 1 or manifests[0].get("kind") != "ReplicationController":
            raise SystemExit("error: -f must contain exactly one ReplicationController")
        new_rc = serde.from_wire(ReplicationController, manifests[0])
    else:
        old = client.get(
            "replicationcontrollers", args.name, namespace=args.namespace
        )
        new_rc = serde.from_wire(ReplicationController, serde.to_wire(old))
        new_rc.metadata.resource_version = ""
        new_rc.metadata.uid = ""
        if new_rc.spec.template is None or not new_rc.spec.template.spec.containers:
            raise SystemExit("error: old RC has no pod template containers")
        new_rc.spec.template.spec.containers[0].image = args.image
        key = hashlib.sha1(args.image.encode()).hexdigest()[:8]
        new_rc.metadata.name = f"{args.name}-{key}"
        # Deployment-key label keeps the two selectors disjoint
        # (rolling_updater.go AddDeploymentKeyToReplicationController).
        new_rc.spec.selector = dict(new_rc.spec.selector or {})
        new_rc.spec.selector["deployment"] = key
        new_rc.spec.template.metadata.labels = dict(
            new_rc.spec.template.metadata.labels or {}
        )
        new_rc.spec.template.metadata.labels["deployment"] = key
    updater = RollingUpdater(
        client,
        poll_interval=args.poll_interval,
        timeout=args.timeout,
        progress=lambda msg: print(msg),
    )
    try:
        survivor = updater.update(args.name, new_rc, namespace=args.namespace)
    except UpdateTimeout as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"replicationcontroller/{survivor} rolling updated")
    return 0


def cmd_stop(client: Client, args) -> int:
    """reference: pkg/kubectl/cmd/stop.go (reapers drain before
    deleting)."""
    from kubernetes_tpu.cli.updater import Reaper, UpdateTimeout

    resource = resolve_resource(args.resource)
    try:
        Reaper(client, timeout=args.timeout).stop(
            resource, args.name, namespace=args.namespace
        )
    except UpdateTimeout as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(f"{resource}/{args.name} stopped")
    return 0


def cmd_label(client: Client, args) -> int:
    resource = resolve_resource(args.resource)
    obj = client.get(resource, args.name, namespace=args.namespace)
    for kv in args.labels:
        if "=" in kv:
            k, v = kv.split("=", 1)
            if obj.metadata.labels.get(k) is not None and not args.overwrite:
                raise SystemExit(
                    f"error: label {k!r} already set; use --overwrite"
                )
            obj.metadata.labels[k] = v
        elif kv.endswith("-"):
            obj.metadata.labels.pop(kv[:-1], None)
        else:
            raise SystemExit(f"error: bad label spec {kv!r}")
    client.update(resource, obj, namespace=args.namespace)
    print(f"{resource}/{args.name} labeled")
    return 0


def cmd_expose(client: Client, args) -> int:
    """reference: expose.go — make a Service fronting an RC."""
    resource = resolve_resource(args.resource)
    if resource != "replicationcontrollers":
        raise SystemExit(f"error: cannot expose {resource}; only replicationcontrollers")
    rc = client.get("replicationcontrollers", args.name, namespace=args.namespace)
    svc = {
        "kind": "Service",
        "metadata": {"name": args.service_name or args.name},
        "spec": {
            "selector": dict(rc.spec.selector),
            "ports": [{"port": args.port, "targetPort": args.target_port or args.port}],
        },
    }
    out = client.create("services", svc, namespace=args.namespace)
    print(f"services/{out.metadata.name} exposed")
    return 0


def cmd_run(client: Client, args) -> int:
    """reference: run.go — create an RC running an image."""
    rc = {
        "kind": "ReplicationController",
        "metadata": {"name": args.name},
        "spec": {
            "replicas": args.replicas,
            "selector": {"run": args.name},
            "template": {
                "metadata": {"labels": {"run": args.name}},
                "spec": {
                    "containers": [
                        {
                            "name": args.name,
                            "image": args.image,
                            "resources": {
                                "limits": {"cpu": args.cpu, "memory": args.memory}
                            },
                        }
                    ]
                },
            },
        },
    }
    out = client.create("replicationcontrollers", rc, namespace=args.namespace)
    print(f"replicationcontrollers/{out.metadata.name} created")
    return 0


def cmd_logs(client: Client, args) -> int:
    """Reference: pkg/kubectl/cmd/log.go — fetch container logs via the
    apiserver's pod log subresource; -f polls for new lines until the
    pod disappears or the user interrupts."""
    if not getattr(args, "follow", False):
        out = client.pod_logs(
            args.name,
            namespace=args.namespace,
            container=args.container or "",
            tail=args.tail,
        )
        sys.stdout.write(out)
        if out and not out.endswith("\n"):
            sys.stdout.write("\n")
        return 0
    import time as _time

    # Char-offset diffing (not line counts): a poll that catches a
    # partially-written last line must emit the rest on the next poll,
    # not lose it. Each poll refetches the full log through the relay —
    # the subresource has no offset parameter; acceptable for the dev
    # clusters this CLI drives.
    seen = 0
    rounds = 0
    fetched = False
    limit = getattr(args, "follow_rounds", None)  # test hook
    while True:
        try:
            text = client.pod_logs(
                args.name, namespace=args.namespace,
                container=args.container or "",
            )
            if not fetched:
                fetched = True
                if args.tail is not None:
                    # Honor --tail on the first emission: skip
                    # everything before the last N lines.
                    cut = text.splitlines(keepends=True)[-args.tail:]
                    seen = len(text) - sum(len(c) for c in cut)
            if len(text) < seen:
                seen = 0  # log truncated/rotated: re-emit
            sys.stdout.write(text[seen:])
            sys.stdout.flush()
            seen = len(text)
            rounds += 1
            if limit is not None and rounds >= limit:
                return 0
            _time.sleep(0.5)
        except APIError as e:
            if e.code == 404 and fetched:
                return 0  # pod gone mid-stream: clean end
            raise  # never-seen pod: surface the error like plain logs
        except KeyboardInterrupt:
            return 0


def cmd_exec(client: Client, args) -> int:
    """Reference: pkg/kubectl/cmd/exec.go — run a command in a
    container (JSON run-exec; no tty streaming)."""
    result = client.pod_exec(
        args.name,
        args.cmd,
        namespace=args.namespace,
        container=args.container or "",
    )
    output = result.get("output", "")
    sys.stdout.write(output)
    if output and not output.endswith("\n"):
        sys.stdout.write("\n")
    return int(result.get("exitCode", 0))


def forward_port(
    server: str,
    pod: str,
    local_port: int,
    remote_port: int,
    namespace: str = "default",
    ready_event=None,
    stop_event=None,
    headers: Optional[Dict[str, str]] = None,
):
    """Listen on local_port; tunnel each connection through the
    apiserver's pod portforward subresource (websocket) to the pod.
    Reference: pkg/kubectl/cmd/portforward.go + pkg/client/portforward.
    Runs until stop_event is set (or forever). `headers` carry the
    kubeconfig's auth to the handshake."""
    import socket
    import threading
    import urllib.parse as _up

    from kubernetes_tpu.utils import websocket as ws

    if "//" not in server:
        # Same scheme-less tolerance HTTPTransport has ("localhost:8001").
        server = "http://" + server
    parsed = _up.urlparse(server)
    if parsed.scheme == "https":
        raise SystemExit("error: port-forward does not support https servers")
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    listener.bind(("127.0.0.1", local_port))
    listener.listen(16)
    listener.settimeout(0.2)
    bound_port = listener.getsockname()[1]
    if ready_event is not None:
        ready_event.port = bound_port
        ready_event.set()

    def tunnel(conn):
        try:
            upstream = ws.WebSocketClient(
                parsed.hostname,
                parsed.port or 80,
                f"/api/v1/namespaces/{namespace}/pods/{pod}/portforward"
                f"?port={remote_port}",
                headers=headers,
            )
        except (ConnectionError, OSError):
            conn.close()
            return
        upstream.clear_timeout()
        ws.relay_ws_tcp(upstream, conn)

    try:
        while stop_event is None or not stop_event.is_set():
            try:
                conn, _ = listener.accept()
            except socket.timeout:
                continue
            threading.Thread(target=tunnel, args=(conn,), daemon=True).start()
    finally:
        listener.close()


def cmd_port_forward(client: Client, args) -> int:
    local_s, _, remote_s = args.ports.partition(":")
    if not remote_s:
        remote_s = local_s
    print(
        f"Forwarding 127.0.0.1:{local_s} -> {args.name}:{remote_s} "
        "(Ctrl-C to stop)"
    )
    try:
        forward_port(
            args.server, args.name, int(local_s), int(remote_s),
            namespace=args.namespace,
            headers=getattr(args, "_auth_headers", None),
        )
    except KeyboardInterrupt:
        print("stopped")
    return 0


def cmd_top(client: Client, args) -> int:
    """Live resource usage, heapster-era style: scrape every node's
    kubelet /stats THROUGH the apiserver node proxy (reference:
    cluster/addons/cluster-monitoring pulls cadvisor stats via the
    master; kubectl top arrived with that pipeline)."""
    import json as _json
    import urllib.error
    import urllib.request

    if args.what == "cluster":
        return _cmd_top_cluster(client, args)
    if args.what == "capacity":
        return _cmd_top_capacity(client, args)
    if args.what == "health":
        return _cmd_top_health(client, args)
    nodes, _ = client.list("nodes")
    node_util = {}
    if args.what == "nodes":
        # UTIL% rides the capacity plane's per-node utilization (the
        # scheduler's own staged occupancy view) rather than a second
        # kubelet scrape — one sample source, no extra round-trips.
        try:
            cap = _fetch_capacity_report(client, args)
            if cap.get("sampled"):
                node_util = cap.get("node_utilization", {}) or {}
        except Exception:
            node_util = {}
        print(f"{'NAME':20}{'PODS':6}{'RSS':>12}{'DISK-USED':>11}{'UTIL%':>8}")
    else:
        print(f"{'POD-UID':38}{'CONTAINER':14}{'STATE':10}{'RSS':>12}{'RESTARTS':>9}")
    for node in nodes:
        stats = None
        if args.server:
            url = (
                f"{args.server}/api/v1/nodes/{node.metadata.name}"
                "/proxy/stats"
            )
            try:
                with urllib.request.urlopen(url, timeout=10) as resp:
                    stats = _json.loads(resp.read())
            except (urllib.error.URLError, OSError) as e:
                print(
                    f"# {node.metadata.name}: unreachable ({e})",
                    file=sys.stderr,
                )
        if args.what == "nodes":
            # Kubelet stats may be unreachable (or there is no HTTP
            # server at all — injected transport): the scheduler-side
            # UTIL% column still renders, the kubelet columns dash out.
            pods = (stats or {}).get("pods", {})
            rss = sum(
                c.get("rssBytes", 0) for cs in pods.values() for c in cs
            )
            disk = (stats or {}).get("disk", {}).get("usedFraction", 0)
            # Binding-resource utilization: the max of cpu/mem/pods
            # ratios — the one that fills first is the one that blocks
            # the next placement.
            util = node_util.get(node.metadata.name)
            util_s = f"{max(util):.0%}" if util else "-"
            print(
                f"{node.metadata.name:20}"
                f"{len(pods) if stats else '-':<6}"
                f"{_human_bytes(rss) if stats else '-':>12}"
                f"{f'{disk:.0%}' if stats else '-':>10}{util_s:>8}"
            )
        else:
            if stats is None:
                continue
            pods = stats.get("pods", {})
            for uid, containers in sorted(pods.items()):
                for c in containers:
                    print(
                        f"{uid:38}{c.get('name', ''):14}"
                        f"{c.get('state', ''):10}"
                        f"{_human_bytes(c.get('rssBytes', 0)):>12}"
                        f"{c.get('restartCount', 0):>9}"
                    )
    return 0


def _human_bytes(n: int) -> str:
    for unit in ("B", "Ki", "Mi", "Gi"):
        if n < 1024 or unit == "Gi":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n}B"


def cmd_api_resources(client: Client, args) -> int:
    seen = set()
    print(f"{'NAME':32}{'NAMESPACED':12}KIND")
    for name, info in sorted(RESOURCES.items()):
        if info.name in seen or name != info.name:
            continue
        seen.add(info.name)
        print(f"{info.name:32}{str(info.namespaced).lower():12}{info.kind}")
    return 0


def _server_get_json(args, path: str) -> Dict:
    import urllib.request

    req = urllib.request.Request(
        f"{args.server}{path}", headers=getattr(args, "_auth_headers", {}) or {}
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read().decode())


def cmd_version(client: Client, args) -> int:
    """Reference: pkg/kubectl/cmd/version.go — client and server
    versions."""
    from kubernetes_tpu import __version__

    print(f"Client Version: {__version__}")
    if not args.server:
        # Injected in-process transport: the "server" is this process.
        print(f"Server Version: {__version__} (tpu)")
        return 0
    try:
        info = _server_get_json(args, "/version")
    except Exception as e:
        print(f"error: couldn't read version from server: {e}", file=sys.stderr)
        return 1
    print(f"Server Version: {info.get('gitVersion', '?')} ({info.get('platform', '')})")
    return 0


def cmd_api_versions(client: Client, args) -> int:
    """Reference: pkg/kubectl/cmd/apiversions.go."""
    if not args.server:
        from kubernetes_tpu.models import conversion

        print("Available Server Api Versions:", ",".join(conversion.VERSIONS))
        return 0
    try:
        info = _server_get_json(args, "/api")
    except Exception as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print("Available Server Api Versions:", ",".join(info.get("versions", [])))
    return 0


def cmd_cluster_info(client: Client, args) -> int:
    """Reference: pkg/kubectl/cmd/clusterinfo.go — master address plus
    any services labeled kubernetes.io/cluster-service=true."""
    print(f"Kubernetes master is running at {args.server}")
    services, _ = client.list(
        "services", namespace="", label_selector="kubernetes.io/cluster-service=true"
    )
    for svc in services:
        ns, name = svc.metadata.namespace, svc.metadata.name
        print(
            f"{name} is running at {args.server}"
            f"/api/v1/namespaces/{ns}/services/{name}/proxy"
        )
    return 0


def cmd_namespace(client: Client, args) -> int:
    """Reference: pkg/kubectl/cmd/namespace.go — show or set the
    default namespace recorded in the kubeconfig's current context."""
    from kubernetes_tpu.client import kubeconfig as kc

    path = kc.config_path(args.kubeconfig)
    data = kc.load_raw(path)
    current = args.context or data.get("current-context", "")
    if not args.ns:
        ctx = kc._by_name(data.get("contexts"), current) or {}
        print(ctx.get("context", {}).get("namespace") or "default")
        return 0
    if not current:
        print("error: no current context to set the namespace on", file=sys.stderr)
        return 1
    kc.set_entry(data, "contexts", current, "context", {"namespace": args.ns})
    kc.save_raw(path, data)
    print(f'Set default namespace to "{args.ns}" in context "{current}"')
    return 0


def cmd_update(client: Client, args) -> int:
    """Reference: pkg/kubectl/cmd/update.go — full replace from -f, or
    a merge patch with --patch."""
    if bool(args.filename) == bool(args.patch):
        raise SystemExit("error: exactly one of -f or --patch is required")
    if args.patch:
        if not (args.resource and args.name):
            raise SystemExit("error: --patch requires RESOURCE NAME")
        resource = resolve_resource(args.resource)
        try:
            patch = json.loads(args.patch)
        except json.JSONDecodeError as e:
            raise SystemExit(f"error: --patch is not valid JSON: {e}")
        client.patch(resource, args.name, patch, namespace=args.namespace)
        print(f"{resource}/{args.name} updated")
        return 0
    for wire in load_manifests(args.filename):
        resource = resource_for_kind(wire.get("kind", ""))
        name = wire.get("metadata", {}).get("name", "")
        client.update(resource, wire, namespace=args.namespace)
        print(f"{resource}/{name} updated")
    return 0


class _ProxyServer:
    """`ktctl proxy` — a local HTTP relay to the apiserver carrying the
    kubeconfig's credentials (pkg/kubectl/proxy.go, cmd/proxy.go):
    lets credential-less local tools browse the API."""

    def __init__(self, server: str, headers: Dict[str, str],
                 host: str = "127.0.0.1", port: int = 8001,
                 api_prefix: str = "/api"):
        import http.server
        import socketserver
        import urllib.error
        import urllib.request

        upstream = server.rstrip("/")
        prefix = api_prefix.rstrip("/") or "/api"

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):  # noqa: N802
                pass

            def _relay(self, verb: str) -> None:
                if not (self.path.startswith(prefix + "/") or self.path == prefix
                        or self.path.startswith(("/version", "/healthz", "/swagger"))):
                    self.send_error(404, "not proxied")
                    return
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else None
                req = urllib.request.Request(
                    upstream + self.path, data=body, method=verb,
                    headers={**headers, "Content-Type": "application/json"},
                )
                try:
                    resp = urllib.request.urlopen(req, timeout=30)
                    code, payload = resp.status, resp.read()
                except urllib.error.HTTPError as e:
                    code, payload = e.code, e.read()
                except (urllib.error.URLError, OSError) as e:
                    # Apiserver unreachable: answer 502 instead of
                    # resetting the client's connection.
                    code = 502
                    payload = json.dumps(
                        {
                            "kind": "Status",
                            "status": "Failure",
                            "reason": "BadGateway",
                            "message": f"apiserver unreachable: {e}",
                        }
                    ).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802
                self._relay("GET")

            def do_POST(self):  # noqa: N802
                self._relay("POST")

            def do_PUT(self):  # noqa: N802
                self._relay("PUT")

            def do_DELETE(self):  # noqa: N802
                self._relay("DELETE")

            def do_PATCH(self):  # noqa: N802
                self._relay("PATCH")

        class Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True

        self.httpd = Server((host, port), Handler)

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def serve_background(self):
        import threading

        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def cmd_proxy(client: Client, args) -> int:
    srv = _ProxyServer(
        args.server,
        getattr(args, "_auth_headers", {}) or {},
        port=args.port,
        api_prefix=args.api_prefix,
    )
    print(f"Starting to serve on 127.0.0.1:{srv.port}")
    try:
        srv.httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


def cmd_trace(client: Client, args) -> int:
    """Render recent scheduling traces as span trees (the CLI face of
    GET /debug/traces): `ktctl trace <pod>` shows every trace that
    touched the pod — enqueue through bind — with durations."""
    from kubernetes_tpu.utils import tracing

    transport = client.t
    get_json = getattr(transport, "get_json", None)
    if get_json is not None:
        data = get_json(
            "/debug/traces",
            query={"pod": args.name or "", "limit": str(args.limit)},
        )
    else:
        # Transport without a raw-GET surface (LocalTransport: the
        # injected in-process client of tests/embedding) — the trace
        # buffer is process-local, read it directly.
        data = tracing.DEFAULT_BUFFER.to_dicts(
            pod=args.name or "", limit=args.limit
        )
    traces = data.get("traces", [])
    if not traces:
        # Clean nonzero exit, nothing on stdout: a script piping this
        # must see the miss, not an empty tree.
        if args.name:
            print(
                f'no trace recorded for pod "{args.name}"', file=sys.stderr
            )
        else:
            print("no traces recorded", file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(data, indent=2))
        return 0
    if args.output == "yaml":
        print(yaml.safe_dump(data, default_flow_style=False))
        return 0
    for tr in traces:
        print(tracing.format_trace(tr))
    return 0


def cmd_explain(client: Client, args) -> int:
    """`ktctl explain pod <name>` — the CLI face of the scheduling
    flight recorder (GET /debug/decisions): why the pod landed where it
    did (winner + score decomposition), or a per-node table of "why
    not" predicate reasons when it is stuck, plus any preemption
    verdict (nominated node / victims)."""
    from kubernetes_tpu.utils import flightrecorder

    resource = resolve_resource(args.resource)
    if resource != "pods":
        raise SystemExit("error: explain supports pods only")
    key = f"{args.namespace}/{args.name}"
    transport = client.t
    get_json = getattr(transport, "get_json", None)
    if get_json is not None:
        data = get_json(
            "/debug/decisions",
            query={"pod": key, "limit": str(args.limit)},
        )
    else:
        # Injected in-process transport (LocalTransport): the recorder
        # is process-local, read it directly — same as `ktctl trace`.
        data = flightrecorder.DEFAULT.decisions(pod=key, limit=args.limit)
    decisions = data.get("decisions", [])
    if not decisions:
        print(
            f'no decision recorded for pod "{args.name}"', file=sys.stderr
        )
        return 1
    if args.output == "json":
        print(json.dumps(data, indent=2))
        return 0
    if args.output == "yaml":
        print(yaml.safe_dump(data, default_flow_style=False))
        return 0
    for d in decisions:
        print(flightrecorder.format_decision(d))
    return 0


def _fetch_slo_report(client: Client, args) -> Dict:
    """The SLO report: GET /debug/slo over HTTP transports, or the
    process-local engine for injected LocalTransport clients (same
    split as `ktctl trace` / `ktctl explain`)."""
    transport = client.t
    get_json = getattr(transport, "get_json", None)
    if get_json is not None:
        return get_json("/debug/slo")
    from kubernetes_tpu.utils import slo

    return slo.evaluate()


def _render_slo_table(report: Dict) -> List[str]:
    lines = [
        f"{'OBJECTIVE':24}{'SERIES':34}{'P50':>9}{'P99':>9}"
        f"{'TARGET':>9}{'SAMPLES':>9}  VERDICT"
    ]
    for o in report.get("objectives", ()):
        series = o.get("series", "")
        labels = o.get("labels") or {}
        if labels:
            inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
            series = f"{series}{{{inner}}}"

        def num(v):
            return "-" if v is None else f"{v:.4g}"

        lines.append(
            f"{o.get('name', ''):24}{series:34}"
            f"{num(o.get('p50')):>9}"
            f"{num(o.get('p99', o.get('value'))):>9}"
            f"{num(o.get('target')):>9}"
            f"{o.get('samples', 0):>9}  {o.get('verdict', '')}"
        )
    lines.append(f"overall: {report.get('verdict', 'no_data')}")
    return lines


def cmd_slo(client: Client, args) -> int:
    """`ktctl slo` — per-objective service-level verdicts from the SLO
    engine (GET /debug/slo): pod-startup milestone watermarks, watch
    fan-out lag, and solver device telemetry with pass/warn/burn
    verdicts. Exits 1 with 'no SLI samples recorded' when no objective
    has samples yet (mirror of the trace/explain miss contract)."""
    report = _fetch_slo_report(client, args)
    if not report.get("sampled"):
        # Clean nonzero exit, empty stdout: a script gating on SLOs
        # must see that nothing has been measured, not a hollow pass.
        print("no SLI samples recorded", file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(report, indent=2))
        return 0
    if args.output == "yaml":
        print(yaml.safe_dump(report, default_flow_style=False))
        return 0
    for line in _render_slo_table(report):
        print(line)
    return 0


def _fmt_qty(v) -> str:
    """Human-compact engineering figure for ledger table cells."""
    if v is None:
        return "-"
    v = float(v)
    for unit, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.2f}{unit}"
    return f"{v:.4g}"


def cmd_profile(client: Client, args) -> int:
    """`ktctl profile [kernels|cpu|device]` — the device-time profiling
    plane's CLI face:

    - kernels: the XLA compile/cost ledger (GET /debug/kernels) — one
      row per jitted kernel with compile counts/wall and the harvested
      cost/memory analysis. Exits 1 with 'no compiles recorded' on a
      cold process (the trace/explain/slo miss contract).
    - cpu: the wall-clock sampling profiler (GET /debug/profile),
      --format collapsed emits flamegraph.pl/speedscope folded stacks.
    - device: an on-demand jax.profiler device trace
      (GET /debug/device-profile?seconds=N); prints the server-side
      trace directory.
    """
    transport = client.t
    get_json = getattr(transport, "get_json", None)
    if args.what in ("cpu", "device") and hasattr(transport, "timeout"):
        # The capture blocks the handler for --seconds; the transport's
        # default 30s socket timeout would sever a longer capture
        # mid-flight (and the server-side trace would keep running,
        # 409-ing the retry).
        transport.timeout = max(transport.timeout, args.seconds + 30.0)
    if args.what == "kernels":
        if get_json is not None:
            data = get_json("/debug/kernels")
        else:
            # Injected in-process transport (LocalTransport): the
            # ledger is process-local — read it via sys.modules so a
            # process that never dispatched a kernel (ledger module
            # never imported) reports the miss without loading jax.
            led = sys.modules.get("kubernetes_tpu.ops.ledger")
            data = (
                led.DEFAULT.to_dict()
                if led is not None
                else {"kernels": [], "summary": {"compiles": 0}}
            )
        rows = data.get("kernels", [])
        if not rows:
            # Clean nonzero exit, empty stdout: a script gating on the
            # ledger must see that nothing compiled, not a hollow table.
            print("no compiles recorded", file=sys.stderr)
            return 1
        if args.output == "json":
            print(json.dumps(data, indent=2))
            return 0
        if args.output == "yaml":
            print(yaml.safe_dump(data, default_flow_style=False))
            return 0
        print(
            f"{'KERNEL':44}{'CALLS':>7}{'COMPILES':>9}{'COMPILE_S':>10}"
            f"{'FLOPS':>9}{'BYTES':>9}{'AI':>7}  {'CONTRACT':9} COMM"
        )
        mismatches = []
        for r in rows:
            shapes = r.get("shapes", ())

            def peak(metric):
                vals = [
                    s.get(metric) for s in shapes
                    if s.get(metric) is not None
                ]
                return max(vals) if vals else None

            # Declared-vs-observed staged shapes (ops/contracts.py):
            # the worst verdict across this kernel's shape rows — one
            # drifted bucket marks the kernel, details listed below.
            verdicts = [s.get("contract") for s in shapes]
            if any(v and v.startswith("mismatch") for v in verdicts):
                contract = "MISMATCH"
                mismatches.extend(
                    (r["kernel"], s.get("signature", ""), s["contract"])
                    for s in shapes
                    if (s.get("contract") or "").startswith("mismatch")
                )
            elif "uncontracted" in verdicts:
                contract = "uncontracted"
            elif verdicts and all(v == "ok" for v in verdicts):
                contract = "ok"
            else:
                contract = "-"
            # Collective-inventory verdict (harvest-attached; same
            # worst-across-shape-rows logic): DRIFT when any staged
            # bucket compiled an undeclared collective kind.
            comms = [s.get("collectives_verdict") for s in shapes]
            if any(v and v.startswith("drift") for v in comms):
                comm = "DRIFT"
                mismatches.extend(
                    (r["kernel"], s.get("signature", ""),
                     s["collectives_verdict"])
                    for s in shapes
                    if (s.get("collectives_verdict") or "").startswith(
                        "drift"
                    )
                )
            elif comms and all(v == "ok" for v in comms):
                comm = "ok"
            elif "uncontracted" in comms:
                comm = "uncontracted"
            else:
                comm = "-"
            ai = peak("arithmetic_intensity")
            print(
                f"{r['kernel']:44}{r.get('calls', 0):>7}"
                f"{r.get('compiles', 0):>9}"
                f"{r.get('compile_seconds', 0.0):>10.3f}"
                f"{_fmt_qty(peak('flops')):>9}"
                f"{_fmt_qty(peak('bytes_accessed')):>9}"
                f"{'-' if ai is None else f'{ai:.2f}':>7}  "
                f"{contract:9} {comm}"
            )
        for kernel, signature, verdict in mismatches:
            print(f"  {kernel} {signature}: {verdict}")
        summary = data.get("summary", {})
        print(
            f"total: {summary.get('compiles', 0)} compiles, "
            f"{summary.get('compile_seconds_total', 0.0)}s compiling, "
            f"{summary.get('pending_cost_rows', 0)} cost rows pending"
        )
        return 0
    if args.what == "cpu":
        get_text = getattr(transport, "get_text", None)
        if get_text is not None:
            body = get_text(
                "/debug/profile",
                query={"seconds": str(args.seconds), "format": args.fmt},
            )
        else:
            from kubernetes_tpu.utils import debug

            body = debug.sample_profile(seconds=args.seconds, fmt=args.fmt)
        sys.stdout.write(body)
        return 0
    # device
    if get_json is not None:
        info = get_json(
            "/debug/device-profile", query={"seconds": str(args.seconds)}
        )
    else:
        from kubernetes_tpu.utils import profiler

        try:
            info = profiler.capture_device_trace(seconds=args.seconds)
        except (profiler.TraceInProgress, profiler.ProfilerUnavailable) as e:
            # Same one-line contract the HTTP path gets via 409/503 ->
            # APIError; a traceback is not an error message.
            print(f"error: {e}", file=sys.stderr)
            return 1
    if args.output == "json":
        print(json.dumps(info, indent=2))
        return 0
    print(
        f"device trace: {info.get('seconds')}s captured to "
        f"{info.get('dir')} ({len(info.get('files', []))} files)"
    )
    return 0


#: /metrics series prefixes `ktctl top cluster` surfaces (the telemetry
#: plane's device/solver/watch families, not the whole exposition).
_TOP_CLUSTER_PREFIXES = (
    "pod_startup_latency_seconds",
    "watch_fanout_lag_versions",
    "watch_streams_dropped_total",
    "watch_stream_queue_depth",
    "scheduler_informer_staleness_seconds",
    "solver_device_transfer_bytes_total",
    "solver_xla_",
    "device_memory_bytes",
    "cluster_fragmentation_score",
    "cluster_headroom_pods",
    "slice_alloc_success_rate",
    "scheduler_backlog_pressure",
    "capacity_zero_headroom_ticks_total",
)


def _fetch_capacity_report(client: Client, args) -> Dict:
    """The capacity report: GET /debug/capacity over HTTP transports,
    or the process-local monitor for injected LocalTransport clients
    (same split as `ktctl slo` — utils/capacity keeps jax off its
    import path, so the local read is safe in a thin CLI process)."""
    transport = client.t
    get_json = getattr(transport, "get_json", None)
    if get_json is not None:
        return get_json("/debug/capacity")
    from kubernetes_tpu.utils import capacity

    return capacity.DEFAULT.snapshot()


def _cmd_top_capacity(client: Client, args) -> int:
    """`ktctl top capacity` — the capacity & fragmentation plane:
    cluster fragmentation score, per-probe-shape headroom table, top-k
    stranded nodes, and backlog pressure (GET /debug/capacity). Exits 1
    with 'no capacity samples recorded' on a cluster whose scheduler
    has not sampled yet (the trace/explain/slo miss contract)."""
    report = _fetch_capacity_report(client, args)
    if not report.get("sampled"):
        # Clean nonzero exit, empty stdout: a script gating on capacity
        # must see that nothing was measured, not a hollow table.
        print("no capacity samples recorded", file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(report, indent=2))
        return 0
    if args.output == "yaml":
        print(yaml.safe_dump(report, default_flow_style=False))
        return 0
    backlog = report.get("backlog", {})
    print(
        f"fragmentation: {report.get('fragmentation_score', 0.0):.4f}  "
        f"slice-alloc: {report.get('slice_alloc_success_rate', 0.0):.0%}  "
        f"live-nodes: {report.get('live_nodes', 0)}  "
        f"stranded: {report.get('stranded_node_count', 0)}"
    )
    print(
        f"backlog: depth={backlog.get('depth', 0)} "
        f"oldest={backlog.get('oldest_age_s', 0.0):.2f}s "
        f"pressure={backlog.get('pressure', 0.0):.2f}"
    )
    print()
    print(
        f"{'SHAPE':20}{'CPU(m)':>8}{'MEM(MiB)':>10}{'MIN':>5}"
        f"{'HEADROOM':>10}{'FRAG':>8}  ALLOC"
    )
    for p in report.get("probes", ()):
        print(
            f"{p.get('shape', ''):20}{p.get('cpu_milli', 0):>8.0f}"
            f"{p.get('mem_mib', 0):>10.0f}{p.get('min_member', 1):>5}"
            f"{p.get('headroom_pods', 0):>10}"
            f"{p.get('fragmentation', 0.0):>8.3f}"
            f"  {'yes' if p.get('allocatable') else 'NO'}"
        )
    stranded = report.get("stranded_nodes", ())
    if stranded:
        print()
        print(f"{'STRANDED-NODE':20}{'FREE-CPU(m)':>12}{'FREE-MEM(MiB)':>14}")
        for n in stranded:
            print(
                f"{n.get('name', ''):20}{n.get('free_cpu_milli', 0):>12.0f}"
                f"{n.get('free_mem_mib', 0):>14.0f}"
            )
    trend = report.get("trend", ())
    if trend:
        print()
        print(
            f"trend ({len(trend)} samples): "
            + " ".join(f"{v:.3f}" for v in trend[-12:])
        )
    return 0


def _fetch_rebalance_report(client: Client, args) -> Dict:
    """The rebalance report: GET /debug/rebalance over HTTP
    transports, or the process-local monitor for injected
    LocalTransport clients (utils/rebalance keeps jax off its import
    path — same split as the capacity fetch above)."""
    transport = client.t
    get_json = getattr(transport, "get_json", None)
    if get_json is not None:
        return get_json("/debug/rebalance")
    from kubernetes_tpu.utils import rebalance

    return rebalance.DEFAULT.snapshot()


def _fetch_alert_report(client: Client, args) -> Dict:
    """The alert report: GET /debug/alerts over HTTP transports, or
    the process-local engine for injected LocalTransport clients
    (utils/alerts keeps jax off its import path — same split as the
    slo/capacity fetches above)."""
    transport = client.t
    get_json = getattr(transport, "get_json", None)
    if get_json is not None:
        return get_json("/debug/alerts")
    from kubernetes_tpu.utils import alerts

    return alerts.DEFAULT.snapshot()


def cmd_alerts(client: Client, args) -> int:
    """`ktctl alerts` — the burn-rate alerting plane: one row per
    declarative rule with its multi-window multi-burn-rate state
    (inactive/pending/firing/resolved), the observed value against the
    threshold, and the recent transition log (GET /debug/alerts).
    Exits 1 with 'no alert evaluations recorded' until the retention
    sampler has fed the engine at least one evaluation pass (the
    trace/explain/slo miss contract)."""
    report = _fetch_alert_report(client, args)
    if not report.get("sampled"):
        # Clean nonzero exit, empty stdout: a script gating on alerts
        # must see that nothing was evaluated, not a hollow all-clear.
        print("no alert evaluations recorded", file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(report, indent=2))
        return 0
    if args.output == "yaml":
        print(yaml.safe_dump(report, default_flow_style=False))
        return 0

    def num(v):
        return "-" if v is None else f"{v:.4g}"

    print(
        f"{'RULE':26}{'SERIES':34}{'SEVERITY':9}{'STATE':10}"
        f"{'VALUE':>9}{'THRESHOLD':>10}{'SINCE':>8}"
    )
    for r in report.get("rules", ()):
        since = r.get("sinceS")
        print(
            f"{r.get('name', ''):26}{r.get('series', ''):34}"
            f"{r.get('severity', ''):9}{r.get('state', ''):10}"
            f"{num(r.get('value')):>9}{num(r.get('threshold')):>10}"
            f"{'-' if since is None else f'{since:.0f}s':>8}"
        )
    firing = report.get("firing", ())
    print(f"firing: {len(firing)}" + (f" ({' '.join(firing)})" if firing else ""))
    transitions = report.get("transitions", ())
    if transitions:
        print()
        print("RECENT TRANSITIONS")
        for t in transitions[-args.limit:]:
            print(
                f"  {t.get('rule', ''):26}{t.get('from', ''):>9} -> "
                f"{t.get('to', ''):9}value={num(t.get('value'))}"
            )
    return 0


def _fetch_health_rollup(client: Client, args) -> Dict:
    """The HA-aware health rollup: GET /debug/health over HTTP
    transports. For injected LocalTransport clients the server-side
    components (healthz subchecks, replication, leases) have no
    process-local equivalent, so the rollup degrades to the two
    process-global planes — SLO verdicts and alert state."""
    transport = client.t
    get_json = getattr(transport, "get_json", None)
    if get_json is not None:
        return get_json("/debug/health")
    from kubernetes_tpu.utils import alerts, slo

    slo_report = slo.evaluate()
    alert_snap = alerts.DEFAULT.snapshot()
    slo_verdict = slo_report.get("verdict", "no_data")
    components = {
        "slo": {
            "verdict": "pass" if slo_verdict == "no_data" else slo_verdict,
            "sampled": bool(slo_report.get("sampled")),
            "objectivesBurning": [
                o["name"] for o in slo_report.get("objectives", ())
                if o.get("verdict") == "burn"
            ],
        },
        "alerts": {
            "verdict": "burn" if any(
                r.get("state") == "firing" and r.get("severity") == "page"
                for r in alert_snap.get("rules", ())
            ) else ("warn" if alert_snap.get("firing") else "pass"),
            "status": "firing" if alert_snap.get("firing") else "ok",
            "firing": list(alert_snap.get("firing", ())),
        },
    }
    return {
        "kind": "HealthRollup",
        "verdict": slo.worst(*[c["verdict"] for c in components.values()]),
        "sampled": bool(slo_report.get("sampled")) or bool(alert_snap.get("sampled")),
        "components": components,
    }


def _cmd_top_health(client: Client, args) -> int:
    """`ktctl top health` — the HA-aware health rollup: one verdict
    per control-plane component (apiserver subchecks, replication,
    leases, SLO plane, alert plane) folded into an overall cluster
    verdict (GET /debug/health). Exits 1 with 'no health samples
    recorded' until either the SLO or alert plane has measured
    anything (the trace/explain/slo miss contract)."""
    report = _fetch_health_rollup(client, args)
    if not report.get("sampled"):
        # Clean nonzero exit, empty stdout: a script gating on health
        # must see that nothing was measured, not a hollow green board.
        print("no health samples recorded", file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(report, indent=2))
        return 0
    if args.output == "yaml":
        print(yaml.safe_dump(report, default_flow_style=False))
        return 0
    print(f"overall: {report.get('verdict', 'no_data')}")
    print()
    print(f"{'COMPONENT':16}{'VERDICT':9}DETAIL")
    for name, comp in sorted(report.get("components", {}).items()):
        detail = ""
        if name == "replication":
            lag = comp.get("maxFollowerLag")
            detail = (
                f"role={comp.get('role', '')}"
                + (f" max-follower-lag={lag}" if lag is not None else "")
            )
        elif name == "leases":
            stale = [r["name"] for r in comp.get("records", ()) if r.get("stale")]
            detail = (
                f"tracked={len(comp.get('records', ()))}"
                + (f" stale={','.join(stale)}" if stale else "")
            )
        elif name == "slo":
            burning = comp.get("objectivesBurning", ())
            detail = f"burning={','.join(burning)}" if burning else "all objectives ok"
        elif name == "alerts":
            firing = comp.get("firing", ())
            detail = f"firing={','.join(firing)}" if firing else "no alerts firing"
        elif comp.get("status"):
            detail = str(comp["status"])
        print(f"{name:16}{comp.get('verdict', ''):9}{detail}")
    return 0


def cmd_rebalance(client: Client, args) -> int:
    """`ktctl rebalance plan|status` — the rebalancing plane: the
    descheduler's last defrag plan (per-move table) or its cycle
    status (scores, move outcomes, improvement trend). Exits 1 with
    'no rebalance samples recorded' until the first executed defrag
    cycle (the trace/explain/slo/capacity miss contract)."""
    report = _fetch_rebalance_report(client, args)
    if not report.get("sampled"):
        # Clean nonzero exit, empty stdout: a script gating on defrag
        # must see that nothing ran, not a hollow table.
        print("no rebalance samples recorded", file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(report, indent=2))
        return 0
    if args.output == "yaml":
        print(yaml.safe_dump(report, default_flow_style=False))
        return 0
    cycle = report.get("last_cycle", {})
    plan = report.get("last_plan", {})
    if args.what == "plan":
        print(
            f"score: {plan.get('score_before', 0.0):.4f} -> "
            f"{plan.get('score_after', 0.0):.4f} (forecast)  "
            f"budget: {plan.get('move_budget', 0)}  "
            f"movable: {plan.get('movable_pods', 0)}"
        )
        dropped = plan.get("dropped_partial_gangs", ())
        if dropped:
            print("dropped partial gangs: " + " ".join(dropped))
        print()
        print(f"{'POD':32}{'FROM':16}{'TO':16}{'GAIN':>6}  KIND")
        for m in plan.get("moves", ()):
            kind = "gang" if m.get("gang") else (
                "drain" if m.get("forced") else "defrag"
            )
            print(
                f"{m.get('pod', ''):32}{m.get('from', ''):16}"
                f"{m.get('to', ''):16}{m.get('gain', 0):>6}  {kind}"
            )
        return 0
    print(
        f"cycles: {report.get('samples', 0)}  last: "
        f"{cycle.get('score_before', 0.0):.4f} -> "
        f"{cycle.get('score_after', 0.0):.4f} "
        f"(improvement {cycle.get('improvement', 0.0):.4f}, "
        f"{cycle.get('moves_executed', 0)} moves, "
        f"{cycle.get('trigger', '')})"
    )
    outcomes = report.get("outcomes", {})
    if outcomes:
        print(
            "moves: "
            + "  ".join(
                f"{k}={outcomes[k]}" for k in sorted(outcomes)
            )
        )
    trend = report.get("trend", ())
    if trend:
        print(
            f"trend ({len(trend)} cycles): "
            + " ".join(f"{v:.3f}" for v in trend[-12:])
        )
    return 0


def _cmd_top_cluster(client: Client, args) -> int:
    """`ktctl top cluster` — the cluster-level resource view: SLO
    verdict table, the capacity plane's headline row, plus the raw
    telemetry-plane series from /metrics (device memory, transfer
    bytes, compile cache, watch fan-out)."""
    report = _fetch_slo_report(client, args)
    for line in _render_slo_table(report):
        print(line)
    cap = _fetch_capacity_report(client, args)
    if cap.get("sampled"):
        worst = min(
            (p for p in cap.get("probes", ())),
            key=lambda p: p.get("headroom_pods", 0),
            default=None,
        )
        head = (
            f"min-headroom {worst.get('headroom_pods', 0)} pods "
            f"({worst.get('shape', '')})"
            if worst is not None
            else "no probes"
        )
        print()
        print(
            f"CAPACITY  fragmentation={cap.get('fragmentation_score', 0.0):.4f}"
            f"  {head}  stranded-nodes={cap.get('stranded_node_count', 0)}"
        )
    transport = client.t
    if getattr(transport, "get_json", None) is not None and args.server:
        import urllib.request

        req = urllib.request.Request(
            f"{args.server}/metrics",
            headers=getattr(args, "_auth_headers", {}) or {},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            text = resp.read().decode()
    else:
        from kubernetes_tpu.utils import metrics as _metrics

        text = _metrics.DEFAULT.render()
    shown = [
        line
        for line in text.splitlines()
        if not line.startswith("#")
        and line.startswith(_TOP_CLUSTER_PREFIXES)
    ]
    if shown:
        print()
        print("TELEMETRY")
        for line in shown:
            print(line)
    return 0


def cmd_config(client: Client, args) -> int:
    """Reference: pkg/kubectl/cmd/config/ — view / set-cluster /
    set-credentials / set-context / use-context / set / unset over the
    kubeconfig file."""
    from kubernetes_tpu.client import kubeconfig as kc

    path = kc.config_path(args.kubeconfig)
    data = kc.load_raw(path)
    sub = args.config_cmd
    if sub == "view":
        print(json.dumps(data, indent=2, sort_keys=True))
        return 0
    if sub == "use-context":
        if kc._by_name(data.get("contexts"), args.cname) is None:
            print(f'error: no context exists with the name: "{args.cname}"',
                  file=sys.stderr)
            return 1
        data["current-context"] = args.cname
        kc.save_raw(path, data)
        print(f'Switched to context "{args.cname}"')
        return 0
    if sub == "set-cluster":
        body = {}
        if args.server_url:
            body["server"] = args.server_url
        kc.set_entry(data, "clusters", args.cname, "cluster", body)
        kc.save_raw(path, data)
        print(f'Cluster "{args.cname}" set')
        return 0
    if sub == "set-credentials":
        body = {}
        if args.username:
            body["username"] = args.username
        if args.password:
            body["password"] = args.password
        if args.token:
            body["token"] = args.token
        kc.set_entry(data, "users", args.cname, "user", body)
        kc.save_raw(path, data)
        print(f'User "{args.cname}" set')
        return 0
    if sub == "set-context":
        body = {}
        if args.cluster:
            body["cluster"] = args.cluster
        if args.user:
            body["user"] = args.user
        if args.ctx_namespace:
            body["namespace"] = args.ctx_namespace
        kc.set_entry(data, "contexts", args.cname, "context", body)
        kc.save_raw(path, data)
        print(f'Context "{args.cname}" set')
        return 0
    if sub in ("set", "unset"):
        # Dotted-path property access (config/set.go navigation steps);
        # the useful subset: top-level keys like current-context.
        if "." in args.prop:
            print(f"error: only top-level properties supported: {args.prop!r}",
                  file=sys.stderr)
            return 1
        if sub == "set":
            data[args.prop] = args.value
        else:
            data.pop(args.prop, None)
        kc.save_raw(path, data)
        print(f'Property "{args.prop}" {sub}')
        return 0
    raise SystemExit(f"unknown config subcommand {sub!r}")


# ---------------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    # Global flags live on a parent parser attached to every
    # subcommand, so `ktctl get pods -o yaml` parses naturally.
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--server", "-s", default=None)
    common.add_argument("--namespace", "-n", default=None)
    common.add_argument("--kubeconfig", default=None)
    common.add_argument("--context", default=None)
    common.add_argument("--output", "-o", default="table",
                        choices=["table", "json", "yaml", "name"])
    p = argparse.ArgumentParser(prog="ktctl", description="kubernetes-tpu CLI")
    sub = p.add_subparsers(dest="command", required=True)

    g = sub.add_parser("get", parents=[common])
    g.add_argument("resource")
    g.add_argument("name", nargs="?")
    g.add_argument("--selector", "-l")
    g.add_argument("--all-namespaces", "-A", action="store_true")
    g.add_argument("--watch", "-w", action="store_true",
                   help="after listing, watch for changes")
    g.add_argument("--watch-only", action="store_true",
                   help="watch without the initial list")
    g.add_argument("--watch-events", type=int, default=None,
                   help=argparse.SUPPRESS)  # exit after N events (tests)
    g.set_defaults(fn=cmd_get)

    c = sub.add_parser("create", parents=[common])
    c.add_argument("--filename", "-f", required=True)
    c.set_defaults(fn=cmd_create)

    a = sub.add_parser("apply", parents=[common])
    a.add_argument("--filename", "-f", required=True)
    a.set_defaults(fn=cmd_apply)

    d = sub.add_parser("delete", parents=[common])
    d.add_argument("resource", nargs="?")
    d.add_argument("name", nargs="?")
    d.add_argument("--filename", "-f")
    d.add_argument("--selector", "-l")
    d.add_argument(
        "--grace-period", type=int, default=None,
        help="seconds a bound pod stays Terminating before removal "
        "(0 = immediate; pods only)",
    )
    d.set_defaults(fn=cmd_delete)

    ds = sub.add_parser("describe", parents=[common])
    ds.add_argument("resource")
    ds.add_argument("name")
    ds.set_defaults(fn=cmd_describe)

    sc = sub.add_parser("scale", parents=[common])
    sc.add_argument("resource")
    sc.add_argument("name")
    sc.add_argument("--replicas", type=int, required=True)
    sc.set_defaults(fn=cmd_scale)

    lb = sub.add_parser("label", parents=[common])
    lb.add_argument("resource")
    lb.add_argument("name")
    lb.add_argument("labels", nargs="+")
    lb.add_argument("--overwrite", action="store_true")
    lb.set_defaults(fn=cmd_label)

    ex = sub.add_parser("expose", parents=[common])
    ex.add_argument("resource")  # only rc supported
    ex.add_argument("name")
    ex.add_argument("--port", type=int, required=True)
    ex.add_argument("--target-port", type=int)
    ex.add_argument("--service-name")
    ex.set_defaults(fn=cmd_expose)

    rn = sub.add_parser("run", parents=[common])
    rn.add_argument("name")
    rn.add_argument("--image", required=True)
    rn.add_argument("--replicas", "-r", type=int, default=1)
    rn.add_argument("--cpu", default="100m")
    rn.add_argument("--memory", default="64Mi")
    rn.set_defaults(fn=cmd_run)

    ru = sub.add_parser("rolling-update", parents=[common])
    ru.add_argument("name")
    ru.add_argument("--filename", "-f", default=None)
    ru.add_argument("--image", default=None)
    ru.add_argument("--poll-interval", type=float, default=0.2)
    ru.add_argument("--timeout", type=float, default=60.0)
    ru.set_defaults(fn=cmd_rolling_update)

    st = sub.add_parser("stop", parents=[common])
    st.add_argument("resource")
    st.add_argument("name")
    st.add_argument("--timeout", type=float, default=30.0)
    st.set_defaults(fn=cmd_stop)

    lg = sub.add_parser("logs", parents=[common])
    lg.add_argument("name")
    lg.add_argument("--container", "-c", default="")
    lg.add_argument("--tail", type=int, default=None)
    lg.add_argument("--follow", "-f", action="store_true",
                    help="stream new lines until the pod goes away")
    lg.add_argument("--follow-rounds", type=int, default=None,
                    help=argparse.SUPPRESS)  # exit after N polls (tests)
    lg.set_defaults(fn=cmd_logs)

    ee = sub.add_parser("exec", parents=[common])
    ee.add_argument("name")
    ee.add_argument("--container", "-c", default="")
    ee.add_argument("cmd", nargs="+")
    ee.set_defaults(fn=cmd_exec)

    tp = sub.add_parser("top", parents=[common])
    tp.add_argument(
        "what", choices=["nodes", "pods", "cluster", "capacity", "health"]
    )
    tp.set_defaults(fn=cmd_top)

    sl = sub.add_parser("slo", parents=[common])
    sl.set_defaults(fn=cmd_slo)

    al = sub.add_parser("alerts", parents=[common])
    al.add_argument("--limit", type=int, default=16,
                    help="transitions to show, newest last")
    al.set_defaults(fn=cmd_alerts)

    rb = sub.add_parser("rebalance", parents=[common])
    rb.add_argument("what", nargs="?", default="status",
                    choices=["plan", "status"])
    rb.set_defaults(fn=cmd_rebalance)

    pf2 = sub.add_parser("profile", parents=[common])
    pf2.add_argument(
        "what", nargs="?", default="kernels",
        choices=["kernels", "cpu", "device"],
    )
    pf2.add_argument("--seconds", type=float, default=2.0)
    pf2.add_argument(
        "--format", dest="fmt", default="top",
        choices=["top", "collapsed"],
        help="cpu profile rendering: human-readable or folded stacks",
    )
    pf2.set_defaults(fn=cmd_profile)

    tc = sub.add_parser("trace", parents=[common])
    tc.add_argument("name", nargs="?", help="pod name (omit for all)")
    tc.add_argument("--limit", type=int, default=16)
    tc.set_defaults(fn=cmd_trace)

    xp = sub.add_parser("explain", parents=[common])
    xp.add_argument("resource", help="pods (or an alias)")
    xp.add_argument("name")
    xp.add_argument("--limit", type=int, default=1,
                    help="decisions to show, newest first")
    xp.set_defaults(fn=cmd_explain)

    pf = sub.add_parser("port-forward", parents=[common])
    pf.add_argument("name")
    pf.add_argument("ports", help="LOCAL:REMOTE (or one port for both)")
    pf.set_defaults(fn=cmd_port_forward)

    ar = sub.add_parser("api-resources", parents=[common])
    ar.set_defaults(fn=cmd_api_resources)

    vs = sub.add_parser("version", parents=[common])
    vs.set_defaults(fn=cmd_version)

    av = sub.add_parser("api-versions", parents=[common])
    av.set_defaults(fn=cmd_api_versions)

    ci = sub.add_parser("cluster-info", parents=[common])
    ci.set_defaults(fn=cmd_cluster_info)

    nsp = sub.add_parser("namespace", parents=[common])
    nsp.add_argument("ns", nargs="?")
    nsp.set_defaults(fn=cmd_namespace)

    up = sub.add_parser("update", parents=[common])
    up.add_argument("resource", nargs="?")
    up.add_argument("name", nargs="?")
    up.add_argument("--filename", "-f", default=None)
    up.add_argument("--patch", default=None, help="JSON merge patch")
    up.set_defaults(fn=cmd_update)

    px = sub.add_parser("proxy", parents=[common])
    px.add_argument("--port", "-p", type=int, default=8001)
    px.add_argument("--api-prefix", default="/api")
    px.set_defaults(fn=cmd_proxy)

    cf = sub.add_parser("config", parents=[common])
    cfs = cf.add_subparsers(dest="config_cmd", required=True)
    cfs.add_parser("view")
    for name in ("set-cluster", "set-credentials", "set-context", "use-context"):
        cp = cfs.add_parser(name)
        cp.add_argument("cname")
        if name == "set-cluster":
            cp.add_argument("--server-url", "--cluster-server", dest="server_url")
        elif name == "set-credentials":
            cp.add_argument("--username")
            cp.add_argument("--password")
            cp.add_argument("--token")
        elif name == "set-context":
            cp.add_argument("--cluster")
            cp.add_argument("--user")
            cp.add_argument("--ctx-namespace", "--set-namespace",
                            dest="ctx_namespace")
    for name in ("set", "unset"):
        cp = cfs.add_parser(name)
        cp.add_argument("prop")
        if name == "set":
            cp.add_argument("value")
    cf.set_defaults(fn=cmd_config, local_only=True)
    nsp.set_defaults(local_only=True)
    return p


def main(argv: Optional[List[str]] = None, client: Optional[Client] = None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "local_only", False):
        # config/namespace operate on the kubeconfig file only — no
        # server connection (and no requirement that one exists).
        from kubernetes_tpu.client.kubeconfig import KubeconfigError

        try:
            return args.fn(client, args)
        except (OSError, KubeconfigError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    if client is None:
        # kubeconfig resolution (pkg/client/clientcmd): explicit flags
        # win, then the file's current-context, then local defaults.
        # Skipped entirely for injected clients (tests/embedding must
        # not pick up the operator's personal config).
        from kubernetes_tpu.client.kubeconfig import (
            KubeconfigError,
            load_kubeconfig,
        )

        try:
            cfg = load_kubeconfig(args.kubeconfig, context=args.context)
        except KubeconfigError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        if args.server is None:
            args.server = cfg.server
        if args.namespace is None:
            args.namespace = cfg.namespace or "default"
        args._auth_headers = cfg.auth_headers()
        client = Client(HTTPTransport(args.server, headers=args._auth_headers))
    if args.namespace is None:
        args.namespace = "default"
    try:
        return args.fn(client, args)
    except APIError as e:
        print(f"Error from server ({e.reason}): {e.message}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # stdout consumer went away (logs -f | head): end quietly.
        try:
            sys.stdout.close()
        except OSError:
            pass
        return 0
    except (OSError, ConnectionError) as e:
        print(f"Unable to connect to server {args.server}: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
