"""python -m kubernetes_tpu.cli — ktctl entry point."""

import sys

from kubernetes_tpu.cli.ktctl import main

sys.exit(main())
