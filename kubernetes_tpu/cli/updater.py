"""Rolling updater, scaler, and reapers — the kubectl operational tier.

Reference:
- pkg/kubectl/rolling_updater.go (RollingUpdater.Update): scale the new
  RC up and the old RC down one replica at a time, waiting for ready
  pods between steps, then delete the old RC and (when the caller asks)
  rename the new one to the old name.
- pkg/kubectl/scale.go (Scaler with retry): conflict-retrying scale
  with a wait-for-replicas option.
- pkg/kubectl/stop.go (reapers): deleting an RC first scales it to 0
  and waits for its pods to drain, so nothing re-creates them.
"""

from __future__ import annotations

import copy
import time
from typing import Callable, Optional

from kubernetes_tpu.server.api import APIError


class UpdateTimeout(Exception):
    pass


def selector_string(selector) -> str:
    """Canonical label-selector string for a selector dict."""
    return ",".join(f"{k}={v}" for k, v in sorted((selector or {}).items()))


def _wait(cond: Callable[[], bool], timeout: float, interval: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    if cond():
        return
    raise UpdateTimeout(f"timed out waiting for {what}")


class Scaler:
    """Conflict-retrying scaler (pkg/kubectl/scale.go ScaleWithRetries)."""

    def __init__(self, client, retries: int = 10, interval: float = 0.1):
        self.client = client
        self.retries = retries
        self.interval = interval

    def scale(
        self,
        name: str,
        replicas: int,
        namespace: str = "default",
        wait: bool = False,
        timeout: float = 30.0,
    ) -> None:
        for attempt in range(self.retries):
            rc = self.client.get(
                "replicationcontrollers", name, namespace=namespace
            )
            rc.spec.replicas = replicas
            try:
                self.client.update(
                    "replicationcontrollers", rc, namespace=namespace
                )
                break
            except APIError as e:
                if e.code != 409 or attempt == self.retries - 1:
                    raise
                time.sleep(self.interval)
        if wait:
            # Selector is immutable for the duration of the wait: fetch
            # once, poll only the pod list.
            selector = self._selector(name, namespace)
            _wait(
                lambda: self._observed(selector, namespace) == replicas,
                timeout,
                0.1,
                f"rc {name} to reach {replicas} replicas",
            )

    def _observed(self, selector: str, namespace: str) -> int:
        pods, _ = self.client.list(
            "pods", namespace=namespace, label_selector=selector
        )
        return len([p for p in pods if p.status.phase not in ("Succeeded", "Failed")])

    def _selector(self, name: str, namespace: str) -> str:
        rc = self.client.get("replicationcontrollers", name, namespace=namespace)
        return selector_string(rc.spec.selector)


class RollingUpdater:
    """One-replica-at-a-time RC replacement (rolling_updater.go)."""

    def __init__(
        self,
        client,
        poll_interval: float = 0.2,
        update_period: float = 0.0,
        timeout: float = 60.0,
        progress: Optional[Callable[[str], None]] = None,
    ):
        self.client = client
        self.poll = poll_interval
        self.period = update_period
        self.timeout = timeout
        self._say = progress or (lambda msg: None)

    # -- helpers ------------------------------------------------------

    def _ready_count(self, rc, namespace: str) -> int:
        selector = selector_string(rc.spec.selector)
        pods, _ = self.client.list(
            "pods", namespace=namespace, label_selector=selector
        )
        ready = 0
        for p in pods:
            if p.status.phase != "Running":
                continue
            if any(
                c.type == "Ready" and c.status == "True"
                for c in p.status.conditions
            ):
                ready += 1
        return ready

    def _scale(self, name: str, replicas: int, namespace: str) -> None:
        Scaler(self.client).scale(name, replicas, namespace=namespace)

    def _ensure_disjoint(self, old, new_rc, namespace: str):
        """If the old RC's selector would adopt the NEW pods, retrofit a
        deployment-key label onto the old RC and its existing pods so
        the two controllers can't fight over replicas during the update
        (rolling_updater.go AddDeploymentKeyToReplicationController:
        label the live pods FIRST, then narrow the selector)."""
        import hashlib
        import json as _json

        from kubernetes_tpu.models import serde

        old_sel = dict(old.spec.selector or {})
        new_labels = dict(
            (new_rc.spec.template.metadata.labels or {})
            if new_rc.spec.template is not None
            else {}
        )
        if not all(new_labels.get(k) == v for k, v in old_sel.items()):
            return old  # already disjoint
        key = hashlib.sha1(
            _json.dumps(serde.to_wire(old.spec.template), sort_keys=True).encode()
        ).hexdigest()[:8]
        pods, _ = self.client.list(
            "pods", namespace=namespace, label_selector=selector_string(old_sel)
        )
        for pod in pods:
            if pod.metadata.labels.get("deployment") == key:
                continue
            pod.metadata.labels["deployment"] = key
            try:
                self.client.update("pods", pod, namespace=namespace)
            except APIError:
                pass  # pod vanished mid-retrofit; the RC will replace it
        old.spec.selector["deployment"] = key
        if old.spec.template is not None:
            old.spec.template.metadata.labels = dict(
                old.spec.template.metadata.labels or {}
            )
            old.spec.template.metadata.labels["deployment"] = key
        return self.client.update(
            "replicationcontrollers", old, namespace=namespace
        )

    # -- the update loop ----------------------------------------------

    def update(
        self,
        old_name: str,
        new_rc,
        namespace: str = "default",
        rename: bool = True,
    ) -> str:
        """Replace old_name's pods with new_rc's, one replica at a time.
        new_rc must carry a DIFFERENT selector than the old RC (the
        reference enforces a deployment-key label for the same reason:
        both RCs run concurrently and must not adopt each other's
        pods). Returns the surviving RC's name."""
        old = self.client.get(
            "replicationcontrollers", old_name, namespace=namespace
        )
        desired = new_rc.spec.replicas or old.spec.replicas
        if new_rc.metadata.name == old_name:
            raise ValueError(
                "new RC must have a different name than the old RC"
            )
        if dict(new_rc.spec.selector) == dict(old.spec.selector):
            raise ValueError(
                "new RC must use a different selector than the old RC"
            )
        # Reverse-adoption guard: if the NEW selector matches the OLD
        # template's labels, the new RC would instantly adopt (and its
        # waits would count) the old pods — and no retrofit can fix the
        # new RC's identity for the user. Refuse up front.
        old_labels = dict(
            (old.spec.template.metadata.labels or {})
            if old.spec.template is not None
            else {}
        )
        new_sel = dict(new_rc.spec.selector or {})
        if new_sel and all(old_labels.get(k) == v for k, v in new_sel.items()):
            raise ValueError(
                "new RC's selector matches the old RC's pods; add a "
                "distinguishing label (e.g. a deployment key) to the new "
                "selector and template"
            )
        old = self._ensure_disjoint(old, new_rc, namespace)

        # Ensure the new RC exists, starting from 0 replicas.
        new_name = new_rc.metadata.name
        try:
            self.client.get(
                "replicationcontrollers", new_name, namespace=namespace
            )
        except APIError as e:
            if e.code != 404:
                raise
            created = copy.deepcopy(new_rc)
            created.spec.replicas = 0
            self.client.create(
                "replicationcontrollers", created, namespace=namespace
            )

        new_count = self.client.get(
            "replicationcontrollers", new_name, namespace=namespace
        ).spec.replicas
        old_count = old.spec.replicas
        while new_count < desired or old_count > 0:
            if new_count < desired:
                new_count += 1
                self._say(f"Scaling {new_name} up to {new_count}")
                self._scale(new_name, new_count, namespace)
                new_obj = self.client.get(
                    "replicationcontrollers", new_name, namespace=namespace
                )
                _wait(
                    lambda: self._ready_count(new_obj, namespace) >= new_count,
                    self.timeout,
                    self.poll,
                    f"{new_name} to have {new_count} ready pods",
                )
            if old_count > 0:
                old_count -= 1
                self._say(f"Scaling {old_name} down to {old_count}")
                self._scale(old_name, old_count, namespace)
            if self.period:
                time.sleep(self.period)

        # Old RC drained: delete it (rolling_updater.go cleanup).
        self.client.delete(
            "replicationcontrollers", old_name, namespace=namespace
        )
        if rename and new_name != old_name:
            # Reference renames the new RC back to the old name so the
            # deployment keeps its identity (rolling_updater.go Rename:
            # delete + recreate under the old name; pods are adopted by
            # selector, not by RC name, so they are untouched).
            final = self.client.get(
                "replicationcontrollers", new_name, namespace=namespace
            )
            self.client.delete(
                "replicationcontrollers", new_name, namespace=namespace
            )
            final.metadata.name = old_name
            final.metadata.resource_version = ""
            final.metadata.uid = ""
            self.client.create(
                "replicationcontrollers", final, namespace=namespace
            )
            return old_name
        return new_name


class Reaper:
    """Graceful deletion (stop.go): RCs drain before deletion so the
    controller can't re-create their pods."""

    def __init__(self, client, timeout: float = 30.0):
        self.client = client
        self.timeout = timeout

    def stop(self, resource: str, name: str, namespace: str = "default") -> None:
        if resource == "replicationcontrollers":
            scaler = Scaler(self.client)
            scaler.scale(name, 0, namespace=namespace, wait=True, timeout=self.timeout)
            self.client.delete(
                "replicationcontrollers", name, namespace=namespace
            )
            return
        self.client.delete(resource, name, namespace=namespace)
