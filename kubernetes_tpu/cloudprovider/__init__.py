"""Cloud provider layer.

Reference: pkg/cloudprovider/cloud.go — Interface{Instances,
TCPLoadBalancer, Zones, Routes, Clusters} with per-cloud
implementations and a plugin registry (pkg/cloudprovider/plugins.go).

TPU-native framing: in this framework the "cloud" is the accelerator
fabric itself. The TPU provider (tpu.py) discovers the pod slice's
hosts/chips/ICI topology through JAX instead of querying a VM API:
instances are TPU hosts, zones are slice coordinates, routes are ICI
links. The fake provider mirrors pkg/cloudprovider/fake/fake.go.
"""

from kubernetes_tpu.cloudprovider.interface import (
    CloudProvider,
    Instance,
    LoadBalancerStub,
    Route,
    Zone,
    get_provider,
    register_provider,
)
from kubernetes_tpu.cloudprovider.fake import FakeCloudProvider
from kubernetes_tpu.cloudprovider.tpu import TPUCloudProvider

__all__ = [
    "CloudProvider",
    "FakeCloudProvider",
    "Instance",
    "LoadBalancerStub",
    "Route",
    "TPUCloudProvider",
    "Zone",
    "get_provider",
    "register_provider",
]
