"""TPU fabric provider: the accelerator pod IS the cloud.

Reference seam: pkg/cloudprovider/gce/gce.go et al. discover VM
instances from a cloud API; here the equivalent inventory — hosts,
chips, ICI links — comes from JAX's view of the TPU slice
(jax.devices(): process_index = host, coords = position in the
physical torus, device_kind = chip generation).

One INSTANCE per host (a host runs one kubelet/node agent and owns its
local chips); chip inventory and torus coordinates surface as instance
labels so the scheduler can use them as nodeSelector targets, exactly
how cloud zone/instance-type labels are used in the reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetes_tpu.cloudprovider.interface import (
    CloudProvider,
    Instance,
    LoadBalancerStub,
    Route,
    Zone,
    register_provider,
)

# Node label keys (the reference-era equivalents were
# failure-domain.beta.kubernetes.io/zone etc.).
LABEL_PLATFORM = "tpu.kubernetes-tpu.io/platform"
LABEL_CHIP = "tpu.kubernetes-tpu.io/chip"
LABEL_CHIPS = "tpu.kubernetes-tpu.io/chips-per-host"
LABEL_HOST = "tpu.kubernetes-tpu.io/host-index"
LABEL_COORDS = "tpu.kubernetes-tpu.io/coords"


class TPUCloudProvider(CloudProvider):
    name = "tpu"

    def __init__(self, devices=None, slice_name: str = "slice-0"):
        if devices is None:
            import jax

            devices = jax.devices()
        self.devices = list(devices)
        self.slice_name = slice_name
        # Managed routes (RouteController's pod-CIDR routes) layered on
        # top of the discovered ICI base ring.
        self._managed_routes: Dict[str, Route] = {}
        # Fabric ingress surface: portal rules at the slice edge.
        self._lb = LoadBalancerStub()

    # -- host grouping ------------------------------------------------

    def _hosts(self) -> Dict[int, List]:
        hosts: Dict[int, List] = {}
        for d in self.devices:
            hosts.setdefault(int(getattr(d, "process_index", 0)), []).append(d)
        return hosts

    @staticmethod
    def _coords(device) -> Optional[tuple]:
        coords = getattr(device, "coords", None)
        return tuple(coords) if coords is not None else None

    def host_name(self, process_index: int) -> str:
        return f"tpu-host-{process_index}"

    # -- CloudProvider ------------------------------------------------

    def instances(self) -> List[Instance]:
        out = []
        hosts = self._hosts()
        for pid, devs in sorted(hosts.items()):
            kind = getattr(devs[0], "device_kind", "unknown")
            platform = getattr(devs[0], "platform", "tpu")
            coords = [c for c in (self._coords(d) for d in devs) if c]
            labels = {
                LABEL_PLATFORM: str(platform),
                LABEL_CHIP: str(kind).replace(" ", "-"),
                LABEL_CHIPS: str(len(devs)),
                LABEL_HOST: str(pid),
            }
            if coords:
                # Label-value safe encoding (no commas/semicolons pass
                # validation): chip coords dash-joined, chips dot-joined
                # -> "0-0-0.1-0-0".
                labels[LABEL_COORDS] = ".".join(
                    "-".join(str(x) for x in c) for c in sorted(coords)
                )
            out.append(
                Instance(
                    name=self.host_name(pid),
                    addresses=("127.0.0.1",) if len(hosts) == 1 else (),
                    instance_type=f"{platform}-{len(devs)}x-{str(kind).replace(' ', '-')}",
                    instance_id=f"{self.slice_name}/host-{pid}",
                    labels=tuple(sorted(labels.items())),
                )
            )
        return out

    def zone_of(self, instance_name: str) -> Optional[Zone]:
        for pid in self._hosts():
            if self.host_name(pid) == instance_name:
                return Zone(
                    failure_domain=f"{self.slice_name}/host-{pid}",
                    region=self.slice_name,
                )
        return None

    def _base_routes(self) -> List[Route]:
        """ICI connectivity between hosts, modeled as a ring over host
        indices — the wraparound links every host has on real torus
        slices. (Finer-grained coords-based adjacency would refine
        this; the ring is what consumers can rely on today.)"""
        hosts = sorted(self._hosts())
        if len(hosts) <= 1:
            return []
        out = []
        for i, pid in enumerate(hosts):
            nxt = hosts[(i + 1) % len(hosts)]
            out.append(
                Route(
                    name=f"ici-{pid}-{nxt}",
                    target_instance=self.host_name(nxt),
                    destination_cidr=f"host://{nxt}",
                )
            )
        return out

    def routes(self) -> List[Route]:
        return self._base_routes() + list(self._managed_routes.values())

    def create_route(
        self, name: str, target_instance: str, destination_cidr: str
    ) -> None:
        self._managed_routes[name] = Route(
            name=name,
            target_instance=target_instance,
            destination_cidr=destination_cidr,
        )

    def delete_route(self, name: str) -> None:
        self._managed_routes.pop(name, None)

    def load_balancer(self) -> LoadBalancerStub:
        return self._lb

    def cluster_names(self) -> List[str]:
        return [self.slice_name]


register_provider("tpu", TPUCloudProvider)
