"""Fake cloud provider for tests.

Reference: pkg/cloudprovider/fake/fake.go — fully configurable
instances/zones/routes plus a call log so controllers can be tested
against deterministic cloud state.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetes_tpu.cloudprovider.interface import (
    CloudProvider,
    Instance,
    LoadBalancerStub,
    Route,
    Zone,
    register_provider,
)


class FakeCloudProvider(CloudProvider):
    name = "fake"

    def __init__(
        self,
        instances: Optional[List[Instance]] = None,
        zones: Optional[Dict[str, Zone]] = None,
        routes: Optional[List[Route]] = None,
    ):
        self._instances = instances if instances is not None else []
        self._zones = zones or {}
        self._routes = routes if routes is not None else []
        self._lb = LoadBalancerStub()
        self.calls: List[str] = []

    def instances(self) -> Optional[List[Instance]]:
        self.calls.append("instances")
        return list(self._instances)

    def zone_of(self, instance_name: str) -> Optional[Zone]:
        self.calls.append(f"zone_of:{instance_name}")
        return self._zones.get(instance_name)

    def routes(self) -> Optional[List[Route]]:
        self.calls.append("routes")
        return list(self._routes)

    def create_route(
        self, name: str, target_instance: str, destination_cidr: str
    ) -> None:
        self.calls.append(f"create_route:{name}")
        self._routes = [r for r in self._routes if r.name != name]
        self._routes.append(
            Route(
                name=name,
                target_instance=target_instance,
                destination_cidr=destination_cidr,
            )
        )

    def delete_route(self, name: str) -> None:
        self.calls.append(f"delete_route:{name}")
        self._routes = [r for r in self._routes if r.name != name]

    def load_balancer(self) -> Optional[LoadBalancerStub]:
        return self._lb

    def cluster_names(self) -> List[str]:
        return ["fake-cluster"]

    # test helpers
    def set_instances(self, instances: List[Instance]) -> None:
        self._instances = list(instances)


register_provider("fake", FakeCloudProvider)
