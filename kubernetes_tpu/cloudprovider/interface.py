"""Cloud provider interface + registry.

Reference: pkg/cloudprovider/cloud.go (Interface, Instances, Zones,
Routes, TCPLoadBalancer, Clusters) and plugins.go (RegisterCloudProvider
/ GetCloudProvider).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass(frozen=True)
class Instance:
    """One schedulable machine (reference: Instances.List/NodeAddresses).
    For the TPU provider an instance is a TPU HOST (the unit that runs
    a kubelet), not a chip."""

    name: str
    addresses: tuple = ()  # (ip, ...)
    instance_type: str = ""
    instance_id: str = ""
    labels: tuple = ()  # ((k, v), ...) — hashable

    def labels_dict(self) -> Dict[str, str]:
        return dict(self.labels)


@dataclass(frozen=True)
class Zone:
    """Failure/locality domain (reference: Zones.GetZone). TPU analog:
    one slice (or one host's coordinates within it)."""

    failure_domain: str
    region: str


@dataclass(frozen=True)
class Route:
    """Inter-instance connectivity (reference: Routes). TPU analog: an
    ICI link between neighboring hosts."""

    name: str
    target_instance: str
    destination_cidr: str = ""


class LoadBalancerStub:
    """TCP load balancer surface (reference: TCPLoadBalancer). Cloud
    LBs don't exist on the fabric; providers may override with
    something real (the fake records calls for tests)."""

    def __init__(self):
        self.balancers: Dict[str, List[str]] = {}

    def ensure(self, name: str, hosts: List[str]) -> str:
        self.balancers[name] = list(hosts)
        return self.address(name)

    def address(self, name: str) -> str:
        """Ingress address of an already-provisioned balancer."""
        return f"lb-{name}"

    def update_hosts(self, name: str, hosts: List[str]) -> None:
        if name in self.balancers:
            self.balancers[name] = list(hosts)

    def delete(self, name: str) -> None:
        self.balancers.pop(name, None)


class CloudProvider:
    """The provider interface. Capability getters return None when
    unsupported, mirroring the reference's (iface, bool) returns."""

    name: str = ""

    def instances(self) -> Optional[List[Instance]]:
        return None

    def zone_of(self, instance_name: str) -> Optional[Zone]:
        return None

    def routes(self) -> Optional[List[Route]]:
        return None

    def create_route(
        self, name: str, target_instance: str, destination_cidr: str
    ) -> None:
        """Program one route (reference: Routes.CreateRoute). Providers
        without a mutable route table raise."""
        raise NotImplementedError(f"{self.name}: routes are read-only")

    def delete_route(self, name: str) -> None:
        raise NotImplementedError(f"{self.name}: routes are read-only")

    def load_balancer(self) -> Optional[LoadBalancerStub]:
        return None

    def cluster_names(self) -> List[str]:
        return []


_lock = threading.Lock()
_providers: Dict[str, Callable[[], CloudProvider]] = {}


def register_provider(name: str, factory: Callable[[], CloudProvider]) -> None:
    with _lock:
        _providers[name] = factory


def get_provider(name: str) -> CloudProvider:
    with _lock:
        if name not in _providers:
            raise KeyError(
                f"cloud provider {name!r} not registered "
                f"(have: {sorted(_providers)})"
            )
        return _providers[name]()
