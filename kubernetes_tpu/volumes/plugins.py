"""Volume plugin framework.

Reference: pkg/volume/ (volume.go Builder/Cleaner interfaces,
plugins.go VolumePluginMgr.FindPluginBySpec) and the per-plugin
packages: empty_dir, host_path, secret, git_repo, nfs, gce_pd,
aws_ebs, iscsi, glusterfs, rbd, persistent_claim.

Layout mirrors the reference kubelet's disk format:
  <root>/pods/<pod-uid>/volumes/<escaped-plugin-name>/<volume-name>

Local plugins (empty_dir, host_path, secret, git_repo) do real
filesystem work; network/block plugins (nfs, gce_pd, aws_ebs, iscsi,
glusterfs, rbd) drive the Mounter seam (mount.py) so they run
unprivileged under FakeMounter and for real under ExecMounter.
"""

from __future__ import annotations

import base64
import os
import shutil
import subprocess
from dataclasses import dataclass
from typing import List, Optional

from kubernetes_tpu.models.objects import Volume
from kubernetes_tpu.volumes.mount import FakeMounter, Mounter


@dataclass
class VolumeHost:
    """What plugins may use from their host kubelet (reference:
    volume.VolumeHost)."""

    root_dir: str
    client: object = None  # apiserver client (secret/claim plugins)
    mounter: Mounter = None
    node_name: str = ""

    def __post_init__(self):
        if self.mounter is None:
            self.mounter = FakeMounter()

    def pod_volume_dir(self, pod_uid: str, plugin_name: str, volume_name: str) -> str:
        escaped = plugin_name.replace("/", "~")
        return os.path.join(
            self.root_dir, "pods", pod_uid, "volumes", escaped, volume_name
        )

    def pod_volumes_root(self, pod_uid: str) -> str:
        return os.path.join(self.root_dir, "pods", pod_uid, "volumes")


class Builder:
    """Sets up a volume for a pod (reference: volume.Builder)."""

    def setup(self) -> str:
        """Materialize the volume; returns the host path to mount into
        containers."""
        raise NotImplementedError

    def get_path(self) -> str:
        raise NotImplementedError


class Cleaner:
    """Tears a volume down (reference: volume.Cleaner)."""

    def teardown(self) -> None:
        raise NotImplementedError


class VolumePlugin:
    name: str = ""

    def init(self, host: VolumeHost) -> None:
        self.host = host

    def can_support(self, volume: Volume) -> bool:
        raise NotImplementedError

    def new_builder(self, volume: Volume, pod) -> Builder:
        raise NotImplementedError

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirCleaner(
            self.host.pod_volume_dir(pod_uid, self.name, volume_name)
        )


class _DirCleaner(Cleaner):
    def __init__(self, path: str, mounter: Optional[Mounter] = None):
        self.path = path
        self.mounter = mounter

    def teardown(self) -> None:
        if self.mounter is not None and self.mounter.is_mount_point(self.path):
            self.mounter.unmount(self.path)
        if os.path.islink(self.path):
            os.unlink(self.path)
        elif os.path.isdir(self.path):
            shutil.rmtree(self.path, ignore_errors=True)


class _DirBuilder(Builder):
    def __init__(self, path: str):
        self.path = path

    def get_path(self) -> str:
        return self.path


# ---------------------------------------------------------------------------
# Local plugins
# ---------------------------------------------------------------------------


class EmptyDirPlugin(VolumePlugin):
    """pkg/volume/empty_dir/ — a fresh directory per (pod, volume)."""

    name = "kubernetes.io/empty-dir"

    def can_support(self, volume: Volume) -> bool:
        return volume.empty_dir is not None

    def new_builder(self, volume: Volume, pod) -> Builder:
        path = self.host.pod_volume_dir(
            pod.metadata.uid or pod.metadata.name, self.name, volume.name
        )

        class B(_DirBuilder):
            def setup(self) -> str:
                os.makedirs(self.path, exist_ok=True)
                return self.path

        return B(path)


class HostPathPlugin(VolumePlugin):
    """pkg/volume/host_path/ — expose an existing host path; nothing
    is created or destroyed."""

    name = "kubernetes.io/host-path"

    def can_support(self, volume: Volume) -> bool:
        return volume.host_path is not None

    def new_builder(self, volume: Volume, pod) -> Builder:
        class B(_DirBuilder):
            def setup(self) -> str:
                return self.path

        return B(volume.host_path.path)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        class NoopCleaner(Cleaner):
            def teardown(self) -> None:
                pass

        return NoopCleaner()


class SecretPlugin(VolumePlugin):
    """pkg/volume/secret/ — fetch the Secret and write each key as a
    file (values are base64 in the wire format)."""

    name = "kubernetes.io/secret"

    def can_support(self, volume: Volume) -> bool:
        return volume.secret is not None

    def new_builder(self, volume: Volume, pod) -> Builder:
        host = self.host
        path = host.pod_volume_dir(
            pod.metadata.uid or pod.metadata.name, self.name, volume.name
        )
        secret_name = volume.secret.secret_name
        namespace = pod.metadata.namespace or "default"

        class B(_DirBuilder):
            def setup(self) -> str:
                secret = host.client.get(
                    "secrets", secret_name, namespace=namespace
                )
                os.makedirs(self.path, exist_ok=True)
                data = secret.data if not isinstance(secret, dict) else secret.get("data", {})
                for key, b64 in (data or {}).items():
                    with open(os.path.join(self.path, key), "wb") as f:
                        f.write(base64.b64decode(b64))
                return self.path

        return B(path)


class GitRepoPlugin(VolumePlugin):
    """pkg/volume/git_repo/ — clone a repository into the volume dir."""

    name = "kubernetes.io/git-repo"

    def can_support(self, volume: Volume) -> bool:
        return volume.git_repo is not None

    def new_builder(self, volume: Volume, pod) -> Builder:
        path = self.host.pod_volume_dir(
            pod.metadata.uid or pod.metadata.name, self.name, volume.name
        )
        repo = volume.git_repo.repository
        revision = volume.git_repo.revision
        # A pod spec is untrusted input: a repository/revision starting
        # with "-" would be parsed as a git OPTION (e.g.
        # --upload-pack=<cmd> executes arbitrary commands as the
        # kubelet user).
        if repo.startswith("-") or revision.startswith("-"):
            raise ValueError("gitRepo repository/revision may not start with '-'")

        class B(_DirBuilder):
            def setup(self) -> str:
                os.makedirs(self.path, exist_ok=True)
                if not os.listdir(self.path):
                    subprocess.run(
                        ["git", "clone", "--", repo, self.path],
                        check=True, capture_output=True,
                    )
                    if revision:
                        subprocess.run(
                            ["git", "checkout", revision, "--"],
                            cwd=self.path, check=True, capture_output=True,
                        )
                return self.path

        return B(path)


# ---------------------------------------------------------------------------
# Network / block plugins — all reduce to "mount a remote source at the
# per-pod dir" through the Mounter seam.
# ---------------------------------------------------------------------------


class _MountedPlugin(VolumePlugin):
    def _source(self, volume: Volume) -> tuple:
        """(device/source, fstype, options) for this volume."""
        raise NotImplementedError

    def new_builder(self, volume: Volume, pod) -> Builder:
        host = self.host
        path = host.pod_volume_dir(
            pod.metadata.uid or pod.metadata.name, self.name, volume.name
        )
        source, fstype, options = self._source(volume)

        class B(_DirBuilder):
            def setup(self) -> str:
                os.makedirs(self.path, exist_ok=True)
                if not host.mounter.is_mount_point(self.path):
                    host.mounter.mount(source, self.path, fstype, options)
                return self.path

        return B(path)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        return _DirCleaner(
            self.host.pod_volume_dir(pod_uid, self.name, volume_name),
            mounter=self.host.mounter,
        )


class NFSPlugin(_MountedPlugin):
    name = "kubernetes.io/nfs"

    def can_support(self, volume: Volume) -> bool:
        return volume.nfs is not None

    def _source(self, volume: Volume):
        nfs = volume.nfs
        opts = ["ro"] if nfs.read_only else []
        return f"{nfs.server}:{nfs.path}", "nfs", opts


class GCEPersistentDiskPlugin(_MountedPlugin):
    name = "kubernetes.io/gce-pd"

    def can_support(self, volume: Volume) -> bool:
        return volume.gce_persistent_disk is not None

    def _source(self, volume: Volume):
        pd = volume.gce_persistent_disk
        opts = ["ro"] if pd.read_only else []
        return f"/dev/disk/by-id/google-{pd.pd_name}", pd.fs_type or "ext4", opts


class AWSElasticBlockStorePlugin(_MountedPlugin):
    name = "kubernetes.io/aws-ebs"

    def can_support(self, volume: Volume) -> bool:
        return volume.aws_elastic_block_store is not None

    def _source(self, volume: Volume):
        ebs = volume.aws_elastic_block_store
        opts = ["ro"] if ebs.read_only else []
        return f"aws://{ebs.volume_id}", ebs.fs_type or "ext4", opts


class GlusterfsPlugin(_MountedPlugin):
    name = "kubernetes.io/glusterfs"

    def can_support(self, volume: Volume) -> bool:
        return volume.glusterfs is not None

    def _source(self, volume: Volume):
        g = volume.glusterfs
        opts = ["ro"] if g.read_only else []
        return f"{g.endpoints_name}:{g.path}", "glusterfs", opts


class RBDPlugin(_MountedPlugin):
    name = "kubernetes.io/rbd"

    def can_support(self, volume: Volume) -> bool:
        return volume.rbd is not None

    def _source(self, volume: Volume):
        r = volume.rbd
        opts = ["ro"] if r.read_only else []
        return f"rbd:{r.pool}/{r.image}", r.fs_type or "ext4", opts


class ISCSIPlugin(_MountedPlugin):
    name = "kubernetes.io/iscsi"

    def can_support(self, volume: Volume) -> bool:
        return volume.iscsi is not None

    def _source(self, volume: Volume):
        i = volume.iscsi
        opts = ["ro"] if i.read_only else []
        return f"{i.target_portal}:{i.iqn}:lun{i.lun}", i.fs_type or "ext4", opts


# ---------------------------------------------------------------------------
# persistent_claim — delegates to the plugin matching the bound PV
# ---------------------------------------------------------------------------


class PersistentClaimPlugin(VolumePlugin):
    """pkg/volume/persistent_claim/ — resolve PVC -> bound PV ->
    underlying plugin, and build THAT volume in this pod's dirs."""

    name = "kubernetes.io/persistent-claim"

    def __init__(self, manager: "VolumePluginManager"):
        self.manager = manager

    def can_support(self, volume: Volume) -> bool:
        return volume.persistent_volume_claim is not None

    def new_builder(self, volume: Volume, pod) -> Builder:
        claim_name = volume.persistent_volume_claim.claim_name
        namespace = pod.metadata.namespace or "default"
        claim = self.host.client.get(
            "persistentvolumeclaims", claim_name, namespace=namespace
        )
        volume_name = (
            claim.spec.volume_name
            if not isinstance(claim, dict)
            else claim.get("spec", {}).get("volumeName", "")
        )
        if not volume_name:
            raise ValueError(f"claim {namespace}/{claim_name} is not bound yet")
        pv = self.host.client.get("persistentvolumes", volume_name)
        src = pv.spec.persistent_volume_source
        # Re-wrap the PV's source as a pod Volume carrying the claim
        # volume's name, so paths land under this pod. A read-only
        # claim must stay read-only regardless of what the PV says —
        # copy each source (never mutate the cached PV) and force the
        # flag through.
        import dataclasses as _dc

        def _ro(source):
            if source is None:
                return None
            if volume.persistent_volume_claim.read_only and hasattr(
                source, "read_only"
            ):
                return _dc.replace(source, read_only=True)
            return source

        inner = Volume(
            name=volume.name,
            host_path=_ro(src.host_path),
            gce_persistent_disk=_ro(src.gce_persistent_disk),
            aws_elastic_block_store=_ro(src.aws_elastic_block_store),
            nfs=_ro(src.nfs),
            glusterfs=_ro(src.glusterfs),
            rbd=_ro(src.rbd),
            iscsi=_ro(src.iscsi),
        )
        plugin = self.manager.find_plugin(inner, exclude=self.name)
        if plugin is None:
            raise ValueError(f"no plugin supports PV {volume_name}")
        return plugin.new_builder(inner, pod)

    def new_cleaner(self, volume_name: str, pod_uid: str) -> Cleaner:
        # The delegate built under its own plugin dir; pod-level GC
        # (teardown_orphans) sweeps every plugin dir, so nothing to do.
        class NoopCleaner(Cleaner):
            def teardown(self) -> None:
                pass

        return NoopCleaner()


# ---------------------------------------------------------------------------
# Manager
# ---------------------------------------------------------------------------


class VolumePluginManager:
    """Registry + dispatch (reference: volume.VolumePluginMgr)."""

    def __init__(self, host: VolumeHost, plugins: Optional[List[VolumePlugin]] = None):
        self.host = host
        if plugins is None:
            plugins = [
                EmptyDirPlugin(),
                HostPathPlugin(),
                SecretPlugin(),
                GitRepoPlugin(),
                NFSPlugin(),
                GCEPersistentDiskPlugin(),
                AWSElasticBlockStorePlugin(),
                GlusterfsPlugin(),
                RBDPlugin(),
                ISCSIPlugin(),
                PersistentClaimPlugin(self),
            ]
        self.plugins = plugins
        for p in self.plugins:
            p.init(host)

    def find_plugin(self, volume: Volume, exclude: str = "") -> Optional[VolumePlugin]:
        for p in self.plugins:
            if p.name != exclude and p.can_support(volume):
                return p
        return None

    # -- kubelet entry points -----------------------------------------

    def mount_pod_volumes(self, pod) -> dict:
        """SetUp every volume in the pod spec; returns
        {volume_name: host_path} (reference: kubelet.go
        mountExternalVolumes :1135)."""
        paths = {}
        for volume in pod.spec.volumes:
            plugin = self.find_plugin(volume)
            if plugin is None:
                raise ValueError(f"no plugin for volume {volume.name!r}")
            paths[volume.name] = plugin.new_builder(volume, pod).setup()
        return paths

    def list_pod_uids(self) -> List[str]:
        """Pod uids that have on-disk volume state (reference: the
        kubelet's cleanupOrphanedVolumes scans the disk layout — the
        runtime's memory of pods is not the source of truth for GC)."""
        pods_dir = os.path.join(self.host.root_dir, "pods")
        if not os.path.isdir(pods_dir):
            return []
        return os.listdir(pods_dir)

    def teardown_pod_volumes(self, pod_uid: str) -> None:
        """Tear down everything under the pod's volumes dir (reference:
        kubelet cleanupOrphanedVolumes)."""
        root = self.host.pod_volumes_root(pod_uid)
        if not os.path.isdir(root):
            return
        for escaped in os.listdir(root):
            plugin_dir = os.path.join(root, escaped)
            plugin_name = escaped.replace("~", "/")
            plugin = next(
                (p for p in self.plugins if p.name == plugin_name), None
            )
            for volume_name in os.listdir(plugin_dir):
                if plugin is not None:
                    plugin.new_cleaner(volume_name, pod_uid).teardown()
                else:
                    _DirCleaner(
                        os.path.join(plugin_dir, volume_name),
                        mounter=self.host.mounter,
                    ).teardown()
        shutil.rmtree(os.path.join(self.host.root_dir, "pods", pod_uid),
                      ignore_errors=True)
