"""Mount utility abstraction.

Reference: pkg/util/mount/ — Interface{Mount, Unmount, List} with a
real exec'd implementation and a FakeMounter for tests. Network/block
volume plugins never touch mount(8) directly; they go through this
seam so the whole volume subsystem is testable without privileges.
"""

from __future__ import annotations

import subprocess
import threading
from dataclasses import dataclass, field
from typing import List, Optional


@dataclass(frozen=True)
class MountPoint:
    device: str
    path: str
    fstype: str
    opts: tuple = ()


class Mounter:
    """Interface (reference: mount.Interface)."""

    def mount(self, source: str, target: str, fstype: str, options: List[str]) -> None:
        raise NotImplementedError

    def unmount(self, target: str) -> None:
        raise NotImplementedError

    def list(self) -> List[MountPoint]:
        raise NotImplementedError

    def is_mount_point(self, path: str) -> bool:
        return any(m.path == path for m in self.list())


class FakeMounter(Mounter):
    """In-memory mount table + action log (reference: mount.FakeMounter)."""

    def __init__(self, fail_on: Optional[set] = None):
        self._lock = threading.Lock()
        self.mounts: List[MountPoint] = []
        self.log: List[tuple] = []
        self.fail_on = fail_on or set()

    def mount(self, source, target, fstype, options) -> None:
        with self._lock:
            self.log.append(("mount", source, target, fstype, tuple(options)))
            if target in self.fail_on:
                raise OSError(f"fake mount failure for {target}")
            self.mounts.append(MountPoint(source, target, fstype, tuple(options)))

    def unmount(self, target) -> None:
        with self._lock:
            self.log.append(("unmount", target))
            self.mounts = [m for m in self.mounts if m.path != target]

    def list(self) -> List[MountPoint]:
        with self._lock:
            return list(self.mounts)


class ExecMounter(Mounter):
    """Shells out to mount(8)/umount(8) (reference: mount.Mounter).
    Requires privileges; used only in real deployments."""

    def mount(self, source, target, fstype, options) -> None:
        cmd = ["mount"]
        if fstype:
            cmd += ["-t", fstype]
        if options:
            cmd += ["-o", ",".join(options)]
        cmd += [source, target]
        subprocess.run(cmd, check=True, capture_output=True)

    def unmount(self, target) -> None:
        subprocess.run(["umount", target], check=True, capture_output=True)

    def list(self) -> List[MountPoint]:
        out = []
        with open("/proc/mounts") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 4:
                    out.append(
                        MountPoint(
                            parts[0], parts[1], parts[2], tuple(parts[3].split(","))
                        )
                    )
        return out
