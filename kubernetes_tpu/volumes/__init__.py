"""Volume subsystem (reference: pkg/volume/ + pkg/util/mount/)."""

from kubernetes_tpu.volumes.mount import ExecMounter, FakeMounter, MountPoint, Mounter
from kubernetes_tpu.volumes.plugins import (
    Builder,
    Cleaner,
    VolumeHost,
    VolumePlugin,
    VolumePluginManager,
)

__all__ = [
    "Builder",
    "Cleaner",
    "ExecMounter",
    "FakeMounter",
    "MountPoint",
    "Mounter",
    "VolumeHost",
    "VolumePlugin",
    "VolumePluginManager",
]
