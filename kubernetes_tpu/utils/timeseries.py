"""In-memory time-series retention for the metrics registry.

The registry's series are cumulative-since-reset; every consumer so
far (SLO engine, bench gates, ktctl) read them point-in-time, so one
early latency burn pinned a histogram's p99 forever and nothing ever
*resolved*. This module is the retention half of the health plane
(Monarch's shape — Adams et al., VLDB 2020 — at cluster scale: keep
the recent raw points in memory, answer windowed queries from deltas):

- A background :class:`Sampler` snapshots every Counter/Gauge/Histogram
  on the registry into bounded per-series rings at a configurable
  cadence (``KT_TS_INTERVAL_S``; zero-cost when never started — the
  default state for unit tests and thin control-plane processes).
- Windowed queries are computed from **deltas** between ring samples,
  never from the cumulative values themselves: :func:`Retention.rate`
  / ``increase`` (counter-reset tolerant: negative steps are a restart,
  not negative traffic), ``delta``/``max_over_time``/``avg_over_time``
  (gauges), and ``quantile_over_time`` — histogram +le bucket deltas
  interpolated by the same :func:`metrics.bucket_quantile` the live
  histogram uses, so a windowed p99 and a lifetime p99 can never
  disagree about interpolation.

Consumers: utils/slo.py (windowed objective verdicts with lifetime
fallback), utils/alerts.py (multi-window burn rates), GET
/debug/timeseries, and the soak harness's alert oracle. The sampler
registers a fault site (``timeseries.sample.skip``, PR 15 convention)
so chaos runs can prove windowed queries degrade to surviving samples
instead of extrapolating through a gap.

Summaries are deliberately NOT retained: a sampled reservoir is not
delta-composable (two snapshots of the same reservoir share elements),
and every SLO-feeding latency series is a Histogram precisely so
windows CAN be taken (utils/metrics.py docstring).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.utils import faults, metrics, sanitizer

#: Sampler cadence / per-series ring bound (env-tunable; soak and the
#: check.sh smoke shrink the cadence to make minutes-long windows run
#: on CI clocks). 5s x 720 samples retains one hour per series.
DEFAULT_INTERVAL_S = float(os.environ.get("KT_TS_INTERVAL_S", "5.0"))
DEFAULT_RETAIN_SAMPLES = int(os.environ.get("KT_TS_RETAIN", "720"))

SAMPLES = metrics.DEFAULT.counter(
    "timeseries_samples_total",
    "Retention sampler sweeps taken (utils/timeseries.py)",
)
RETAINED = metrics.DEFAULT.gauge(
    "timeseries_retained_series",
    "Live series held in retention rings",
)
SAMPLE_SECONDS = metrics.DEFAULT.histogram(
    "timeseries_sample_seconds",
    "Wall time per retention sweep (the health plane's overhead "
    "figure; bench pins sampler+alerts under 5% of the churn drill)",
)


class Retention:
    """Bounded per-series rings of registry snapshots + the windowed
    query surface. Writes come from one sampler thread; reads from any
    (SLO engine, alert engine, debug handlers)."""

    def __init__(self, retain_samples: int = DEFAULT_RETAIN_SAMPLES):
        self.retain_samples = int(retain_samples)
        self._lock = sanitizer.lock("timeseries.retention")
        # metric name -> label tuple -> ring of (t_mono, payload).
        # Payload: float for counter/gauge; (count, sum, buckets) for
        # histograms.
        self._rings: Dict[str, Dict[Tuple[str, ...], deque]] = {}
        # metric name -> {"type", "label_names", "buckets"}.
        self._meta: Dict[str, dict] = {}
        self._samples = 0

    # -- ingest --------------------------------------------------------

    def sample_now(self, registry=None, now: Optional[float] = None) -> int:
        """One sweep: snapshot every retainable metric into its rings.
        Returns the number of series touched. The registry locks are
        held per-family during snapshot and never nested under the
        retention lock (snapshots are collected first, appended after)."""
        registry = metrics.DEFAULT if registry is None else registry
        now = time.monotonic() if now is None else now
        if faults.enabled() and faults.fire(faults.TIMESERIES_SAMPLE_SKIP):
            return 0
        collected = []
        for m in registry.all():
            snap = getattr(m, "snapshot", None)
            if snap is None:
                continue  # summaries: reservoirs are not delta-composable
            if isinstance(m, metrics.Histogram):
                mtype = "histogram"
            elif isinstance(m, metrics.Counter):
                mtype = "counter"
            elif isinstance(m, metrics.Gauge):
                mtype = "gauge"
            else:
                continue
            collected.append((m, mtype, snap()))
        touched = 0
        with self._lock:
            for m, mtype, series in collected:
                # meta is fixed at first sight; bucket ladders are set
                # at registration so no refresh is needed.
                self._meta.setdefault(
                    m.name,
                    {
                        "type": mtype,
                        "label_names": m.label_names,
                        "buckets": tuple(getattr(m, "buckets", ())),
                    },
                )
                rings = self._rings.setdefault(m.name, {})
                for key, payload in series.items():
                    ring = rings.get(key)
                    if ring is None:
                        ring = rings[key] = deque(maxlen=self.retain_samples)
                    ring.append((now, payload))
                    touched += 1
            self._samples += 1
            total = sum(
                1 for rs in self._rings.values() for r in rs.values() if r
            )
        SAMPLES.inc()
        RETAINED.set(float(total))
        return touched

    # -- introspection -------------------------------------------------

    @property
    def sampled(self) -> bool:
        with self._lock:
            return self._samples > 0

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def label_sets(self, series: str) -> List[Dict[str, str]]:
        """Label-value dicts of the retained series (the windowed SLO
        engine's analog of Metric.label_values())."""
        with self._lock:
            meta = self._meta.get(series)
            rings = self._rings.get(series)
            if meta is None or rings is None:
                return []
            names = meta["label_names"]
            return [dict(zip(names, key)) for key in rings]

    def reset(self) -> None:
        """Drop every ring (tests and bench open fresh windows)."""
        with self._lock:
            self._rings.clear()
            self._meta.clear()
            self._samples = 0

    # -- windowed queries ----------------------------------------------

    def _window(
        self, series: str, labels: Dict[str, str], window_s: float,
        now: Optional[float],
    ) -> List[Tuple[float, object]]:
        now = time.monotonic() if now is None else now
        with self._lock:
            meta = self._meta.get(series)
            rings = self._rings.get(series)
            if meta is None or rings is None:
                return []
            key = tuple(
                (labels or {}).get(k, "") for k in meta["label_names"]
            )
            ring = rings.get(key)
            if not ring:
                return []
            lo = now - window_s
            return [s for s in ring if s[0] >= lo]

    def increase(
        self, series: str, window_s: float,
        labels: Optional[Dict[str, str]] = None, now: Optional[float] = None,
    ) -> Optional[float]:
        """Windowed counter increase: sum of positive per-step deltas
        (a negative step is a process restart — the counter restarted
        from zero, it did not count backwards). None until the window
        holds two samples."""
        win = self._window(series, labels or {}, window_s, now)
        if len(win) < 2:
            return None
        # A query aimed at the wrong kind (increase of a histogram,
        # quantile of a counter) answers None, never raises: rings are
        # homogeneous, so the first sample's shape decides.
        if not isinstance(win[0][1], (int, float)):
            return None
        total = 0.0
        for (_, prev), (_, cur) in zip(win, win[1:]):
            step = float(cur) - float(prev)
            if step > 0:
                total += step
        return total

    def rate(
        self, series: str, window_s: float,
        labels: Optional[Dict[str, str]] = None, now: Optional[float] = None,
    ) -> Optional[float]:
        """Windowed per-second rate over the OBSERVED span (first to
        last sample), not the nominal window — a sparse ring must not
        dilute a burst into a lower rate."""
        win = self._window(series, labels or {}, window_s, now)
        if len(win) < 2:
            return None
        elapsed = win[-1][0] - win[0][0]
        if elapsed <= 0:
            return None
        inc = self.increase(series, window_s, labels, now)
        return None if inc is None else inc / elapsed

    def delta(
        self, series: str, window_s: float,
        labels: Optional[Dict[str, str]] = None, now: Optional[float] = None,
    ) -> Optional[float]:
        """Gauge delta across the window (last - first; signed)."""
        win = self._window(series, labels or {}, window_s, now)
        if len(win) < 2 or not isinstance(win[0][1], (int, float)):
            return None
        return float(win[-1][1]) - float(win[0][1])

    def max_over_time(
        self, series: str, window_s: float,
        labels: Optional[Dict[str, str]] = None, now: Optional[float] = None,
    ) -> Optional[float]:
        win = self._window(series, labels or {}, window_s, now)
        if not win or not isinstance(win[0][1], (int, float)):
            return None
        return max(float(v) for _, v in win)

    def avg_over_time(
        self, series: str, window_s: float,
        labels: Optional[Dict[str, str]] = None, now: Optional[float] = None,
    ) -> Optional[float]:
        win = self._window(series, labels or {}, window_s, now)
        if not win or not isinstance(win[0][1], (int, float)):
            return None
        return sum(float(v) for _, v in win) / len(win)

    def hist_window(
        self, series: str, window_s: float,
        labels: Optional[Dict[str, str]] = None, now: Optional[float] = None,
    ) -> Optional[Tuple[int, float, Tuple[int, ...]]]:
        """Histogram deltas across the window: (count, sum, per-bucket
        raw counts). Counter-reset tolerant: when the process restarted
        mid-window (count went backwards), the last snapshot alone IS
        the since-restart window. None until two samples exist."""
        win = self._window(series, labels or {}, window_s, now)
        if len(win) < 2 or not isinstance(win[0][1], tuple):
            return None
        (c0, s0, b0) = win[0][1]
        (c1, s1, b1) = win[-1][1]
        if c1 < c0 or len(b0) != len(b1):
            return (c1, s1, tuple(b1))
        return (
            c1 - c0,
            s1 - s0,
            tuple(max(0, b - a) for a, b in zip(b0, b1)),
        )

    def quantile_over_time(
        self, series: str, q: float, window_s: float,
        labels: Optional[Dict[str, str]] = None, now: Optional[float] = None,
    ) -> Optional[float]:
        """Interpolated quantile of the observations that landed INSIDE
        the window (bucket deltas -> metrics.bucket_quantile). None when
        the window lacks two samples or saw zero new observations —
        the caller decides whether that means no_data or lifetime
        fallback (utils/slo.py chooses fallback)."""
        hw = self.hist_window(series, window_s, labels, now)
        if hw is None:
            return None
        count, _total_sum, bucket_deltas = hw
        if count <= 0:
            return None
        with self._lock:
            meta = self._meta.get(series)
            bounds = meta["buckets"] if meta else ()
        if not bounds:
            return None
        q_v = metrics.bucket_quantile(bounds, bucket_deltas, count, q)
        return None if q_v != q_v else q_v  # NaN-safe

    # -- debug surface -------------------------------------------------

    def snapshot(
        self, series: str = "", window_s: float = 300.0,
    ) -> dict:
        """The /debug/timeseries payload: the series inventory, or —
        with ?series= — per-label-set windowed figures."""
        out = {
            "kind": "TimeseriesReport",
            "sampled": self.sampled,
            "samples": self.samples,
            "retainSamples": self.retain_samples,
            "series": self.series_names(),
        }
        if not series:
            return out
        with self._lock:
            meta = self._meta.get(series)
        if meta is None:
            out["query"] = {"series": series, "found": False}
            return out
        rows = []
        for labels in self.label_sets(series):
            row: dict = {"labels": labels}
            win = self._window(series, labels, window_s, None)
            row["samplesInWindow"] = len(win)
            if meta["type"] == "histogram":
                hw = self.hist_window(series, window_s, labels)
                if hw is not None:
                    row["increase"] = hw[0]
                for q in (0.5, 0.99):
                    v = self.quantile_over_time(series, q, window_s, labels)
                    if v is not None:
                        row[f"p{int(q * 100)}"] = round(v, 6)
            elif meta["type"] == "counter":
                inc = self.increase(series, window_s, labels)
                if inc is not None:
                    row["increase"] = round(inc, 6)
                r = self.rate(series, window_s, labels)
                if r is not None:
                    row["rate"] = round(r, 6)
            else:
                for fn, label in (
                    (self.delta, "delta"),
                    (self.max_over_time, "max"),
                    (self.avg_over_time, "avg"),
                ):
                    v = fn(series, window_s, labels)
                    if v is not None:
                        row[label] = round(v, 6)
            rows.append(row)
        out["query"] = {
            "series": series,
            "found": True,
            "type": meta["type"],
            "windowS": window_s,
            "labelSets": rows,
        }
        return out


class Sampler:
    """Background cadence thread over one Retention store. Hooks run
    after every sweep on the sampler thread (the alert engine rides
    here so rule evaluation shares the retention clock)."""

    def __init__(self, retention: Retention):
        self.retention = retention
        self.interval_s = DEFAULT_INTERVAL_S
        self._hooks: List[Callable[[], None]] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = sanitizer.lock("timeseries.sampler")

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def add_hook(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn not in self._hooks:
                self._hooks.append(fn)

    def sweep(self) -> None:
        """One sweep + hooks (also the synchronous entry point for
        tests and CLI paths that want deterministic sampling)."""
        t0 = time.monotonic()
        self.retention.sample_now()
        with self._lock:
            hooks = list(self._hooks)
        for fn in hooks:
            try:
                fn()
            except Exception:
                pass  # a broken hook must not kill the cadence
        SAMPLE_SECONDS.observe(time.monotonic() - t0)

    def start(self, interval_s: Optional[float] = None) -> "Sampler":
        """Idempotent: the first caller sets the cadence; later callers
        get the running sampler (one per process, like capacity's
        monitor)."""
        with self._lock:
            if interval_s is not None:
                self.interval_s = float(interval_s)
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="kt-timeseries-sampler"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
            self._thread = None
        # Join OUTSIDE the lock: the sampler thread's sweep takes it
        # for the hook list, so joining under it would deadlock until
        # the timeout.
        if t is not None:
            t.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sweep()
            except Exception:
                pass  # the health plane must never take a daemon down


#: Process-global retention + sampler (the shape every plane uses:
#: capacity.DEFAULT, rebalance.DEFAULT, ...). Nothing runs until
#: ensure_started() — unit tests and thin apiservers pay nothing.
DEFAULT = Retention()
SAMPLER = Sampler(DEFAULT)


def ensure_started(interval_s: Optional[float] = None) -> Sampler:
    """Start the process-global sampler if not already running
    (daemons, local-up, soak, bench). KT_TIMESERIES=0 disables."""
    if os.environ.get("KT_TIMESERIES", "1") == "0":
        return SAMPLER
    return SAMPLER.start(interval_s=interval_s)
