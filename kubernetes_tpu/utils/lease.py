"""CAS-renewed lease with a monotonic fencing token.

utils/leaderelect.py (podmaster.go's recipe) answers "who runs the
daemon"; this module answers the harder half of that question: "whose
*writes* are still legitimate". A lease object lives in the store (an
annotated Endpoints record in kube-system, CAS'd through resourceVersion
exactly like the elector's lock) and additionally carries a **fencing
token** — an integer bumped on every change of effective holder, never
on a plain renewal. Any actor doing work on behalf of the lease attaches
its token; validate()/require() refuse tokens older than the current one,
so a stale holder — paused, partitioned, or running on a slow clock —
cannot corrupt state after a takeover even though it still *believes*
it is the leader. (The classic Chubby/ZooKeeper fencing argument: lease
expiry alone cannot stop a holder that does not know the time.)

Failure seams (seeded, deterministic — utils/faults.py):
- ``lease.renew.lost``: the holder's renew CAS vanishes in flight; the
  holder must keep believing only until the lease window expires on its
  own clock, and its token must fence once a rival steals.
- ``lease.clock.skew``: the holder's clock starts running slow by one
  lease duration, so it believes an expired lease is live — the exact
  scenario fencing exists for.

``LeaseElector`` wraps the client in the renew/steal loop (same shape
as LeaderElector, plus the token threaded into the callbacks) and is
what gates the warm-standby scheduler (scheduler/standby.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import faults, metrics

LEASE_NAMESPACE = "kube-system"
HOLDER_KEY = "lease.kubernetes-tpu.io/holder"
RENEW_KEY = "lease.kubernetes-tpu.io/renew-time"
TOKEN_KEY = "lease.kubernetes-tpu.io/fencing-token"

ELECTIONS = metrics.DEFAULT.counter(
    "leader_elections_total",
    "Leadership acquisitions (fencing-token bumps) per control-plane tier",
    labels=("tier",),
)

RENEW_LATENCY = metrics.DEFAULT.histogram(
    "lease_renew_latency_seconds",
    "Lease CAS round-trip (read + conditional write) per op — renew "
    "for the live holder's heartbeat, acquire for create/steal/observe "
    "passes. Must stay well under the lease window: a holder whose "
    "renews take longer than the window demotes itself on slow "
    "storage (utils/slo.py lease_renew_latency; utils/alerts.py "
    "lease_renew_latency burn rule).",
    labels=("op",),
)


class LeaseFenceError(Exception):
    """A write carried a fencing token older than the current lease —
    the writer lost leadership and must stop."""


class LeaseRecord:
    """Immutable snapshot of the lease object."""

    __slots__ = ("holder", "token", "renewed", "resource_version")

    def __init__(self, holder: str, token: int, renewed: float,
                 resource_version: Optional[int]):
        self.holder = holder
        self.token = token
        self.renewed = renewed
        self.resource_version = resource_version

    def __repr__(self) -> str:
        return (
            f"<Lease holder={self.holder!r} token={self.token} "
            f"renewed={self.renewed:.3f}>"
        )


class LeaseClient:
    """CAS lease mechanics for one identity over one named lease.

    `clock` is injectable (property tests drive whole renew/expire/
    steal schedules without sleeping). The LEASE_CLOCK_SKEW fault makes
    THIS identity's view of that clock run slow by one lease duration
    from the moment it fires — the store's record always carries true
    clock times (written by whoever renews), only the local holder
    belief skews."""

    def __init__(
        self,
        client,
        name: str,
        identity: str,
        tier: str = "scheduler",
        lease_duration: float = 5.0,
        clock: Callable[[], float] = time.time,
    ):
        self.client = client
        self.name = name
        self.identity = identity
        self.tier = tier
        self.lease_duration = lease_duration
        self._clock = clock
        self._skew = 0.0
        # Local belief: what this identity thinks it holds. Updated
        # only by its own acquire/renew outcomes and its own (possibly
        # skewed) clock — exactly the information a real process has.
        self._held_token: Optional[int] = None
        self._renewed_local = 0.0

    # -- clock --------------------------------------------------------

    def now(self) -> float:
        if faults.enabled() and faults.fire(
            faults.LEASE_CLOCK_SKEW, self.identity
        ):
            self._skew += self.lease_duration
        return self._clock() - self._skew

    # -- record I/O ---------------------------------------------------

    def _read_obj(self):
        try:
            return self.client.get(
                "endpoints", self.name, namespace=LEASE_NAMESPACE
            )
        except APIError as e:
            if e.code == 404:
                return None
            raise

    @staticmethod
    def _record_of(obj) -> LeaseRecord:
        ann = obj.metadata.annotations or {}
        try:
            renewed = float(ann.get(RENEW_KEY, "0") or "0")
        except ValueError:
            renewed = 0.0
        try:
            token = int(ann.get(TOKEN_KEY, "0") or "0")
        except ValueError:
            token = 0
        rv = None
        try:
            rv = int(obj.metadata.resource_version or 0)
        except (TypeError, ValueError):
            pass
        return LeaseRecord(ann.get(HOLDER_KEY, ""), token, renewed, rv)

    def read(self) -> Optional[LeaseRecord]:
        obj = self._read_obj()
        return None if obj is None else self._record_of(obj)

    def try_acquire(self) -> Optional[int]:
        """Acquire, steal, or renew; returns the fencing token while
        held after this call, None otherwise. A plain renewal keeps the
        token; any change of effective holder — fresh create, steal of
        an expired lease, or re-acquisition after this identity's own
        lease lapsed — bumps it (and counts as an election)."""
        t0 = time.monotonic()
        self._last_op = "acquire"
        try:
            return self._try_acquire()
        finally:
            # Failed/slow CAS rounds count too — a renew that times out
            # is exactly the latency the SLO and burn rule exist for.
            RENEW_LATENCY.observe(time.monotonic() - t0, op=self._last_op)

    def _try_acquire(self) -> Optional[int]:
        now = self.now()
        obj = self._read_obj()
        rec = None if obj is None else self._record_of(obj)
        if rec is None:
            # No lease yet: atomic create; the loser of the race 409s.
            try:
                self.client.create(
                    "endpoints",
                    {
                        "kind": "Endpoints",
                        "metadata": {
                            "name": self.name,
                            "namespace": LEASE_NAMESPACE,
                            "annotations": {
                                HOLDER_KEY: self.identity,
                                RENEW_KEY: str(self._clock()),
                                TOKEN_KEY: "1",
                            },
                        },
                    },
                    namespace=LEASE_NAMESPACE,
                )
            except APIError as e:
                if e.code == 409:
                    return self.held_token()
                raise
            self._held_token = 1
            self._renewed_local = now
            ELECTIONS.inc(tier=self.tier)
            return 1
        true_now = self._clock()
        renewing = (
            rec.holder == self.identity and self._held_token == rec.token
        )
        if renewing:
            self._last_op = "renew"
        expired = true_now - rec.renewed >= self.lease_duration
        if not renewing and not expired:
            return self.held_token()  # someone else holds a live lease
        if renewing and not expired:
            token = rec.token
        else:
            token = rec.token + 1  # takeover: new fencing epoch
        if renewing:
            # The renew CAS can be lost in flight (partition from the
            # lease store). The holder's record write never landed;
            # its local belief decays on its own clock below.
            faults.fire(faults.LEASE_RENEW_LOST, self.identity)
        try:
            # CAS against the resourceVersion of the SAME read the
            # decision used: any rival write in between conflicts.
            ann = dict(obj.metadata.annotations or {})
            ann[HOLDER_KEY] = self.identity
            ann[RENEW_KEY] = str(true_now)
            ann[TOKEN_KEY] = str(token)
            obj.metadata.annotations = ann
            self.client.update("endpoints", obj, namespace=LEASE_NAMESPACE)
        except faults.FaultInjected:
            raise
        except APIError as e:
            if e.code in (404, 409):
                return self.held_token()  # lost the race
            raise
        self._held_token = token
        self._renewed_local = now
        if not renewing:
            ELECTIONS.inc(tier=self.tier)
        return token

    def release(self) -> None:
        """Drop the lease cooperatively (renew-time zeroed so a standby
        can take over immediately); local belief clears regardless."""
        token, self._held_token = self._held_token, None
        if token is None:
            return
        try:
            obj = self.client.get(
                "endpoints", self.name, namespace=LEASE_NAMESPACE
            )
            ann = dict(obj.metadata.annotations or {})
            if ann.get(HOLDER_KEY) != self.identity:
                return
            ann[RENEW_KEY] = "0"
            obj.metadata.annotations = ann
            self.client.update("endpoints", obj, namespace=LEASE_NAMESPACE)
        except APIError:
            pass  # best effort: expiry reclaims it anyway

    # -- belief + fencing ---------------------------------------------

    def held_token(self) -> Optional[int]:
        """The token this identity BELIEVES it holds, decayed on its
        own (possibly skewed) clock — None once the window lapses."""
        if self._held_token is None:
            return None
        if self.now() - self._renewed_local >= self.lease_duration:
            return None  # could have been stolen; stop acting
        return self._held_token

    def validate(self, token: Optional[int]) -> bool:
        """True iff `token` is the CURRENT fencing token — the check a
        resource guards writes with. Reads the record (the fencing
        authority is the store, never anyone's local clock)."""
        if token is None:
            return False
        rec = self.read()
        return rec is not None and rec.token == token

    def require(self, token: Optional[int]) -> None:
        if not self.validate(token):
            rec = self.read()
            raise LeaseFenceError(
                f"{self.identity}: fencing token {token} is stale "
                f"(current: {rec.token if rec else 'none'})"
            )


class LeaseElector:
    """Renew/steal loop over a LeaseClient (LeaderElector's shape, with
    the fencing token threaded through). on_elected(token) fires once
    per acquisition; on_renewed(token) on every successful renew;
    on_lost() when the belief window lapses or a rival CAS'd past."""

    def __init__(
        self,
        lease: LeaseClient,
        renew_period: float = 1.0,
        retry_period: float = 1.0,
        on_elected: Optional[Callable[[int], None]] = None,
        on_renewed: Optional[Callable[[int], None]] = None,
        on_lost: Optional[Callable[[], None]] = None,
    ):
        self.lease = lease
        self.renew_period = renew_period
        self.retry_period = retry_period
        self.on_elected = on_elected or (lambda _t: None)
        self.on_renewed = on_renewed or (lambda _t: None)
        self.on_lost = on_lost or (lambda: None)
        self.token: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self.token is not None

    def start(self) -> "LeaseElector":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"lease-{self.lease.name}-{self.lease.identity}",
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.token is not None:
            self.token = None
            self.lease.release()
            try:
                self.on_lost()
            except Exception:
                pass

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                token = self.lease.try_acquire()
            except Exception:
                # Transient failure (including an injected renew-lost):
                # keep believing only within the local lease window.
                token = self.lease.held_token()
            if self._stop.is_set():
                return
            if token is not None and self.token is None:
                self.token = token
                try:
                    self.on_elected(token)
                except Exception:
                    pass
            elif token is not None:
                self.token = token
                try:
                    self.on_renewed(token)
                except Exception:
                    pass
            elif self.token is not None:
                self.token = None
                try:
                    self.on_lost()
                except Exception:
                    pass
            self._stop.wait(
                self.renew_period if self.is_leader else self.retry_period
            )
