"""ktchaos: a process-global, deterministically seeded fault registry.

The control plane now has real recovery machinery — WAL replay with
torn-line truncation, watch re-list on drops, bind CAS, gang rollback,
graceful-delete confirmation — but until this module, none of it was
*driven*: the code paths only ran when the world happened to misbehave.
This registry turns each recovery seam into a named injection site that
tests and the soak harness (tools/soak.py) can fire on a seeded,
reproducible schedule.

Mirrors the ``KT_SANITIZE`` pattern (utils/sanitizer.py): OFF by
default with one module-global check per ``fire()`` call, so
instrumenting hot paths (WAL append, watch push, heartbeats) costs a
predicate and nothing else. ON via ``KT_FAULTS=<spec>`` in the
environment or the programmatic API (:func:`inject` / :func:`configure`).

Sites are REGISTERED NAMED CONSTANTS in this module — ``faults.fire(
faults.WAL_FSYNC)``, never ``faults.fire("kvstore.wal.fsync")`` — so
the site inventory stays auditable exactly like the sanitizer's lock
names (ktlint rule KT008 enforces this statically; see
tools/ktlint/rules_faults.py).

Determinism: every site owns its own ``random.Random`` seeded from
``(seed, site name)`` and its own call counter, so the firing schedule
at one site is a pure function of (seed, rule, per-site call index) —
independent of how OTHER sites' calls interleave across threads. The
soak harness's acceptance bar ("same seed reproduces the same fault
timeline") rests on this.

Rule grammar (``KT_FAULTS`` / :func:`configure`)::

    seed=42;kvstore.wal.fsync:p=0.01,times=3;http.request.latency:every=7,delay=0.02

``;``-separated rules, each ``<site>:<k>=<v>,...`` with knobs

- ``p``      per-call firing probability (site-seeded RNG);
- ``every``  fire every Nth eligible call (deterministic cadence);
- ``times``  stop after N firings (budget);
- ``after``  skip the first N calls at the site;
- ``delay``  sleep seconds for delay-kind sites (default 0.02).

What firing DOES is the site's declared kind:

- ``error``  raise the site's exception (``FaultInjected`` /
  ``InjectedIOError`` / an injected ``APIError``/``ConnectionError``);
- ``delay``  sleep ``delay`` seconds, then proceed;
- ``trip``   return True — the call site interprets it (torn WAL
  write, forced watch-stream drop, skipped heartbeat).

``fire()`` returns False when disabled or nothing fired, so call sites
read ``if faults.fire(faults.X): <site-specific behavior>``.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "FaultInjected",
    "InjectedIOError",
    "FaultSite",
    "SITES",
    "clear",
    "configure",
    "enabled",
    "fire",
    "inject",
    "reset_stats",
    "rules",
    "stats",
    "timeline",
]


class FaultInjected(Exception):
    """An injected failure (never raised by real code paths); carries
    the site name so logs/tests can tell chaos from genuine faults."""


class InjectedIOError(FaultInjected, OSError):
    """Injected I/O failure — an OSError so the code under test takes
    its real I/O-error path (WAL fsync, snapshot rename)."""


def _api_error_503(site: str):
    # Lazy import: utils must stay importable below the server layer.
    from kubernetes_tpu.server.api import APIError

    return APIError(
        503, "ServiceUnavailable", f"fault injected at {site}"
    )


class FaultSite:
    """A named injection point. Instances are the module constants
    below — the one place sites are minted (KT008)."""

    __slots__ = ("name", "kind", "exc", "doc")

    def __init__(self, name: str, kind: str, exc=None, doc: str = ""):
        assert kind in ("error", "delay", "trip")
        self.name = name
        self.kind = kind
        self.exc = exc  # callable(site_name) -> Exception, for "error"
        self.doc = doc

    def __repr__(self) -> str:
        return f"<FaultSite {self.name} [{self.kind}]>"


#: name -> FaultSite; populated by _site() only (module constants).
SITES: Dict[str, FaultSite] = {}


def _site(name: str, kind: str, exc=None, doc: str = "") -> FaultSite:
    site = FaultSite(name, kind, exc=exc, doc=doc)
    SITES[name] = site
    return site


def _fi(site: str) -> Exception:
    return FaultInjected(f"fault injected at {site}")


def _io(site: str) -> Exception:
    return InjectedIOError(f"fault injected at {site}")


def _reset(site: str) -> Exception:
    return ConnectionResetError(f"fault injected at {site}")


# -- the site inventory -------------------------------------------------
# kvstore durability seams (store/kvstore.py):
WAL_TORN_WRITE = _site(
    "kvstore.wal.torn_write", "trip",
    doc="append only a prefix of the WAL record (no newline) and raise "
        "— the mid-append process death _recover()'s torn-line "
        "truncation exists for; pair with KVStore.crash()",
)
WAL_FSYNC = _site(
    "kvstore.wal.fsync", "error", exc=_io,
    doc="group-commit fsync fails; the acking writer surfaces a real "
        "I/O error and the write is flushed-but-not-durable",
)
SNAPSHOT_RENAME = _site(
    "kvstore.snapshot.rename", "error", exc=_io,
    doc="crash before the snapshot's os.replace — recovery must keep "
        "serving from the previous snapshot + full WAL",
)
# watch fan-out (store/watch.py):
WATCH_DROP = _site(
    "watch.stream.drop", "trip",
    doc="force the slow-consumer drop on a store-fed stream; the "
        "consumer must re-list (Reflector backoff path)",
)
WATCH_DELAY = _site(
    "watch.stream.delay", "delay",
    doc="stall event delivery on the dispatcher thread",
)
# client HTTP transport (client/rest.py):
HTTP_RESET = _site(
    "http.request.reset", "error", exc=_reset,
    doc="connection reset before the request is sent; idempotent "
        "verbs retry with capped jittered backoff",
)
HTTP_5XX = _site(
    "http.request.error5xx", "error", exc=_api_error_503,
    doc="transient server 5xx; idempotent verbs retry with backoff",
)
HTTP_DELAY = _site(
    "http.request.latency", "delay",
    doc="added request latency on the client transport",
)
# scheduler commit path (scheduler/daemon.py):
SCHED_COMMIT_CRASH = _site(
    "scheduler.commit.crash", "error", exc=_fi,
    doc="daemon dies between solve and commit: the commit job raises "
        "before any bind lands — recovery is a daemon restart that "
        "rebuilds its SolverSession from LIST+watch",
)
SCHED_EVICT_ERROR = _site(
    "scheduler.evict.error", "error", exc=_fi,
    doc="victim eviction fails transiently; the preemption pass must "
        "count evict_failed and retry without recording a nomination",
)
# descheduler move execution (controllers/descheduler.py):
DESCHED_MOVE_CRASH = _site(
    "descheduler.move.crash", "error", exc=_fi,
    doc="descheduler dies mid-move, after the eviction but before the "
        "replacement pod is recreated — the journaled move intent "
        "(PodTemplate) must let recovery re-pend the pod so a crashed "
        "defrag strands nothing",
)
# kubelet sync loop (kubelet/agent.py):
KUBELET_TERMINATING_STALL = _site(
    "kubelet.terminating.stall", "delay",
    doc="the Terminating confirm path stalls; grace-deadline handling "
        "and exactly-one-DELETED must survive the lag",
)
KUBELET_HEARTBEAT_DROP = _site(
    "kubelet.heartbeat.drop", "trip",
    doc="skip a node status heartbeat (lost beat, not a dead kubelet)",
)
# lease-based leader election (utils/lease.py):
LEASE_RENEW_LOST = _site(
    "lease.renew.lost", "error", exc=_fi,
    doc="the holder's renew CAS is lost in flight (network partition "
        "from the lease store); the holder must demote itself once the "
        "lease window expires on its own clock, never before",
)
LEASE_CLOCK_SKEW = _site(
    "lease.clock.skew", "trip",
    doc="the holder's local clock runs slow by one lease duration: it "
        "believes it still holds an expired lease while a rival steals "
        "it — the fencing token is what keeps its stale writes out",
)
# health-plane retention sampler (utils/timeseries.py):
TIMESERIES_SAMPLE_SKIP = _site(
    "timeseries.sample.skip", "trip",
    doc="the retention sampler misses a cadence beat (GC pause / "
        "stalled scrape analog); windowed queries must degrade to the "
        "surviving samples, never extrapolate through the gap",
)


# -- rule state ---------------------------------------------------------


class FaultRule:
    """One armed rule at one site. Mutable counters are guarded by the
    module lock; the parameters are frozen at install."""

    __slots__ = ("site", "p", "every", "times", "after", "delay_s", "fired")

    def __init__(
        self,
        site: FaultSite,
        p: float = 0.0,
        every: int = 0,
        times: Optional[int] = None,
        after: int = 0,
        delay_s: float = 0.02,
    ):
        if p <= 0.0 and every <= 0:
            raise ValueError(
                f"rule at {site.name}: need p= or every= to ever fire"
            )
        self.site = site
        self.p = float(p)
        self.every = int(every)
        self.times = None if times is None else int(times)
        self.after = int(after)
        self.delay_s = float(delay_s)
        self.fired = 0

    def describe(self) -> dict:
        return {
            "site": self.site.name,
            "p": self.p,
            "every": self.every,
            "times": self.times,
            "after": self.after,
            "delay_s": self.delay_s,
            "fired": self.fired,
        }


class _SiteState:
    __slots__ = ("calls", "fired", "rng")

    def __init__(self, seed: int, name: str):
        self.calls = 0
        self.fired = 0
        self.rng = random.Random(f"{seed}:{name}")


#: Master switch — a plain module global, read on every fire() (the
#: zero-cost-when-off contract, same shape as sanitizer._enabled).
_enabled = False

_lock = threading.Lock()
_seed = 0
_rules: Dict[str, List[FaultRule]] = {}
_state: Dict[str, _SiteState] = {}
#: Bounded fired-event log: (site name, per-site call index). The soak
#: artifact records it as the realized fault timeline.
_timeline: List[Tuple[str, int]] = []
_MAX_TIMELINE = 4096


def enabled() -> bool:
    return _enabled


def _state_for_locked(name: str) -> _SiteState:
    st = _state.get(name)
    if st is None:
        st = _state[name] = _SiteState(_seed, name)
    return st


def inject(site: FaultSite, **kw) -> FaultRule:
    """Arm a rule at `site` (see FaultRule knobs) and enable the
    registry. Returns the rule (live counters) so tests can assert
    `rule.fired`."""
    global _enabled
    if not isinstance(site, FaultSite):
        raise TypeError(
            "inject() takes a registered FaultSite constant "
            "(faults.WAL_FSYNC, ...), not a string — KT008"
        )
    rule = FaultRule(site, **kw)
    with _lock:
        _rules.setdefault(site.name, []).append(rule)
        _state_for_locked(site.name)
        _enabled = True
    return rule


def clear(site: Optional[FaultSite] = None) -> None:
    """Disarm rules (one site, or all) — the registry disables itself
    when no rule remains armed. Per-site call counters and the timeline
    survive until reset_stats()."""
    global _enabled
    with _lock:
        if site is None:
            _rules.clear()
        else:
            _rules.pop(site.name, None)
        if not _rules:
            _enabled = False


def reset_stats(reseed: Optional[int] = None) -> None:
    """Drop counters, per-site RNG state and the timeline (a fresh
    deterministic run); optionally install a new seed."""
    global _seed
    with _lock:
        if reseed is not None:
            _seed = int(reseed)
        _state.clear()
        del _timeline[:]
        for rs in _rules.values():
            for r in rs:
                r.fired = 0


def configure(spec: str, seed: Optional[int] = None) -> None:
    """Parse a KT_FAULTS-style spec and arm it (replacing any armed
    rules). Empty spec = disarm."""
    clear()
    if seed is not None:
        reset_stats(reseed=seed)
    for part in (spec or "").replace("\n", ";").split(";"):
        part = part.strip()
        if not part:
            continue
        if part.startswith("seed="):
            reset_stats(reseed=int(part[5:]))
            continue
        name, _, knobs = part.partition(":")
        name = name.strip()
        site = SITES.get(name)
        if site is None:
            raise ValueError(
                f"KT_FAULTS: unknown fault site {name!r} "
                f"(known: {', '.join(sorted(SITES))})"
            )
        kw: dict = {}
        for knob in knobs.split(","):
            knob = knob.strip()
            if not knob:
                continue
            k, _, v = knob.partition("=")
            k = k.strip()
            if k == "p":
                kw["p"] = float(v)
            elif k == "every":
                kw["every"] = int(v)
            elif k == "times":
                kw["times"] = int(v)
            elif k == "after":
                kw["after"] = int(v)
            elif k == "delay":
                kw["delay_s"] = float(v)
            else:
                raise ValueError(f"KT_FAULTS: unknown knob {k!r} in {part!r}")
        inject(site, **kw)


def fire(site: FaultSite, detail: str = "") -> bool:
    """Consult the armed rules for `site`. No-op (False) when the
    registry is off — the only cost hot paths pay. When a rule fires:
    error-kind sites RAISE, delay-kind sites sleep then return True,
    trip-kind sites return True for the call site to interpret."""
    if not _enabled:
        return False
    delay_s = 0.0
    fired = None
    with _lock:
        site_rules = _rules.get(site.name)
        st = _state_for_locked(site.name)
        st.calls += 1
        if not site_rules:
            return False
        for rule in site_rules:
            if st.calls <= rule.after:
                continue
            if rule.times is not None and rule.fired >= rule.times:
                continue
            eligible = st.calls - rule.after
            if rule.every > 0:
                if eligible % rule.every != 0:
                    continue
            elif not (rule.p > 0.0 and st.rng.random() < rule.p):
                continue
            rule.fired += 1
            st.fired += 1
            if len(_timeline) < _MAX_TIMELINE:
                _timeline.append((site.name, st.calls))
            fired = rule
            delay_s = rule.delay_s
            break
    if fired is None:
        return False
    if site.kind == "error":
        raise site.exc(site.name if not detail else f"{site.name}: {detail}")
    if site.kind == "delay":
        time.sleep(delay_s)
    return True


def rules() -> List[dict]:
    with _lock:
        return [r.describe() for rs in _rules.values() for r in rs]


def stats() -> Dict[str, dict]:
    """Per-site {calls, fired} counters (the soak artifact's
    faults-injected figure)."""
    with _lock:
        return {
            name: {"calls": st.calls, "fired": st.fired}
            for name, st in sorted(_state.items())
        }


def timeline() -> List[Tuple[str, int]]:
    """The realized fault timeline: (site, per-site call index) per
    firing, in process order (bounded)."""
    with _lock:
        return list(_timeline)


# -- env arming ---------------------------------------------------------

_env_spec = os.environ.get("KT_FAULTS", "")
if _env_spec:
    configure(_env_spec)
