"""Declarative SLO engine over the metrics registry.

An :class:`Objective` names a metric series, a percentile, and a
target; :func:`evaluate` turns the registry's current window into
pass / warn / burn verdicts. One engine serves every consumer —
``GET /debug/slo`` on the apiserver, ``ktctl slo`` / ``ktctl top
cluster``, the check.sh SLO smoke, and bench.py's gates — so
production and bench can never disagree about what an SLO means
(the pre-PR-9 state: bench.py derived its own ``bind_latency_slo`` /
``churn_api_slo`` / ``pod_crud_slo`` math inline).

Verdict ladder (worst wins):

    pass     within target (and outside the warn band)
    no_data  the series has no samples in the current window
    warn     inside the warn band, or a warn-severity objective breached
    burn     a gate-severity objective breached (error budget burning)

Objective kinds:

    quantile_max  series percentile must stay <= target (latency SLOs;
                  histograms/summaries — multiple matching label sets
                  evaluate as the WORST set, like HighLatencyRequests)
    counter_max   the summed counter must stay <= target (e.g. zero
                  dropped watch streams)
    gauge_max     the worst (max) live gauge value must stay <= target
                  (watermarks — replication follower lag)
    value_max     a directly supplied figure must stay <= target
    value_min     a directly supplied figure must stay >= target
                  (throughput floors; bench's churn/CRUD gates)

Windows: ``window_s`` is REAL when the retention plane has history
(utils/timeseries.py, PR 20): quantile_max evaluates the interpolated
quantile of the window's bucket DELTAS, counter_max the windowed
increase, gauge_max the windowed max — so a recovered burn returns to
``pass`` within one window. Without history (sampler never started —
unit tests, thin apiservers, bench's reset-based windows), objectives
fall back to the lifetime-cumulative series exactly as before; each
report entry carries ``windowed: true|false`` so a reader knows which
path verdicted. SLO gates and benches may still open fresh windows by
resetting the series (``reset_request_latency``); the fallback
preserves those semantics bit-for-bit.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

from kubernetes_tpu.utils import metrics

#: Verdict severity order — worst() picks the rightmost.
_RANK = {"pass": 0, "no_data": 1, "warn": 2, "burn": 3}


def worst(*verdicts: str) -> str:
    """The most severe of the given verdicts (pass < no_data < warn <
    burn); 'no_data' when none are given."""
    out = None
    for v in verdicts:
        if out is None or _RANK.get(v, 0) > _RANK.get(out, 0):
            out = v
    return out if out is not None else "no_data"


@dataclass(frozen=True)
class Objective:
    """One service-level objective against one metric series."""

    name: str
    series: str
    target: float
    #: quantile_max|counter_max|gauge_max|value_max|value_min
    kind: str = "quantile_max"
    percentile: float = 0.99
    #: Label filter as (name, value) pairs (hashable for frozen);
    #: partial filters evaluate the worst matching label set.
    labels: Tuple[Tuple[str, str], ...] = ()
    #: gate -> breach is "burn"; warn -> breach is only ever "warn"
    #: (advisory objectives, like bench's throughput floors on CI CPUs).
    severity: str = "gate"
    #: For max kinds: values above warn_ratio*target verdict "warn"
    #: before the target is breached. 0 disables the warn band.
    warn_ratio: float = 0.75
    #: Evaluation window: when > 0 AND the retention plane has history
    #: for the series, the objective verdicts the window's deltas;
    #: otherwise the lifetime-cumulative fallback (module docstring).
    window_s: float = 0.0
    description: str = ""


def verdict_for_value(obj: Objective, value: Optional[float]) -> str:
    """Verdict for a directly supplied figure (bench.py's entry point;
    also the final step of every registry evaluation)."""
    if value is None or (isinstance(value, float) and math.isnan(value)):
        return "no_data"
    breach = "warn" if obj.severity == "warn" else "burn"
    if obj.kind == "value_min":
        return "pass" if value >= obj.target else breach
    if value > obj.target:
        return breach
    if (
        obj.kind in ("quantile_max", "value_max", "gauge_max")
        and obj.warn_ratio
        and value > obj.warn_ratio * obj.target
    ):
        return "warn"
    return "pass"


def _matching_label_sets(metric, labels: Dict[str, str]):
    """Label-value dicts of the metric's live series matching the
    (possibly partial) filter."""
    for values in metric.label_values():
        lm = dict(zip(metric.label_names, values))
        if all(lm.get(k) == v for k, v in labels.items()):
            yield lm


def evaluate_objective(obj: Objective, registry=None, history=None) -> dict:
    """Evaluate one objective. Returns a dict entry for the SLO
    report: measured value, p50/p99 context, sample count, and the
    verdict.

    `history` is the retention plane (utils/timeseries.Retention;
    defaults to its process-global store). When the objective declares
    a window AND history holds enough samples for the series, the
    verdict comes from the window's deltas; otherwise the lifetime
    cumulative fallback below verdicts exactly as pre-PR-20."""
    registry = metrics.DEFAULT if registry is None else registry
    if history is None:
        from kubernetes_tpu.utils import timeseries

        history = timeseries.DEFAULT
    labels = dict(obj.labels)
    entry = {
        "name": obj.name,
        "series": obj.series,
        "kind": obj.kind,
        "target": obj.target,
        "severity": obj.severity,
        "samples": 0,
    }
    if labels:
        entry["labels"] = labels
    if obj.kind.startswith("quantile"):
        entry["percentile"] = obj.percentile
    if obj.description:
        entry["description"] = obj.description
    if obj.window_s > 0:
        entry["windowS"] = obj.window_s
    metric = registry.get(obj.series) if hasattr(registry, "get") else None
    if metric is None:
        entry["verdict"] = "no_data"
        return entry
    # A series registered under the objective's name but with the wrong
    # shape (a counter where a histogram is expected) is unmeasurable,
    # not a crash — /debug/health keeps serving.
    needed = "quantile" if obj.kind == "quantile_max" else "value"
    if not hasattr(metric, needed):
        entry["verdict"] = "no_data"
        return entry
    use_window = (
        obj.window_s > 0
        and history is not None
        and getattr(history, "sampled", False)
    )
    value: Optional[float] = None
    windowed = False
    if obj.kind == "counter_max":
        if use_window:
            # Windowed increase summed across matching label sets; a
            # series whose ring lacks two samples contributes nothing
            # (None) — all-None falls through to lifetime.
            w_total: Optional[float] = None
            for lm in _matching_label_sets(metric, labels):
                inc = history.increase(obj.series, obj.window_s, lm)
                if inc is not None:
                    w_total = (w_total or 0.0) + inc
            if w_total is not None:
                value = w_total
                entry["samples"] = int(w_total)
                windowed = True
        if not windowed:
            # A counter with no series yet IS zero (nothing has been
            # counted): verdict pass, but samples stay 0 so the
            # report's `sampled` flag (the ktctl slo miss contract) is
            # untouched.
            total = 0.0
            for lm in _matching_label_sets(metric, labels):
                total += metric.value(**lm)
            value = total
            entry["samples"] = int(total)
    elif obj.kind == "gauge_max":
        # Watermark objective: the WORST live (or windowed-max) value
        # across matching label sets — replication follower lag's
        # shape: any one follower trailing far is the problem.
        n_sets = 0
        for lm in _matching_label_sets(metric, labels):
            if use_window:
                v = history.max_over_time(obj.series, obj.window_s, lm)
                if v is not None:
                    windowed = True
                else:
                    v = metric.value(**lm)
            else:
                v = metric.value(**lm)
            n_sets += 1
            if value is None or v > value:
                value = v
        entry["samples"] = n_sets
    elif obj.kind == "quantile_max":
        samples = 0
        p50 = None
        if use_window:
            for lm in _matching_label_sets(metric, labels):
                q = history.quantile_over_time(
                    obj.series, obj.percentile, obj.window_s, lm
                )
                if q is None:
                    continue
                windowed = True
                # Worst matching label set carries the verdict.
                if value is None or q > value:
                    value = q
                q50 = history.quantile_over_time(
                    obj.series, 0.5, obj.window_s, lm
                )
                if q50 is not None and (p50 is None or q50 > p50):
                    p50 = q50
                hw = history.hist_window(obj.series, obj.window_s, lm)
                samples += hw[0] if hw is not None else 0
        if not windowed:
            for lm in _matching_label_sets(metric, labels):
                q = metric.quantile(obj.percentile, **lm)
                if math.isnan(q):
                    continue
                # Worst matching label set carries the verdict — the
                # HighLatencyRequests shape for partially-filtered
                # series.
                if value is None or q > value:
                    value = q
                q50 = metric.quantile(0.5, **lm)
                if not math.isnan(q50) and (p50 is None or q50 > p50):
                    p50 = q50
                count = getattr(metric, "count", None)
                samples += count(**lm) if count is not None else 0
        entry["samples"] = samples
        if p50 is not None:
            entry["p50"] = round(p50, 6)
        if value is not None:
            entry["p99" if obj.percentile >= 0.99 else "value"] = round(
                value, 6
            )
    else:
        # value_max / value_min objectives have no registry series to
        # read — they verdict figures the caller supplies
        # (verdict_for_value); evaluating them here reports no_data.
        entry["verdict"] = "no_data"
        return entry
    entry["windowed"] = windowed
    if value is not None:
        entry["value"] = round(value, 6)
    entry["verdict"] = verdict_for_value(obj, value)
    return entry


#: The cluster's default objective set — what /debug/slo serves and
#: ``ktctl slo`` renders. Latency targets are the reference's e2e bars
#: (99% of scheduling decisions < 1 s, docs/roadmap.md; density.go's
#: 5 s pod-startup watermark); the advisory (warn-severity) objectives
#: chart direction without failing CI CPU boxes.
DEFAULT_OBJECTIVES: Tuple[Objective, ...] = (
    Objective(
        "pod_startup_latency", "pod_startup_latency_seconds", target=5.0,
        labels=(("milestone", "running"),), window_s=300.0,
        description="watch-visible create -> kubelet Running, p99",
    ),
    Objective(
        "pod_bound_latency", "pod_startup_latency_seconds", target=1.0,
        labels=(("milestone", "bound"),), window_s=300.0,
        description="watch-visible create -> binding visible, p99 "
        "(the reference's 99%-in-1s scheduling SLO)",
    ),
    Objective(
        "pod_decision_latency", "pod_startup_latency_seconds", target=1.0,
        labels=(("milestone", "decision"),), severity="warn",
        window_s=300.0,
        description="watch-visible create -> flight-recorder decision, p99",
    ),
    Objective(
        "watch_fanout_lag", "watch_fanout_lag_versions", target=4096.0,
        severity="warn", warn_ratio=0.0, window_s=300.0,
        description="store versions a watch delivery trails the applied "
        "watermark by, p99",
    ),
    Objective(
        "watch_stream_drops", "watch_streams_dropped_total",
        kind="counter_max", target=0.0, window_s=300.0,
        description="slow-consumer watch streams dropped (forced relists)",
    ),
    Objective(
        "solve_phase_latency", "scheduler_phase_seconds", target=1.0,
        labels=(("phase", "solve"),), severity="warn", window_s=300.0,
        description="device solve dispatch phase, p99",
    ),
    Objective(
        "solver_compile_churn", "solver_xla_compiles_total",
        kind="counter_max", target=64.0, severity="warn",
        description="XLA solver compiles observed; shape-bucket padding "
        "keeps this bounded (PR-7 recompilation sentinel)",
    ),
    Objective(
        "capacity_fragmentation", "cluster_fragmentation_score",
        target=0.5, severity="warn",
        description="cluster fragmentation score (stranded capacity for "
        "the canonical probe-pod shapes), p99 — sustained high scores "
        "mean the free capacity exists but is unusable shards",
    ),
    Objective(
        "capacity_zero_headroom", "capacity_zero_headroom_ticks_total",
        kind="counter_max", target=0.0,
        description="scheduler ticks where pods were waiting and some "
        "live probe shape had ZERO cluster headroom — capacity "
        "starvation no reshuffle can fix",
    ),
    Objective(
        "rebalance_efficiency", "rebalance_moves_per_improvement",
        target=64.0, severity="warn",
        description="evictions spent per unit of measured "
        "fragmentation-score improvement, p99 — a defrag cycle must "
        "pay for its disruption (moves are cheap only when the score "
        "actually drops)",
    ),
    Objective(
        "rebalance_stranded_pods", "rebalance_stranded_pods_total",
        kind="counter_max", target=0.0,
        description="pods evicted by a defrag move that never "
        "re-bound (journal recovery exhausted) — the "
        "stranded-pod-after-defrag gate",
    ),
    # HA tier (PR 20, satellite of the PR 19 control plane): cover
    # replication and lease health out of the box, not only in the
    # bench failover gate. Warn severity: advisory until the alerting
    # plane's burn rules escalate (utils/alerts.py).
    Objective(
        "replication_follower_lag", "replication_follower_lag_versions",
        kind="gauge_max", target=4096.0, severity="warn", warn_ratio=0.0,
        window_s=300.0,
        description="store versions the slowest follower trails the "
        "leader's commit index by (worst follower; sustained lag is "
        "the pre-quorum-loss signal)",
    ),
    Objective(
        "lease_renew_latency", "lease_renew_latency_seconds", target=1.0,
        severity="warn", window_s=300.0,
        description="lease acquire/renew CAS round-trip, p99 — must "
        "stay well under the 5s lease window or holders start "
        "demoting themselves on slow storage",
    ),
)


#: Bench gate objectives (bench.py reads targets AND verdicts from
#: here; tests/test_bind_latency.py asserts the figures carry these
#: verdicts). The throughput floors are warn-severity: they chart the
#: API-plane targets (ROADMAP item 1) without failing CPU CI boxes.
BENCH_OBJECTIVES: Dict[str, Objective] = {
    "bind_latency_slo": Objective(
        "bind_latency_slo", "bind_latency_p99_s", target=0.1,
        kind="value_max", warn_ratio=0.0,
        description="p99 create -> binding watch-visible over the real "
        "HTTP control plane; 100ms is the always-resident incremental "
        "loop's bar at 1k nodes on TPU (bench callers may widen via "
        "gate_s, e.g. for the reference 1s SLO on CPU CI boxes)",
    ),
    "churn_api_slo": Objective(
        "churn_api_slo", "churn_api_pods_per_sec", target=25000.0,
        kind="value_min", severity="warn",
        description="API-plane bulk churn ingestion floor",
    ),
    "pod_crud_slo": Objective(
        "pod_crud_slo", "pod_crud_ops_per_sec", target=20000.0,
        kind="value_min", severity="warn",
        description="bulk CRUD ops floor over HTTP",
    ),
    "failover_to_first_bind_s": Objective(
        "failover_to_first_bind_s", "failover_to_first_bind_p99_s",
        target=1.0, kind="value_max", warn_ratio=0.0,
        description="scheduler-leader kill -> the warm standby's first "
        "bind watch-visible, p99; the warm-standby path (prewarmed "
        "SolverSession + hot informers + lease takeover) must land "
        "this under a second — the cold path pays LIST + session "
        "build + bucket compile and cannot",
    ),
}


def evaluate(
    objectives: Optional[Iterable[Objective]] = None, registry=None,
    history=None,
) -> dict:
    """Evaluate the objective set into an SLOReport dict (the
    /debug/slo response shape): per-objective entries plus the overall
    worst verdict and whether ANY objective has samples (``sampled`` —
    the ``ktctl slo`` empty-cluster miss contract keys on it)."""
    objectives = DEFAULT_OBJECTIVES if objectives is None else objectives
    entries: List[dict] = [
        evaluate_objective(o, registry=registry, history=history)
        for o in objectives
    ]
    # Overall verdict: worst MEASURED verdict — an objective with no
    # data yet must not drag a healthy cluster's overall to no_data
    # (it stays visible per-objective); all-no_data reports no_data.
    measured = [e["verdict"] for e in entries if e["verdict"] != "no_data"]
    return {
        "kind": "SLOReport",
        "verdict": worst(*measured) if measured else "no_data",
        "sampled": any(e["samples"] for e in entries),
        "objectives": entries,
    }


def with_target(obj: Objective, target: float) -> Objective:
    """The objective with a different target (bench knobs like
    ``gate_s`` tune the gate without forking the definition)."""
    return dataclasses.replace(obj, target=float(target))
