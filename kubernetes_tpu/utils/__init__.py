"""Shared utilities: metrics, rate limiting, backoff, loops."""
