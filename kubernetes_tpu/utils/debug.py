"""Debug/observability surfaces: request log, stack dump, profiler.

Reference analogs:
- pkg/httplog/ (request logging with verbosity) -> an in-memory ring of
  recent requests served at /debug/requests.
- net/http/pprof goroutine dump -> /debug/stacks renders every Python
  thread's current stack (the goroutine-dump equivalent for a threaded
  runtime).
- pprof CPU profile -> /debug/profile?seconds=N runs an in-process
  wall-clock sampling profiler over sys._current_frames() (py-spy
  style) and renders the hottest stacks.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
import traceback
from typing import Deque, Dict, Tuple


class RequestLog:
    """Fixed-size ring of recent HTTP requests (httplog analog)."""

    def __init__(self, size: int = 256):
        self._ring: Deque[Tuple[float, str, str, int, float]] = (
            collections.deque(maxlen=size)
        )
        self._lock = threading.Lock()

    def record(
        self, verb: str, path: str, code: int, duration_s: float
    ) -> None:
        with self._lock:
            self._ring.append((time.time(), verb, path, code, duration_s))

    def render(self) -> str:
        with self._lock:
            entries = list(self._ring)
        lines = [f"{'TIME':23} {'CODE':5} {'MS':>8}  VERB PATH"]
        for ts, verb, path, code, dur in reversed(entries):
            stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))
            lines.append(
                f"{stamp:23} {code:<5} {dur * 1000:8.1f}  {verb} {path}"
            )
        return "\n".join(lines) + "\n"


DEFAULT_REQUEST_LOG = RequestLog()


def dump_stacks() -> str:
    """Every thread's current stack (goroutine-dump analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} (id {tid}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out) + "\n"


def sample_profile(seconds: float = 2.0, interval: float = 0.01) -> str:
    """Wall-clock sampling profiler: periodically snapshot every
    thread's stack and report the hottest ones. No instrumentation, no
    tracing overhead on the profiled code — the same trade py-spy and
    pprof's CPU profile make."""
    if seconds != seconds:  # NaN slips through min/max clamps
        seconds = 2.0
    seconds = min(max(seconds, 0.1), 30.0)
    me = threading.get_ident()
    counts: Dict[Tuple[str, ...], int] = collections.defaultdict(int)
    samples = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # don't profile the profiler
            stack = []
            f = frame
            while f is not None and len(stack) < 24:
                code = f.f_code
                stack.append(f"{code.co_filename}:{f.f_lineno} {code.co_name}")
                f = f.f_back
            counts[tuple(reversed(stack))] += 1
        samples += 1
        time.sleep(interval)
    top = sorted(counts.items(), key=lambda kv: -kv[1])[:20]
    lines = [
        f"sampling profile: {samples} samples over {seconds:.1f}s "
        f"({len(counts)} distinct stacks)",
        "",
    ]
    for stack, n in top:
        lines.append(f"=== {n} samples ({100.0 * n / max(samples, 1):.1f}%) ===")
        lines.extend(f"  {frame}" for frame in stack[-12:])
        lines.append("")
    return "\n".join(lines) + "\n"
