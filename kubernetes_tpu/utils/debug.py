"""Debug/observability surfaces: request log, stack dump, profiler.

Reference analogs:
- pkg/httplog/ (request logging with verbosity) -> an in-memory ring of
  recent requests served at /debug/requests; entries carry the request's
  X-Trace-Id (when the client stamped one) so a slow request in the
  ring can be looked up in /debug/traces directly.
- net/http/pprof goroutine dump -> /debug/stacks renders every Python
  thread's current stack (the goroutine-dump equivalent for a threaded
  runtime).
- pprof CPU profile -> /debug/profile?seconds=N runs an in-process
  wall-clock sampling profiler over sys._current_frames() (py-spy
  style) and renders the hottest stacks — human-readable by default,
  or folded stacks (?format=collapsed: flamegraph.pl / speedscope
  input) for flamegraph tooling.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
import traceback
from typing import Deque, Dict, Tuple


class RequestLog:
    """Fixed-size ring of recent HTTP requests (httplog analog)."""

    def __init__(self, size: int = 256):
        self._ring: Deque[Tuple[float, str, str, int, float, str]] = (
            collections.deque(maxlen=size)
        )
        self._lock = threading.Lock()

    def record(
        self,
        verb: str,
        path: str,
        code: int,
        duration_s: float,
        trace_id: str = "",
    ) -> None:
        with self._lock:
            self._ring.append(
                (time.time(), verb, path, code, duration_s, trace_id)
            )

    def render(self) -> str:
        with self._lock:
            entries = list(self._ring)
        lines = [
            f"{'TIME':23} {'CODE':5} {'MS':>8}  {'TRACE':16} VERB PATH"
        ]
        for ts, verb, path, code, dur, tid in reversed(entries):
            stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))
            lines.append(
                f"{stamp:23} {code:<5} {dur * 1000:8.1f}  "
                f"{(tid or '-'):16} {verb} {path}"
            )
        return "\n".join(lines) + "\n"


DEFAULT_REQUEST_LOG = RequestLog()


def dump_stacks() -> str:
    """Every thread's current stack (goroutine-dump analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(tid, '?')} (id {tid}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out) + "\n"


def _collect_samples(
    seconds: float, interval: float
) -> Tuple[Dict[Tuple[Tuple[str, int, str], ...], int], int]:
    """(stack -> sample count, total samples): the sampling loop shared
    by both render formats. Stacks are root-first tuples of (filename,
    lineno, funcname) frames."""
    me = threading.get_ident()
    counts: Dict[Tuple[Tuple[str, int, str], ...], int] = (
        collections.defaultdict(int)
    )
    samples = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # don't profile the profiler
            stack = []
            f = frame
            while f is not None and len(stack) < 24:
                code = f.f_code
                stack.append((code.co_filename, f.f_lineno, code.co_name))
                f = f.f_back
            counts[tuple(reversed(stack))] += 1
        samples += 1
        time.sleep(interval)
    return counts, samples


def _render_top(counts, samples: int, seconds: float) -> str:
    top = sorted(counts.items(), key=lambda kv: -kv[1])[:20]
    lines = [
        f"sampling profile: {samples} samples over {seconds:.1f}s "
        f"({len(counts)} distinct stacks)",
        "",
    ]
    for stack, n in top:
        lines.append(f"=== {n} samples ({100.0 * n / max(samples, 1):.1f}%) ===")
        lines.extend(
            f"  {fname}:{lineno} {func}"
            for fname, lineno, func in stack[-12:]
        )
        lines.append("")
    return "\n".join(lines) + "\n"


def _render_collapsed(counts) -> str:
    """Folded stacks: one 'frame;frame;frame count' line per distinct
    stack, root first — flamegraph.pl / speedscope input. Frames are
    'func (file:line)'; semicolons inside a frame would split the
    fold, so they are scrubbed."""
    lines = []
    for stack, n in sorted(counts.items(), key=lambda kv: -kv[1]):
        if not stack:
            continue
        folded = ";".join(
            f"{func} ({fname}:{lineno})".replace(";", ":")
            for fname, lineno, func in stack
        )
        lines.append(f"{folded} {n}")
    return "\n".join(lines) + "\n"


def sample_profile(
    seconds: float = 2.0, interval: float = 0.01, fmt: str = "top"
) -> str:
    """Wall-clock sampling profiler: periodically snapshot every
    thread's stack and report the hottest ones. No instrumentation, no
    tracing overhead on the profiled code — the same trade py-spy and
    pprof's CPU profile make. fmt: "top" (human-readable hottest
    stacks) or "collapsed" (folded stacks for flamegraph tooling)."""
    if seconds != seconds:  # NaN slips through min/max clamps
        seconds = 2.0
    seconds = min(max(seconds, 0.1), 30.0)
    counts, samples = _collect_samples(seconds, interval)
    if fmt == "collapsed":
        return _render_collapsed(counts)
    return _render_top(counts, samples, seconds)
