"""ktsan, runtime half: an opt-in lock/blocking-call sanitizer.

The API plane is now genuinely concurrent — WAL group commit, the
watch cache's event feed, informer-fed controllers, bulk write paths —
and the class of bug that ships silently there is not a wrong value
but a wrong *ordering*: two locks taken in opposite orders on two
threads, or a disk flush performed while holding the lock every other
writer needs. ktlint's KT002 sees one function at a time; this module
watches the locks actually taken at runtime.

Usage: components create their locks through the factory instead of
``threading.Lock()``::

    from kubernetes_tpu.utils import sanitizer
    self._lock = sanitizer.lock("kvstore.lock")
    self._sync_lock = sanitizer.lock("kvstore.sync", io_gate=True)

When the sanitizer is OFF (the default) the factory returns a plain
``threading.Lock``/``RLock`` — zero overhead, nothing imported beyond
stdlib. When ON (``KT_SANITIZE=locks`` in the environment, or
:func:`enable` — tests/conftest.py flips it for the concurrency-heavy
modules), the factory returns instrumented wrappers that feed three
detectors:

1. **Lock-order inversions.** Every acquisition taken while other
   sanitized locks are held adds a ``held -> acquired`` edge to a
   process-global graph keyed by the factory NAME (instances
   aggregate: any ``kvstore.lock`` before any ``watchcache.resource``
   is one edge). A new edge that closes a cycle is a potential
   deadlock and is recorded as a finding with both stacks.
2. **Blocking calls under a lock.** While enabled, ``os.fsync``,
   ``os.fdatasync``, socket connect/accept/recv/sendall,
   ``threading.Event.wait`` *without a timeout*, and the solver's jit
   dispatch entry points (they call :func:`check_blocking`) report a
   finding when any sanitized non-``io_gate`` lock is held. This
   generalizes the kvstore ``_wal_sync`` group-commit invariant from
   PR 3 ("never fsync under self._lock") into an enforced runtime
   check. ``io_gate=True`` marks a lock whose declared PURPOSE is
   serializing blocking I/O (the kvstore sync lock); blocking under
   only io-gate locks is the design, not a finding. A legitimate
   exception (the kvstore snapshot, a stop-the-world compaction) wraps
   itself in :func:`allow_blocking` with a reason.
3. **Leaks at teardown.** :func:`leaked_locks` lists sanitized locks
   still held by threads that have exited (a thread died holding a
   lock — every later acquirer deadlocks); the conftest thread-leak
   fixture pairs it with a live-thread snapshot.

Findings accumulate in-process (:func:`findings`, :func:`reset`); with
``KT_SANITIZE_REPORT=<path>`` the edge graph + findings are dumped as
JSON at exit so ``python -m tools.ktlint --lock-graph --runtime-graph
<path>`` can merge the observed ordering with the statically extracted
one (the node names match by construction).
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import socket
import threading
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = [
    "allow_blocking",
    "check_blocking",
    "disable",
    "edges",
    "enable",
    "enabled",
    "findings",
    "held_locks",
    "leaked_locks",
    "lock",
    "report",
    "reset",
    "rlock",
]

_ENV_MODES = frozenset(
    m.strip()
    for m in os.environ.get("KT_SANITIZE", "").replace(";", ",").split(",")
    if m.strip()
)

#: Master switch. Read on every hot operation, so it must stay a plain
#: module global (one dict lookup + truth test when off).
_enabled = "locks" in _ENV_MODES or "all" in _ENV_MODES

# The sanitizer's own locks are PLAIN locks on purpose (instrumenting
# them would recurse) and are leaves: no user code ever runs under
# them.
_meta = threading.Lock()

# (held_name, acquired_name) -> {"count", "site"} — first observation
# keeps its acquisition site for the report.
_edges: Dict[Tuple[str, str], dict] = {}
_cycles_seen: set = set()
# Finding dicts: {"kind", "detail", ...}. Bounded (newest dropped) so a
# hot loop with a systematic violation can't OOM the process.
_findings: List[dict] = []
_MAX_FINDINGS = 256
_blocking_seen: set = set()

# thread ident -> (thread name, held-stack list). The list object is
# shared with that thread's TLS, so reading it from another thread
# (leak checks) sees the live stack.
_thread_stacks: Dict[int, Tuple[str, list]] = {}

_tls = threading.local()


class _Held:
    __slots__ = ("obj_id", "name", "io_gate")

    def __init__(self, obj_id: int, name: str, io_gate: bool):
        self.obj_id = obj_id
        self.name = name
        self.io_gate = io_gate


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
        t = threading.current_thread()
        with _meta:
            _thread_stacks[t.ident] = (t.name, st)
    return st


def _site(skip_prefixes=("sanitizer.py",)) -> str:
    """Compact 'file:line in func' chain of the last few frames outside
    this module. Only computed on findings/new edges — never hot."""
    frames = traceback.extract_stack()
    keep = [
        f for f in frames
        if not f.filename.endswith(skip_prefixes)
    ][-6:]
    return " <- ".join(
        f"{os.path.basename(f.filename)}:{f.lineno}({f.name})"
        for f in reversed(keep)
    )


def _add_finding(kind: str, **kw) -> None:
    with _meta:
        if len(_findings) < _MAX_FINDINGS:
            _findings.append({"kind": kind, **kw})


# -- detector 1: lock-order graph --------------------------------------


def _path_exists(src: str, dst: str) -> Optional[List[str]]:
    """DFS over _edges (caller holds _meta). Returns the node path
    src..dst if one exists."""
    stack = [(src, [src])]
    seen = {src}
    adj: Dict[str, List[str]] = {}
    for a, b in _edges:
        adj.setdefault(a, []).append(b)
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquire(obj_id: int, name: str, io_gate: bool) -> None:
    st = _stack()
    if _enabled and st:
        for held in st:
            if held.obj_id == obj_id or held.name == name:
                # Same instance (RLock reentry is handled by the
                # wrapper) or a sibling instance of the same class —
                # same-name edges would make every two-store test a
                # false self-cycle.
                continue
            key = (held.name, name)
            with _meta:
                hit = _edges.get(key)
                if hit is not None:
                    hit["count"] += 1
                    continue
                back = _path_exists(name, held.name)
                _edges[key] = {"count": 1, "site": _site()}
                if back:
                    cycle = tuple(sorted(set(back)))
                    if cycle in _cycles_seen:
                        continue
                    _cycles_seen.add(cycle)
                    if len(_findings) < _MAX_FINDINGS:
                        _findings.append({
                            "kind": "lock-order-cycle",
                            "cycle": back + [name],
                            "edge": f"{held.name} -> {name}",
                            "site": _edges[key]["site"],
                            "reverse_site": _edges[
                                (back[0], back[1])
                            ]["site"] if len(back) > 1 else "",
                        })
    st.append(_Held(obj_id, name, io_gate))


def _note_release(obj_id: int) -> None:
    st = getattr(_tls, "stack", None)
    if not st:
        return
    # Almost always LIFO; scan from the top for the rare out-of-order
    # release (which is itself suspicious but legal for Lock objects
    # released by a different code path than acquired).
    for i in range(len(st) - 1, -1, -1):
        if st[i].obj_id == obj_id:
            del st[i]
            return


# -- detector 2: blocking calls under a lock ---------------------------


def check_blocking(kind: str, detail: str = "") -> None:
    """Record a finding if the calling thread performs blocking work
    (`kind`) while holding a sanitized non-io-gate lock. Near-zero when
    the sanitizer is off — instrument hot dispatch entry points
    freely."""
    if not _enabled:
        return
    if getattr(_tls, "allow", 0):
        return
    st = getattr(_tls, "stack", None)
    if not st:
        return
    held = [h.name for h in st if not h.io_gate]
    if not held:
        return
    dedup = (kind, tuple(held))
    with _meta:
        if dedup in _blocking_seen:
            return
        _blocking_seen.add(dedup)
    _add_finding(
        "blocking-under-lock",
        op=kind,
        detail=detail,
        locks=held,
        site=_site(),
    )


@contextlib.contextmanager
def allow_blocking(reason: str):
    """Suppress blocking-under-lock findings for a region whose
    blocking-while-locked behavior is the documented design (e.g. the
    kvstore snapshot's stop-the-world compaction). The reason string is
    the audit trail — grep for allow_blocking to review every grant."""
    _tls.allow = getattr(_tls, "allow", 0) + 1
    try:
        yield
    finally:
        _tls.allow -= 1


# -- instrumented lock types -------------------------------------------


class SanLock:
    """Instrumented non-reentrant lock. Duck-compatible with
    threading.Lock including use as the lock of a threading.Condition
    (the Condition falls back to release()/acquire() pairs, which keep
    the held-stack honest across wait())."""

    __slots__ = ("_inner", "name", "io_gate")

    def __init__(self, name: str, io_gate: bool = False):
        self._inner = threading.Lock()
        self.name = name
        self.io_gate = io_gate

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(id(self), self.name, self.io_gate)
        return ok

    def release(self) -> None:
        _note_release(id(self))
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<SanLock {self.name} {self._inner!r}>"


class SanRLock:
    """Instrumented reentrant lock. Tracks per-thread depth so only the
    OUTERMOST acquire/release touch the held-stack, and exposes the
    _is_owned/_release_save/_acquire_restore trio threading.Condition
    (and kvstore._wal_sync's ownership probe) relies on."""

    __slots__ = ("_inner", "name", "io_gate", "_depth")

    def __init__(self, name: str, io_gate: bool = False):
        self._inner = threading.RLock()
        self.name = name
        self.io_gate = io_gate
        self._depth = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            d = getattr(self._depth, "n", 0)
            self._depth.n = d + 1
            if d == 0:
                _note_acquire(id(self), self.name, self.io_gate)
        return ok

    def release(self) -> None:
        # Mirror RLock: releasing an unowned lock raises BEFORE any
        # bookkeeping changes.
        self._inner.release()
        d = getattr(self._depth, "n", 1) - 1
        self._depth.n = d
        if d == 0:
            _note_release(id(self))

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        d = getattr(self._depth, "n", 0)
        self._depth.n = 0
        _note_release(id(self))
        return (self._inner._release_save(), d)

    def _acquire_restore(self, state) -> None:
        inner_state, d = state
        self._inner._acquire_restore(inner_state)
        self._depth.n = d
        _note_acquire(id(self), self.name, self.io_gate)

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<SanRLock {self.name} {self._inner!r}>"


def lock(name: str, io_gate: bool = False):
    """A named mutex: plain threading.Lock when the sanitizer is off,
    instrumented SanLock when on. `io_gate` marks a lock that exists to
    serialize blocking I/O (see module docstring)."""
    if _enabled:
        return SanLock(name, io_gate)
    return threading.Lock()


def rlock(name: str, io_gate: bool = False):
    """Named reentrant mutex; see lock()."""
    if _enabled:
        return SanRLock(name, io_gate)
    return threading.RLock()


# -- blocking-call patches ---------------------------------------------

_ABSENT = object()
_patches: List[Tuple[object, str, object]] = []


def _patch(owner, attr: str, wrapper) -> None:
    prev = owner.__dict__.get(attr, _ABSENT) if isinstance(owner, type) \
        else getattr(owner, attr, _ABSENT)
    _patches.append((owner, attr, prev))
    setattr(owner, attr, wrapper)


def _install_patches() -> None:
    if _patches:
        return

    orig_fsync = os.fsync
    orig_fdatasync = getattr(os, "fdatasync", None)
    orig_event_wait = threading.Event.wait
    sock_base = socket.socket.__bases__[0]  # _socket.socket

    def fsync(fd):
        check_blocking("fsync")
        return orig_fsync(fd)

    _patch(os, "fsync", fsync)

    if orig_fdatasync is not None:
        def fdatasync(fd):
            check_blocking("fsync")
            return orig_fdatasync(fd)

        _patch(os, "fdatasync", fdatasync)

    def event_wait(self, timeout=None):
        if timeout is None:
            check_blocking("event-wait-no-timeout")
        return orig_event_wait(self, timeout)

    _patch(threading.Event, "wait", event_wait)

    def _sock_wrapper(method_name):
        orig = getattr(sock_base, method_name)

        def wrapper(self, *args, **kw):
            check_blocking("socket-" + method_name)
            return orig(self, *args, **kw)

        wrapper.__name__ = method_name
        return wrapper

    for m in ("connect", "recv", "sendall"):
        # accept() is wrapped at the Python level already and servers
        # legitimately block in it forever; connect/recv/sendall are
        # the calls that stall request paths.
        _patch(socket.socket, m, _sock_wrapper(m))


def _remove_patches() -> None:
    while _patches:
        owner, attr, prev = _patches.pop()
        if prev is _ABSENT:
            try:
                delattr(owner, attr)
            except AttributeError:
                pass
        else:
            setattr(owner, attr, prev)


# -- control + reporting -----------------------------------------------


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Turn the sanitizer on for locks created FROM NOW ON (existing
    plain locks stay plain — tests construct their stores/daemons after
    enabling, which is what the conftest fixture does)."""
    global _enabled
    _enabled = True
    _install_patches()


def disable() -> None:
    global _enabled
    _enabled = False
    _remove_patches()


def findings() -> List[dict]:
    with _meta:
        return list(_findings)


def reset() -> None:
    """Drop findings and the dedup memory; KEEP the edge graph (lock
    order is a process-lifetime property — two tests that each take
    half of a cycle should still be caught). Dead threads' EMPTY
    stacks are pruned (pure bookkeeping); a dead thread still holding
    a lock is preserved for leaked_locks()."""
    alive = {t.ident for t in threading.enumerate()}
    with _meta:
        del _findings[:]
        _blocking_seen.clear()
        for ident in [
            i for i, (_n, st) in _thread_stacks.items()
            if not st and i not in alive
        ]:
            del _thread_stacks[ident]


def purge_dead_threads() -> None:
    """Forget locks held by dead threads — for test harness use AFTER
    a deliberate leak has been asserted, so the state doesn't bleed
    into the next test's leak check."""
    alive = {t.ident for t in threading.enumerate()}
    with _meta:
        for ident in [i for i in _thread_stacks if i not in alive]:
            del _thread_stacks[ident]


def edges() -> List[dict]:
    with _meta:
        return [
            {"from": a, "to": b, "count": e["count"], "site": e["site"]}
            for (a, b), e in sorted(_edges.items())
        ]


def held_locks() -> List[Tuple[str, str]]:
    """(thread name, lock name) for every sanitized lock currently
    held anywhere in the process."""
    out = []
    with _meta:
        snap = list(_thread_stacks.items())
    for _ident, (tname, st) in snap:
        for h in list(st):
            out.append((tname, h.name))
    return out


def leaked_locks() -> List[Tuple[str, str]]:
    """(thread name, lock name) held by threads that are no longer
    alive — a thread died holding a lock; every later acquirer
    deadlocks."""
    alive = {t.ident for t in threading.enumerate()}
    out = []
    with _meta:
        snap = list(_thread_stacks.items())
    for ident, (tname, st) in snap:
        if ident in alive:
            continue
        for h in list(st):
            out.append((tname, h.name))
    return out


def report() -> dict:
    """Everything the static side can merge: the observed edge graph
    plus findings (tools/ktlint --lock-graph --runtime-graph FILE)."""
    return {"edges": edges(), "findings": findings()}


def _atexit_report() -> None:
    path = os.environ.get("KT_SANITIZE_REPORT", "")
    if not path or not _enabled:
        return
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(report(), f, indent=2, sort_keys=True)
    except OSError:
        pass


if _enabled:
    _install_patches()
atexit.register(_atexit_report)
