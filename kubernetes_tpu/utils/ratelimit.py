"""Token-bucket rate limiter + exponential backoff.

Reference: pkg/util/throttle.go (RateLimiter) used for binding QPS
(factory.go:43-46) and client QPS; per-key exponential backoff mirrors
the scheduler's podBackoff (factory.go:334-378).
"""

from __future__ import annotations

import threading
import time
from typing import Dict


class TokenBucket:
    def __init__(self, qps: float, burst: int):
        if qps <= 0:
            raise ValueError("qps must be positive")
        self.qps = qps
        self.burst = max(1, burst)
        self._tokens = float(self.burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = time.monotonic()
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
        self._last = now

    def try_accept(self) -> bool:
        with self._lock:
            self._refill_locked()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def accept(self) -> None:
        """Block until a token is available (reference: RateLimiter.Accept)."""
        while True:
            with self._lock:
                self._refill_locked()
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)


class Backoff:
    """Per-key exponential backoff (reference: podBackoff,
    factory.go:334-378 — 1s initial, 60s max, halved-life garbage
    collection handled by expire())."""

    def __init__(self, initial: float = 1.0, max_backoff: float = 60.0):
        self.initial = initial
        self.max = max_backoff
        self._lock = threading.Lock()
        self._entries: Dict[str, tuple] = {}  # key -> (duration, last_update)

    def duration(self, key: str) -> float:
        """Current duration for key, doubling it for next time."""
        with self._lock:
            dur, _ = self._entries.get(key, (self.initial, 0.0))
            self._entries[key] = (min(dur * 2, self.max), time.monotonic())
            return dur

    def reset(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def expire(self, older_than: float = 120.0) -> None:
        cutoff = time.monotonic() - older_than
        with self._lock:
            self._entries = {
                k: v for k, v in self._entries.items() if v[1] >= cutoff
            }
