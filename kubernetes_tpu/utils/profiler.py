"""Device-time profiling plane: micro-tick duty cycle, solve/commit
overlap, and on-demand device traces.

The compile/cost half of the profiling story lives in ops/ledger.py
(it needs jax; this module must stay importable by a control-plane
process that never touches the accelerator). Here lives the HOST-side
accounting the micro-tick daemon feeds every tick, plus the
``jax.profiler.trace`` wrapper behind ``GET /debug/device-profile``:

- **duty cycle** (``scheduler_device_duty_cycle``): the fraction of a
  micro-tick period the device spent busy — the in-flight window from
  solve dispatch to ``PendingSolve.result()`` over the wall between
  consecutive tick resolutions. An idle cluster reads ~0; a saturated
  pipelined daemon should approach 1.0. Read it against
  ``scheduler_overlap_efficiency`` — high duty + low overlap means the
  host is BLOCKING on the device instead of overlapping it.

- **overlap efficiency** (``scheduler_overlap_efficiency``): of the
  device-busy window, the fraction the host spent doing useful work
  (staging tick k+1, commit I/O) rather than blocked in the readback
  — 1 - blocked/device_busy. This is the realized value of PR 12's
  pipelined dispatch: a fixed-tick daemon measures ~0 here.

- ``scheduler_device_busy_seconds_total``: the raw busy-seconds
  counter behind the duty ratio, so dashboards can rate() it across
  scrape intervals.

- **device traces**: ``capture_device_trace(seconds)`` wraps
  ``jax.profiler.trace`` around a sleep on the calling (HTTP handler)
  thread while the daemon threads keep dispatching — the produced
  directory opens in XProf/TensorBoard or perfetto. One capture at a
  time per process (the profiler backend cannot nest).

Everything here is microseconds-per-tick host bookkeeping;
tests/test_profiler.py pins ledger + duty accounting at <5% of the
bulk-churn drill (the PR-9 always-on budget).
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Optional

from kubernetes_tpu.utils import metrics, sanitizer

#: Ratio ladders: duty/overlap are [0, 1] by construction, so the
#: default latency buckets would dump everything into one bucket.
RATIO_BUCKETS = (
    0.01, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95,
    0.99, 1.0,
)

DUTY_CYCLE = metrics.DEFAULT.histogram(
    "scheduler_device_duty_cycle",
    "Fraction of a micro-tick period the solve device spent busy "
    "(dispatch -> readback over the tick wall)",
    buckets=RATIO_BUCKETS,
)
OVERLAP = metrics.DEFAULT.histogram(
    "scheduler_overlap_efficiency",
    "Fraction of the device-busy window the host overlapped with "
    "useful work instead of blocking on the readback",
    buckets=RATIO_BUCKETS,
)
DEVICE_BUSY = metrics.DEFAULT.counter(
    "scheduler_device_busy_seconds_total",
    "Total seconds the solve device spent busy (in-flight solves)",
)


def observe_tick(
    device_s: float, wall_s: float, blocked_s: float
) -> None:
    """One resolved micro-tick's accounting: ``device_s`` is the
    dispatch->readback in-flight window, ``wall_s`` the period since
    the previous tick resolved, ``blocked_s`` the host time spent
    blocked inside ``result()``. Ratios clamp to [0, 1] — monotonic
    clock jitter must not poison a histogram bucket."""
    if device_s <= 0.0 or wall_s <= 0.0:
        return
    DEVICE_BUSY.inc(device_s)
    DUTY_CYCLE.observe(min(1.0, device_s / wall_s))
    OVERLAP.observe(
        min(1.0, max(0.0, 1.0 - blocked_s / device_s))
    )


# -- on-demand device traces -------------------------------------------


class ProfilerUnavailable(RuntimeError):
    """jax (or its profiler backend) is not importable/startable in
    this process."""


class TraceInProgress(RuntimeError):
    """A device trace capture is already running (the profiler backend
    cannot nest sessions)."""


_CAPTURE_LOCK = sanitizer.lock("profiler.capture")
_CAPTURE_ACTIVE = [False]

#: Capture length clamp — a typo'd ?seconds= must not pin an HTTP
#: handler (and the trace buffer) for minutes.
MAX_TRACE_SECONDS = 60.0


def capture_device_trace(
    seconds: float = 2.0, out_dir: Optional[str] = None
) -> dict:
    """Record ``seconds`` of device activity via ``jax.profiler.trace``
    into a server-side directory (fresh tempdir unless ``out_dir``).
    The caller's thread sleeps inside the session; every OTHER thread's
    dispatches land in the trace — exactly what an operator wants from
    a live daemon. Returns {dir, seconds, files}."""
    if seconds != seconds:  # NaN slips through min/max clamps
        seconds = 2.0
    seconds = min(max(float(seconds), 0.1), MAX_TRACE_SECONDS)
    try:
        import jax
    except Exception as e:  # pragma: no cover - jax is baked into CI
        raise ProfilerUnavailable(f"jax unavailable: {e!r}")
    with _CAPTURE_LOCK:
        if _CAPTURE_ACTIVE[0]:
            raise TraceInProgress(
                "a device trace capture is already in progress"
            )
        _CAPTURE_ACTIVE[0] = True
    try:
        trace_dir = out_dir or tempfile.mkdtemp(prefix="kt-device-trace-")
        try:
            with jax.profiler.trace(trace_dir):
                time.sleep(seconds)
        except Exception as e:
            raise ProfilerUnavailable(
                f"device trace capture failed: {e!r}"
            )
        files = []
        for root, _dirs, names in os.walk(trace_dir):
            for name in names:
                files.append(
                    os.path.relpath(os.path.join(root, name), trace_dir)
                )
        return {
            "dir": trace_dir,
            "seconds": seconds,
            "files": sorted(files),
        }
    finally:
        with _CAPTURE_LOCK:
            _CAPTURE_ACTIVE[0] = False
