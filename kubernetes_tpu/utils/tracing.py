"""End-to-end step traces for the solve pipeline.

Reference lineage: pkg/util/trace.go (util.NewTrace / trace.Step /
LogIfLong — step-timestamped operation traces dumped when they exceed
a threshold), composed with Dapper-style trace-ID propagation so one
pod's create -> enqueue -> lower -> upload -> solve -> readback -> bind
lifecycle is reconstructable across daemons.

Model:
- A Trace owns a tree of Spans (monotonic start/end, point-in-time
  steps, free-form fields) plus the set of pod names it touched.
- The active trace/span rides a contextvar; threads start clean, so a
  reflector callback can never leak into a scheduler tick's trace.
- trace() opens a root trace (sampled, recorded into the bounded
  DEFAULT_BUFFER on exit, logged when over its threshold); when a
  trace is already active it joins as a child span instead, so nested
  instrumented layers compose instead of fragmenting.
- Cross-process propagation: the HTTP client stamps the active trace
  id into the X-Trace-Id header; the apiserver opens a request trace
  under THAT id, and /debug/traces merges entries by trace id.
- phase() is span() plus an unconditional observation into the
  scheduler_phase_seconds histogram — the always-on in-situ phase
  breakdown bench.py publishes, independent of trace sampling.

Disabled tracing (configure(sample_rate=0)) costs one contextvar read
and one RNG draw per trace() call and nothing per span(); the hot
per-pod device code is never instrumented (phases wrap whole chunks).
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import random
import threading
import time
from typing import Dict, Iterable, List, Optional

from kubernetes_tpu.utils import metrics

_LOG = logging.getLogger("kubernetes_tpu.trace")

#: Propagation header (Dapper's trace-id role; one hop, no span ids —
#: entries re-parent by trace id at render time).
TRACE_HEADER = "X-Trace-Id"

#: In-situ per-phase latency of the batched solve pipeline. Always
#: observed (even with tracing sampled out) — this is the histogram
#: bench.py reads back after the headline run. Note: JAX dispatch is
#: async, so in pipelined mode "solve" measures dispatch and the
#: device time accrues to "readback" (the blocking copy-out).
PHASE_SECONDS = metrics.DEFAULT.histogram(
    "scheduler_phase_seconds",
    "Latency of one solve-pipeline phase (lower/upload/solve/readback/bind)",
    ("phase",),
)

_RNG = random.Random()

_CONFIG = {
    "sample_rate": 1.0,
    # Default LogIfLong threshold (seconds); 0 disables the dump.
    "log_threshold_s": 0.0,
    # Cap on pod names remembered per trace (a 50k-pod batch trace
    # must not pin 50k strings in the ring).
    "max_pods": 8192,
}


def configure(
    sample_rate: Optional[float] = None,
    log_threshold_s: Optional[float] = None,
    max_pods: Optional[int] = None,
) -> None:
    if sample_rate is not None:
        _CONFIG["sample_rate"] = float(sample_rate)
    if log_threshold_s is not None:
        _CONFIG["log_threshold_s"] = float(log_threshold_s)
    if max_pods is not None:
        _CONFIG["max_pods"] = int(max_pods)


def new_trace_id() -> str:
    return os.urandom(8).hex()


class Span:
    """One timed operation. Single-writer by design: a span is mutated
    only by the thread that opened it (matching util.NewTrace)."""

    __slots__ = ("name", "start", "end", "fields", "steps", "children")

    def __init__(self, name: str, fields: Optional[dict] = None,
                 start: Optional[float] = None):
        self.name = name
        self.start = time.monotonic() if start is None else start
        self.end: Optional[float] = None
        self.fields = dict(fields) if fields else {}
        self.steps: List = []  # (monotonic_at, label)
        self.children: List["Span"] = []

    def step(self, label: str) -> None:
        """Record a point-in-time step (trace.Step analog)."""
        self.steps.append((time.monotonic(), label))

    def note(self, **fields) -> None:
        self.fields.update(fields)

    def child(self, name: str, start: Optional[float] = None,
              end: Optional[float] = None, **fields) -> "Span":
        sp = Span(name, fields or None, start=start)
        sp.end = end
        self.children.append(sp)
        return sp

    def finish(self) -> "Span":
        if self.end is None:
            self.end = time.monotonic()
        return self

    @property
    def duration_s(self) -> float:
        return (self.end if self.end is not None else time.monotonic()) - self.start

    def to_dict(self, base: float) -> dict:
        d = {
            "name": self.name,
            "start_s": round(self.start - base, 6),
            "duration_s": round(self.duration_s, 6),
        }
        if self.fields:
            d["fields"] = dict(self.fields)
        if self.steps:
            d["steps"] = [
                {"at_s": round(at - base, 6), "label": label}
                for at, label in self.steps
            ]
        if self.children:
            d["children"] = [c.to_dict(base) for c in self.children]
        return d


class _NullSpan:
    """Shared no-op span: every mutator swallows its arguments."""

    __slots__ = ()

    def step(self, label):
        pass

    def note(self, **fields):
        pass

    def child(self, name, start=None, end=None, **fields):
        return self

    def finish(self):
        return self


NULL_SPAN = _NullSpan()


class Trace:
    """A root span plus identity: trace id, wall-clock start, pods."""

    __slots__ = ("trace_id", "root", "start_wall", "pods",
                 "pods_truncated", "threshold_s", "record_threshold_s")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 threshold_s: Optional[float] = None,
                 start: Optional[float] = None,
                 record_threshold_s: float = 0.0):
        self.trace_id = trace_id or new_trace_id()
        self.root = Span(name, start=start)
        self.start_wall = time.time()
        self.pods: set = set()
        self.pods_truncated = False
        self.threshold_s = threshold_s
        self.record_threshold_s = record_threshold_s

    def note_pods(self, names: Iterable[str]) -> None:
        limit = _CONFIG["max_pods"]
        for n in names:
            if len(self.pods) >= limit:
                self.pods_truncated = True
                return
            self.pods.add(n)

    def to_dict(self) -> dict:
        base = self.root.start
        d = {
            "traceId": self.trace_id,
            "start": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(self.start_wall)
            ),
            "duration_s": round(self.root.duration_s, 6),
            "spans": [self.root.to_dict(base)],
        }
        if self.pods:
            d["pods"] = sorted(self.pods)
        if self.pods_truncated:
            d["podsTruncated"] = True
        return d


# Active context: the trace (identity / pod set) and the innermost
# open span (nesting parent). Fresh threads see None for both.
_current_trace: "contextvars.ContextVar[Optional[Trace]]" = (
    contextvars.ContextVar("kt_trace", default=None)
)
_current_span: "contextvars.ContextVar[Optional[Span]]" = (
    contextvars.ContextVar("kt_span", default=None)
)


def current_trace_id() -> str:
    tr = _current_trace.get()
    return tr.trace_id if tr is not None else ""


def note_pods(names: Iterable[str]) -> None:
    """Associate pod names with the active trace (no-op without one)."""
    tr = _current_trace.get()
    if tr is not None:
        tr.note_pods(names)


class TraceBuffer:
    """Bounded ring of completed traces (newest win), merged by trace
    id at render time — entries recorded under one id by different
    components (scheduler tick + apiserver bind request) come back as
    one trace with multiple span trees."""

    def __init__(self, size: int = 512):
        self._size = size
        self._entries: List[Trace] = []
        self._lock = threading.Lock()

    def record(self, trace: Trace) -> None:
        with self._lock:
            self._entries.append(trace)
            if len(self._entries) > self._size:
                del self._entries[: len(self._entries) - self._size]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def to_dicts(self, pod: str = "", limit: int = 64) -> dict:
        """{"kind": "TraceList", "traces": [...]} — newest first,
        entries merged by trace id, optionally filtered to traces that
        touched `pod`."""
        with self._lock:
            entries = list(self._entries)
        merged: Dict[str, dict] = {}
        order: List[str] = []
        for tr in entries:
            d = tr.to_dict()
            cur = merged.get(tr.trace_id)
            if cur is None:
                merged[tr.trace_id] = d
                order.append(tr.trace_id)
            else:
                cur["spans"].extend(d["spans"])
                if d.get("pods"):
                    cur["pods"] = sorted(set(cur.get("pods", [])) | set(d["pods"]))
                cur["duration_s"] = max(cur["duration_s"], d["duration_s"])
        out = []
        for tid in reversed(order):
            if len(out) >= limit:
                break
            d = merged[tid]
            if pod and pod not in d.get("pods", []):
                continue
            out.append(d)
        return {"kind": "TraceList", "traces": out}


DEFAULT_BUFFER = TraceBuffer()


class _TraceCtx:
    """Context manager behind trace(): owns a root Trace, or joins the
    active trace as a child span."""

    __slots__ = ("_trace", "_span", "_tok_trace", "_tok_span")

    def __init__(self, trace: Optional[Trace], join_span: Optional[Span]):
        self._trace = trace
        self._span = trace.root if trace is not None else join_span
        self._tok_trace = None
        self._tok_span = None

    def __enter__(self) -> Span:
        if self._span is None:
            return NULL_SPAN
        if self._trace is not None:
            self._tok_trace = _current_trace.set(self._trace)
        self._tok_span = _current_span.set(self._span)
        return self._span

    def __exit__(self, *exc) -> bool:
        if self._span is None:
            return False
        self._span.finish()
        if self._tok_span is not None:
            _current_span.reset(self._tok_span)
        if self._tok_trace is not None:
            _current_trace.reset(self._tok_trace)
        tr = self._trace
        if tr is not None:
            # record_threshold_s gates chatty sources (per-pod kubelet
            # syncs) out of the shared ring when they did near-zero
            # work, so they cannot evict the scheduling traces.
            if tr.root.duration_s >= tr.record_threshold_s:
                DEFAULT_BUFFER.record(tr)
            threshold = tr.threshold_s
            if threshold is None:
                threshold = _CONFIG["log_threshold_s"]
            if threshold and tr.root.duration_s > threshold:
                _LOG.info(
                    "trace over threshold (%.3fs > %.3fs):\n%s",
                    tr.root.duration_s, threshold, format_trace(tr.to_dict()),
                )
        return False


_NULL_CTX = _TraceCtx(None, None)


def trace(name: str, trace_id: Optional[str] = None, pod: Optional[str] = None,
          pods: Optional[Iterable[str]] = None,
          threshold_s: Optional[float] = None,
          start: Optional[float] = None,
          record_threshold_s: float = 0.0) -> _TraceCtx:
    """Open a root trace (recorded + maybe logged on exit). Joins the
    already-active trace as a child span when one exists. An explicit
    trace_id (header propagation) bypasses sampling — the upstream
    sampler already decided. record_threshold_s suppresses buffer
    recording for traces that finish faster than it (high-frequency
    sources that would otherwise flood the ring)."""
    active = _current_trace.get()
    if active is not None:
        sp = Span(name, start=start)
        parent = _current_span.get()
        (parent or active.root).children.append(sp)
        if pod:
            active.note_pods((pod,))
        if pods:
            active.note_pods(pods)
        return _TraceCtx(None, sp)
    if not trace_id:
        rate = _CONFIG["sample_rate"]
        if rate <= 0.0 or (rate < 1.0 and _RNG.random() >= rate):
            return _NULL_CTX
    tr = Trace(name, trace_id=trace_id, threshold_s=threshold_s, start=start,
               record_threshold_s=record_threshold_s)
    if pod:
        tr.note_pods((pod,))
    if pods:
        tr.note_pods(pods)
    return _TraceCtx(tr, None)


class _SpanCtx:
    __slots__ = ("_span", "_tok", "_phase", "_t0")

    def __init__(self, span: Optional[Span], phase: Optional[str]):
        self._span = span
        self._phase = phase
        self._tok = None
        self._t0 = 0.0

    def __enter__(self):
        if self._phase is not None:
            self._t0 = time.monotonic()
        if self._span is None:
            return NULL_SPAN
        self._tok = _current_span.set(self._span)
        return self._span

    def __exit__(self, *exc) -> bool:
        if self._phase is not None:
            PHASE_SECONDS.observe(
                time.monotonic() - self._t0, phase=self._phase
            )
        if self._span is not None:
            self._span.finish()
            _current_span.reset(self._tok)
        return False


def span(name: str, **fields) -> _SpanCtx:
    """Child span of the active span; no-op without an active trace."""
    parent = _current_span.get()
    if parent is None:
        return _SpanCtx(None, None)
    return _SpanCtx(parent.child(name, **fields), None)


def phase(name: str, **fields) -> _SpanCtx:
    """span() + unconditional scheduler_phase_seconds observation."""
    parent = _current_span.get()
    sp = parent.child(name, **fields) if parent is not None else None
    return _SpanCtx(sp, name)


# -- rendering (shared by the LogIfLong dump and `ktctl trace`) --------


def _format_span(d: dict, indent: int, lines: List[str]) -> None:
    pad = "  " * indent
    fields = d.get("fields") or {}
    extra = "".join(f" {k}={v}" for k, v in sorted(fields.items()))
    lines.append(
        f"{pad}{d['name']:<24} +{d['start_s']:.3f}s "
        f"({d['duration_s'] * 1000:.1f}ms){extra}"
    )
    for st in d.get("steps", ()):
        lines.append(f"{pad}  * {st['label']} @ +{st['at_s']:.3f}s")
    for c in d.get("children", ()):
        _format_span(c, indent + 1, lines)


def format_trace(d: dict) -> str:
    """Render one merged trace dict as an indented span tree."""
    pods = d.get("pods", [])
    head = f"TRACE {d['traceId']} {d.get('start', '')} ({d['duration_s']:.3f}s)"
    if pods:
        shown = ", ".join(pods[:5])
        more = f" +{len(pods) - 5} more" if len(pods) > 5 else ""
        head += f" pods=[{shown}{more}]"
    lines = [head]
    for root in d.get("spans", ()):
        _format_span(root, 1, lines)
    return "\n".join(lines)


def render_json(pod: str = "", limit: int = 64) -> str:
    return json.dumps(DEFAULT_BUFFER.to_dicts(pod=pod, limit=limit))
