"""Rebalancing plane — the host half.

The dense defrag pass lives in ops/rebalance.py (jitted, KT006 twin,
ktshape contract); this module owns everything around it: movable-pod
worklist assembly (largest-first, the best-fit-decreasing order the
kernel's scan expects), gang-atomic move grouping and the move-budget
group clip, the always-on metric series, and the ``/debug/rebalance``
snapshot. Like utils/capacity.py it must stay importable by a pure
control-plane process — jax is only touched inside :func:`build_plan`
(the descheduler is the only caller).

Series (KT005 family ``REBALANCE_METRICS`` + standard suffixes):

- ``rebalance_moves_total{outcome}`` — counter over the move pipeline:
  ``planned`` (kernel emitted, survived gang/budget clipping),
  ``evicted`` (graceful eviction landed), ``rebound`` (replacement pod
  bound at a node), ``recovered`` (crash-orphaned journal replayed —
  the pod was re-created by the recovery pass), ``failed``
  (eviction/recreate error; move abandoned with the source pod intact
  or journal-recovered), and ``stranded`` (journal recovery exhausted
  — the SLO gate's numerator).
- ``rebalance_score_improvement`` — histogram of per-cycle
  ``score_before - score_after`` on the capacity plane's
  fragmentation score ([0, 1] ratio ladder).
- ``rebalance_moves_per_improvement`` — histogram of evictions spent
  per unit of measured score improvement — the defrag-efficiency SLO
  series (a cycle that moves much and improves little burns it).
- ``rebalance_stranded_pods_total`` — counter behind the
  stranded-pod-after-defrag SLO gate.

Gang atomicity: the kernel plans per-pod (gang membership is label
metadata the columns never carry); this module groups the plan's moves
by PodGroup and drops any gang whose movable members were only PARTLY
replanned — a slice defrags as a unit or not at all. Non-gang pods
are singleton groups. The budget clips at group granularity, best
summed-gain groups first.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils.profiler import RATIO_BUCKETS

MOVES = metrics.DEFAULT.counter(
    "rebalance_moves_total",
    "Descheduler move pipeline by outcome: planned/evicted/rebound/"
    "failed/stranded",
    ("outcome",),
)
IMPROVEMENT = metrics.DEFAULT.histogram(
    "rebalance_score_improvement",
    "Per-defrag-cycle drop in the cluster fragmentation score "
    "(score_before - score_after, clamped at 0)",
    buckets=RATIO_BUCKETS,
)
MOVES_PER_IMPROVEMENT = metrics.DEFAULT.histogram(
    "rebalance_moves_per_improvement",
    "Evictions spent per unit of measured fragmentation-score "
    "improvement in one defrag cycle (saturates at the ladder cap "
    "when a cycle moves pods without moving the score)",
)
STRANDED = metrics.DEFAULT.counter(
    "rebalance_stranded_pods_total",
    "Pods evicted by a defrag move that never re-bound (move journal "
    "recovery exhausted) — the stranded-pod-after-defrag SLO gate",
)

#: Movable worklist pads to pow2 buckets >= this (DIM_LATTICES "D").
POD_BUCKET_MIN = 8

#: Default per-cycle move budget (the descheduler may override).
DEFAULT_MOVE_BUDGET = 32

#: Saturation value observed into the efficiency histogram when a
#: cycle executes moves but the score does not improve (the ladder's
#: top finite bucket, so the SLO quantile reads a real breach).
EFFICIENCY_SATURATION = 120.0

#: Rebalance trend ring length (/debug/rebalance's improvement feed).
TREND_LEN = 120


def _pow2(n: int, minimum: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def movable_pods(pods, forced_nodes: Sequence[str] = ()) -> List:
    """The defrag worklist from a pods listing: bound, live phase, not
    Terminating, not itself a mid-move replacement (carrying the
    destination annotation). ``forced_nodes`` (cordon-drain sources)
    only widens eligibility conceptually — filtering is the same, the
    force flag is applied per-pod in :func:`build_plan`."""
    from kubernetes_tpu.models.objects import (
        REBALANCE_DEST_ANNOTATION,
        pod_is_terminating,
    )

    out = []
    for p in pods:
        if not p.spec.node_name:
            continue
        if p.status.phase in ("Succeeded", "Failed"):
            continue
        if pod_is_terminating(p):
            continue
        if (p.metadata.annotations or {}).get(REBALANCE_DEST_ANNOTATION):
            continue
        out.append(p)
    return out


def build_plan(
    cols: Dict[str, np.ndarray],
    node_names: Sequence[Optional[str]],
    pods,
    probes: Sequence[Tuple[str, float, float, int]],
    move_budget: int = DEFAULT_MOVE_BUDGET,
    forced_nodes: Sequence[str] = (),
) -> Optional[dict]:
    """One defrag plan: stage the movable worklist largest-first, run
    the ``plan_moves`` kernel against the occupancy columns, then
    apply the host-side gang-atomic grouping and the group-granular
    budget clip. Returns the plan dict, or None when there is nothing
    movable / the kernel path failed — it never raises (the
    descheduler calls it on a periodic loop)."""
    try:
        return _build_plan(
            cols, node_names, pods, probes, int(move_budget),
            frozenset(forced_nodes),
        )
    except Exception:
        return None


def _build_plan(cols, node_names, pods, probes, move_budget, forced):
    from kubernetes_tpu.models.columnar import (
        mem_to_mib_ceil,
        pod_resource_limits,
    )
    from kubernetes_tpu.models.objects import POD_GROUP_LABEL, pod_full_key
    from kubernetes_tpu.ops.rebalance import plan_moves

    movable = movable_pods(pods)
    if not movable or move_budget <= 0:
        return None
    index = {
        str(name): j for j, name in enumerate(node_names) if name is not None
    }

    rows = []
    for p in movable:
        cpu, mem = pod_resource_limits(p)
        mem = mem_to_mib_ceil(mem)
        rows.append((float(cpu), float(mem), p))
    # Best-fit-decreasing: largest pods place first while the carry is
    # emptiest; name-tiebreak keeps the plan deterministic.
    rows.sort(key=lambda r: (-r[0], -r[1], r[2].metadata.name))

    d = len(rows)
    dp = _pow2(max(d, 1), POD_BUCKET_MIN)
    pod_cpu = np.zeros(dp, np.float32)
    pod_mem = np.zeros(dp, np.float32)
    pod_node = np.full(dp, -1, np.int32)
    pod_live = np.zeros(dp, bool)
    pod_force = np.zeros(dp, bool)
    for i, (cpu, mem, p) in enumerate(rows):
        pod_cpu[i] = cpu
        pod_mem[i] = mem
        pod_node[i] = index.get(p.spec.node_name, -1)
        pod_live[i] = pod_node[i] >= 0
        pod_force[i] = p.spec.node_name in forced

    q = len(probes)
    qp = _pow2(max(q, 1), 4)
    probe_cpu = np.zeros(qp, np.float32)
    probe_mem = np.zeros(qp, np.float32)
    probe_min = np.ones(qp, np.int32)
    probe_live = np.zeros(qp, bool)
    for i, (_name, cpu, mem, minm) in enumerate(probes):
        probe_cpu[i] = cpu
        probe_mem[i] = mem
        probe_min[i] = max(int(minm), 1)
        probe_live[i] = True

    n = int(np.asarray(cols["cpu_cap"]).shape[0])
    npad = _pow2(max(n, 1), 128)

    def col(name, dtype):
        a = np.asarray(cols[name]).astype(dtype, copy=False)
        if a.shape[0] != npad:
            a = np.pad(a, (0, npad - a.shape[0]))
        return a

    dest, moved, gain, n_moves, score_before, score_after = (
        np.asarray(x)
        for x in plan_moves(
            col("cpu_cap", np.float32),
            col("mem_cap", np.float32),
            col("pods_cap", np.float32),
            col("cpu_fit", np.float32),
            col("mem_fit", np.float32),
            col("pods_used", np.float32),
            col("over", bool),
            col("sched", bool),
            pod_cpu,
            pod_mem,
            pod_node,
            pod_live,
            pod_force,
            probe_cpu,
            probe_mem,
            probe_min,
            probe_live,
            np.int32(move_budget),
        )
    )

    def gang_key(p):
        g = (p.metadata.labels or {}).get(POD_GROUP_LABEL, "")
        ns = p.metadata.namespace or "default"
        return f"{ns}/{g}" if g else ""

    moves = []
    gang_total: Dict[str, int] = {}
    gang_moved: Dict[str, int] = {}
    for i, (_cpu, _mem, p) in enumerate(rows):
        g = gang_key(p)
        if g:
            gang_total[g] = gang_total.get(g, 0) + 1
            if moved[i]:
                gang_moved[g] = gang_moved.get(g, 0) + 1
        if not moved[i]:
            continue
        j = int(dest[i])
        to = (
            node_names[j]
            if j < len(node_names) and node_names[j] is not None
            else None
        )
        if to is None:
            continue  # destination landed on a padding row: unusable
        moves.append(
            {
                "pod": pod_full_key(p),
                "name": p.metadata.name,
                "namespace": p.metadata.namespace or "default",
                "from": p.spec.node_name,
                "to": str(to),
                "gain": int(gain[i]),
                "forced": bool(pod_force[i]),
                "group": g or pod_full_key(p),
                "gang": bool(g),
            }
        )

    # Gang-atomic: a gang whose movable members were only partly
    # replanned defrags not at all this cycle (a half-moved slice is
    # worse fragmentation, not less).
    partial = {
        g for g, tot in gang_total.items()
        if 0 < gang_moved.get(g, 0) < tot
    }
    n_planned = len(moves)
    moves = [m for m in moves if m["group"] not in partial]

    # Budget clip at group granularity, best summed-gain groups first
    # (forced drain groups always keep their slot — a cordoned node
    # must empty). Deterministic: gain desc, then group key.
    groups: Dict[str, dict] = {}
    for m in moves:
        e = groups.setdefault(
            m["group"],
            {"group": m["group"], "moves": 0, "gain": 0,
             "forced": False, "gang": m["gang"]},
        )
        e["moves"] += 1
        e["gain"] += m["gain"]
        e["forced"] = e["forced"] or m["forced"]
    ranked = sorted(
        groups.values(),
        key=lambda e: (not e["forced"], -e["gain"], e["group"]),
    )
    kept_groups = set()
    used = 0
    for e in ranked:
        if used + e["moves"] > move_budget and not e["forced"]:
            continue
        kept_groups.add(e["group"])
        used += e["moves"]
    moves = [m for m in moves if m["group"] in kept_groups]

    before = float(score_before)
    after = float(score_after)
    return {
        "kind": "RebalancePlan",
        "score_before": round(before, 6),
        "score_after": round(after, 6),
        "improvement": round(max(before - after, 0.0), 6),
        "move_budget": int(move_budget),
        "movable_pods": d,
        "planned_moves": n_planned,
        "dropped_partial_gangs": sorted(partial),
        "moves": moves,
        "groups": [
            dict(e) for e in ranked if e["group"] in kept_groups
        ],
    }


def fragment_score(
    cols: Dict[str, np.ndarray],
    probes: Sequence[Tuple[str, float, float, int]],
) -> Optional[float]:
    """The current fragmentation score of the occupancy columns under
    the probe set — the ``plan_moves`` kernel run with an all-dead
    worklist and a zero budget (score_before IS the score; the tiny
    fixed D=8 bucket means one cached XLA shape). None on failure."""
    try:
        from kubernetes_tpu.ops.rebalance import plan_moves

        q = len(probes)
        qp = _pow2(max(q, 1), 4)
        probe_cpu = np.zeros(qp, np.float32)
        probe_mem = np.zeros(qp, np.float32)
        probe_min = np.ones(qp, np.int32)
        probe_live = np.zeros(qp, bool)
        for i, (_name, cpu, mem, minm) in enumerate(probes):
            probe_cpu[i] = cpu
            probe_mem[i] = mem
            probe_min[i] = max(int(minm), 1)
            probe_live[i] = True
        n = int(np.asarray(cols["cpu_cap"]).shape[0])
        npad = _pow2(max(n, 1), 128)

        def col(name, dtype):
            a = np.asarray(cols[name]).astype(dtype, copy=False)
            if a.shape[0] != npad:
                a = np.pad(a, (0, npad - a.shape[0]))
            return a

        out = plan_moves(
            col("cpu_cap", np.float32),
            col("mem_cap", np.float32),
            col("pods_cap", np.float32),
            col("cpu_fit", np.float32),
            col("mem_fit", np.float32),
            col("pods_used", np.float32),
            col("over", bool),
            col("sched", bool),
            np.zeros(POD_BUCKET_MIN, np.float32),
            np.zeros(POD_BUCKET_MIN, np.float32),
            np.full(POD_BUCKET_MIN, -1, np.int32),
            np.zeros(POD_BUCKET_MIN, bool),
            np.zeros(POD_BUCKET_MIN, bool),
            probe_cpu,
            probe_mem,
            probe_min,
            probe_live,
            np.int32(0),
        )
        return float(np.asarray(out[4]))
    except Exception:
        return None


class RebalanceMonitor:
    """Process-global rebalance bookkeeping: plan/cycle history, the
    move-outcome counters, and the snapshot served by
    ``GET /debug/rebalance``. Thread-safe; recording never raises."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._trend: deque = deque(maxlen=TREND_LEN)
        self.samples = 0
        self._last_plan: Optional[dict] = None
        self._last_cycle: Optional[dict] = None
        self._outcomes: Dict[str, int] = {}

    def reset(self) -> None:
        with self._lock:
            self._trend.clear()
            self.samples = 0
            self._last_plan = None
            self._last_cycle = None
            self._outcomes = {}

    def record_move(self, outcome: str, count: int = 1) -> None:
        """One move-pipeline transition (planned/evicted/rebound/
        failed/stranded) — feeds the counter family and the snapshot's
        outcome table; ``stranded`` also burns the SLO gate."""
        if count <= 0:
            return
        MOVES.inc(count, outcome=outcome)
        if outcome == "stranded":
            STRANDED.inc(count)
        with self._lock:
            self._outcomes[outcome] = self._outcomes.get(outcome, 0) + count

    def record_plan(self, plan: dict) -> None:
        with self._lock:
            self._last_plan = plan

    def record_cycle(
        self,
        score_before: float,
        score_after: float,
        moves_executed: int,
        trigger: str = "periodic",
    ) -> dict:
        """Fold one executed defrag cycle into the series: improvement
        histogram, the efficiency (moves-per-improvement) series, and
        the snapshot/trend. Returns the cycle summary dict."""
        improvement = max(float(score_before) - float(score_after), 0.0)
        IMPROVEMENT.observe(improvement)
        if moves_executed > 0:
            if improvement > 0:
                MOVES_PER_IMPROVEMENT.observe(
                    min(moves_executed / improvement, EFFICIENCY_SATURATION)
                )
            else:
                MOVES_PER_IMPROVEMENT.observe(EFFICIENCY_SATURATION)
        cycle = {
            "trigger": trigger,
            "score_before": round(float(score_before), 6),
            "score_after": round(float(score_after), 6),
            "improvement": round(improvement, 6),
            "moves_executed": int(moves_executed),
        }
        with self._lock:
            self.samples += 1
            self._trend.append(round(improvement, 6))
            self._last_cycle = cycle
        return cycle

    def snapshot(self) -> dict:
        """The ``/debug/rebalance`` body. ``sampled: false`` until the
        first defrag cycle — the ktctl miss contract keys on it."""
        with self._lock:
            if self.samples == 0:
                return {
                    "kind": "RebalanceReport",
                    "sampled": False,
                    "samples": 0,
                    "moves": [],
                    "outcomes": {},
                    "trend": [],
                }
            return {
                "kind": "RebalanceReport",
                "sampled": True,
                "samples": self.samples,
                "last_plan": dict(self._last_plan or {}),
                "last_cycle": dict(self._last_cycle or {}),
                "moves": list((self._last_plan or {}).get("moves", [])),
                "outcomes": dict(self._outcomes),
                "trend": list(self._trend),
            }


DEFAULT = RebalanceMonitor()
