"""Leader election over the API store + HA hot-standby wrapper.

Reference: contrib/pod-master/podmaster.go — an etcd lock (atomic
create with TTL; the holder renews, standbys take over when the lease
expires) keeping exactly one scheduler/controller-manager active.
Here the lock is an annotated Endpoints object in kube-system, CAS'd
through the apiserver's resourceVersion semantics — the same recipe
later Kubernetes standardized as the Endpoints resource lock.

Clock caveat (same as the reference): holders and standbys must share
a clock within lease_duration tolerances.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from kubernetes_tpu.server.api import APIError

LOCK_NAMESPACE = "kube-system"
HOLDER_KEY = "leaderelection.kubernetes-tpu.io/holder"
RENEW_KEY = "leaderelection.kubernetes-tpu.io/renew-time"


class LeaderElector:
    def __init__(
        self,
        client,
        name: str,
        identity: str,
        lease_duration: float = 5.0,
        renew_period: float = 1.0,
        retry_period: float = 1.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self.client = client
        self.name = name
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_period = renew_period
        self.retry_period = retry_period
        self.on_started = on_started_leading or (lambda: None)
        self.on_stopped = on_stopped_leading or (lambda: None)
        self.is_leader = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lock record --------------------------------------------------

    def _try_acquire_or_renew(self) -> bool:
        now = time.time()
        try:
            obj = self.client.get(
                "endpoints", self.name, namespace=LOCK_NAMESPACE
            )
        except APIError as e:
            if e.code != 404:
                raise
            # No lock yet: atomic create (loser gets 409).
            try:
                self.client.create(
                    "endpoints",
                    {
                        "kind": "Endpoints",
                        "metadata": {
                            "name": self.name,
                            "namespace": LOCK_NAMESPACE,
                            "annotations": {
                                HOLDER_KEY: self.identity,
                                RENEW_KEY: str(now),
                            },
                        },
                    },
                    namespace=LOCK_NAMESPACE,
                )
                return True
            except APIError as ce:
                if ce.code == 409:
                    return False
                raise
        annotations = obj.metadata.annotations or {}
        holder = annotations.get(HOLDER_KEY, "")
        try:
            renewed = float(annotations.get(RENEW_KEY, "0") or "0")
        except ValueError:
            renewed = 0.0
        if holder != self.identity and now - renewed < self.lease_duration:
            return False  # someone else holds a live lease
        # Ours to take/renew: CAS via resourceVersion (update conflicts
        # mean another standby won the race).
        obj.metadata.annotations = dict(annotations)
        obj.metadata.annotations[HOLDER_KEY] = self.identity
        obj.metadata.annotations[RENEW_KEY] = str(now)
        try:
            self.client.update("endpoints", obj, namespace=LOCK_NAMESPACE)
            return True
        except APIError as e:
            if e.code == 409:
                return False
            raise

    # -- loop ---------------------------------------------------------

    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self.is_leader:
            self.is_leader = False
            self.on_stopped()

    def _run(self) -> None:
        last_renew = 0.0
        while not self._stop.is_set():
            now = time.time()
            try:
                acquired = self._try_acquire_or_renew()
                if acquired:
                    last_renew = now
            except Exception:
                # Transient API failure: hold leadership ONLY within the
                # lease window. A leader partitioned from the apiserver
                # must abdicate once its lease could have expired —
                # otherwise a standby takes over and two leaders run
                # (split brain).
                acquired = (
                    self.is_leader
                    and (now - last_renew) < self.lease_duration
                )
            if self._stop.is_set():
                # stop() may have completed while the API call above
                # was stalled; acting on a late `acquired` here would
                # resurrect a daemon nothing will ever stop.
                return
            if acquired:
                self.is_leader = True
                # Called on EVERY renewal, not just the transition:
                # consumers (HAHotStandby) use it to retry failed or
                # still-pending startups; they must be idempotent.
                try:
                    self.on_started()
                except Exception:
                    pass
            elif self.is_leader:
                # Lost the lease (CAS'd past, or renewals failed too long).
                self.is_leader = False
                try:
                    self.on_stopped()
                except Exception:
                    pass
            self._stop.wait(
                self.renew_period if self.is_leader else self.retry_period
            )


class HAHotStandby:
    """Runs a daemon only while holding leadership (podmaster.go's
    whole job: the standby process is alive but idle until the lease
    falls to it).

    `factory` builds and STARTS the daemon, returning an object with
    stop(); called on every leadership acquisition (daemons here are
    not restartable in place)."""

    def __init__(
        self,
        client,
        lock_name: str,
        identity: str,
        factory: Callable[[], object],
        **elector_kwargs,
    ):
        self.factory = factory
        self.daemon: Optional[object] = None
        self._lock = threading.Lock()
        self._want = False
        self._starting = False
        self.elector = LeaderElector(
            client,
            lock_name,
            identity,
            on_started_leading=self._up,
            on_stopped_leading=self._down,
            **elector_kwargs,
        )

    def _up(self) -> None:
        """Idempotent; called on every lease renewal. The build runs on
        its OWN thread: a slow daemon startup (informer sync) on the
        elector thread would block renewals past the lease and hand
        leadership to a standby mid-startup. Failed builds retry on the
        next renewal."""
        with self._lock:
            self._want = True
            if self.daemon is not None or self._starting:
                return
            self._starting = True
        threading.Thread(target=self._build, daemon=True).start()

    def _build(self) -> None:
        try:
            daemon = self.factory()
        except Exception:
            with self._lock:
                self._starting = False  # retried on the next renewal
            return
        stale = None
        with self._lock:
            self._starting = False
            if self._want:
                self.daemon = daemon
            else:
                stale = daemon  # leadership lost while starting
        if stale is not None:
            stale.stop()

    def _down(self) -> None:
        with self._lock:
            self._want = False
            daemon, self.daemon = self.daemon, None
        if daemon is not None:
            daemon.stop()

    def start(self) -> "HAHotStandby":
        self.elector.start()
        return self

    def stop(self) -> None:
        self.elector.stop()
        self._down()

    @property
    def active(self) -> bool:
        return self.daemon is not None
