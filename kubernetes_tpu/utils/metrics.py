"""Prometheus-compatible metrics.

Reference: prometheus client usage across daemons — scheduler
(plugin/pkg/scheduler/metrics/metrics.go), apiserver
(pkg/apiserver/metrics.go), kubelet (pkg/kubelet/metrics/metrics.go).
Counters, gauges, and summaries with label sets, rendered in the
Prometheus text exposition format at /metrics.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Sequence, Tuple


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return tuple(labels.get(k, "") for k in self.label_names)

    @staticmethod
    def _fmt_labels(names, values) -> str:
        if not names:
            return ""
        inner = ",".join(f'{k}="{v}"' for k, v in zip(names, values))
        return "{" + inner + "}"


class Counter(_Metric):
    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            for k, v in sorted(self._values.items()):
                out.append(f"{self.name}{self._fmt_labels(self.label_names, k)} {v}")
        return out


class Gauge(_Metric):
    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            for k, v in sorted(self._values.items()):
                out.append(f"{self.name}{self._fmt_labels(self.label_names, k)} {v}")
        return out


class Summary(_Metric):
    """Windowless summary: running count/sum + streaming quantile estimate
    over a bounded reservoir (good enough for SLO checks; the reference
    uses client_golang summaries with decay)."""

    RESERVOIR = 1024

    def __init__(self, name, help_, label_names=(), quantiles=(0.5, 0.9, 0.99)):
        super().__init__(name, help_, label_names)
        self.quantiles = quantiles
        self._stats: Dict[Tuple[str, ...], Dict] = {}

    def observe(self, value: float, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            s = self._stats.setdefault(k, {"count": 0, "sum": 0.0, "res": []})
            s["count"] += 1
            s["sum"] += value
            res = s["res"]
            if len(res) < self.RESERVOIR:
                res.append(value)
            else:
                # Reservoir sampling keeps the estimate unbiased.
                import random

                i = random.randrange(s["count"])
                if i < self.RESERVOIR:
                    res[i] = value

    def quantile(self, q: float, **labels) -> float:
        with self._lock:
            s = self._stats.get(self._key(labels))
            if not s or not s["res"]:
                return math.nan
            xs = sorted(s["res"])
            idx = min(len(xs) - 1, max(0, int(math.ceil(q * len(xs))) - 1))
            return xs[idx]

    def render(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} summary"]
        with self._lock:
            for k, s in sorted(self._stats.items()):
                xs = sorted(s["res"])
                for q in self.quantiles:
                    if xs:
                        idx = min(len(xs) - 1, max(0, int(math.ceil(q * len(xs))) - 1))
                        val = xs[idx]
                    else:
                        val = math.nan
                    names = self.label_names + ("quantile",)
                    values = k + (str(q),)
                    out.append(f"{self.name}{self._fmt_labels(names, values)} {val}")
                out.append(
                    f"{self.name}_sum{self._fmt_labels(self.label_names, k)} {s['sum']}"
                )
                out.append(
                    f"{self.name}_count{self._fmt_labels(self.label_names, k)} {s['count']}"
                )
        return out


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            return self._metrics.setdefault(metric.name, metric)

    def counter(self, name, help_="", labels=()) -> Counter:
        return self.register(Counter(name, help_, labels))  # type: ignore

    def gauge(self, name, help_="", labels=()) -> Gauge:
        return self.register(Gauge(name, help_, labels))  # type: ignore

    def summary(self, name, help_="", labels=()) -> Summary:
        return self.register(Summary(name, help_, labels))  # type: ignore

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


DEFAULT = Registry()
