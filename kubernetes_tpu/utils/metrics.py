"""Prometheus-compatible metrics.

Reference: prometheus client usage across daemons — scheduler
(plugin/pkg/scheduler/metrics/metrics.go), apiserver
(pkg/apiserver/metrics.go), kubelet (pkg/kubelet/metrics/metrics.go).
Counters, gauges, and summaries with label sets, rendered in the
Prometheus text exposition format at /metrics.
"""

from __future__ import annotations

import math
import random
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.utils import sanitizer

#: Module-level RNG so reservoir sampling is seedable in tests
#: (metrics._RNG.seed(...)) and the hot observe() path never re-imports.
_RNG = random.Random()


def _escape_label_value(v: str) -> str:
    """Per the Prometheus text exposition format, label values escape
    backslash, double-quote, and newline — a pod name carrying '"'
    must not corrupt the /metrics output."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = sanitizer.lock("metrics.series")

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return tuple(labels.get(k, "") for k in self.label_names)

    def _header(self, type_: str) -> List[str]:
        help_ = self.help.replace("\\", "\\\\").replace("\n", "\\n")
        return [f"# HELP {self.name} {help_}", f"# TYPE {self.name} {type_}"]

    def reset(self) -> None:
        """Drop every series (fresh measurement window — SLO gates and
        benches open their own windows on the process-global registry)."""
        with self._lock:
            getattr(self, "_stats", getattr(self, "_values", {})).clear()

    def label_values(self) -> List[Tuple[str, ...]]:
        """Label-value tuples of the live series, ordered like
        label_names."""
        with self._lock:
            return list(
                getattr(self, "_stats", getattr(self, "_values", {}))
            )

    @staticmethod
    def _fmt_labels(names, values) -> str:
        if not names:
            return ""
        inner = ",".join(
            f'{k}="{_escape_label_value(v)}"' for k, v in zip(names, values)
        )
        return "{" + inner + "}"


class Counter(_Metric):
    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def snapshot(self) -> Dict[Tuple[str, ...], float]:
        """Point-in-time copy of every series (the retention sampler's
        read — utils/timeseries.py; one lock hold for the family)."""
        with self._lock:
            return dict(self._values)

    def render(self) -> List[str]:
        out = self._header("counter")
        with self._lock:
            for k, v in sorted(self._values.items()):
                out.append(f"{self.name}{self._fmt_labels(self.label_names, k)} {v}")
        return out


class Gauge(_Metric):
    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def snapshot(self) -> Dict[Tuple[str, ...], float]:
        """Point-in-time copy of every series (utils/timeseries.py)."""
        with self._lock:
            return dict(self._values)

    def render(self) -> List[str]:
        out = self._header("gauge")
        with self._lock:
            for k, v in sorted(self._values.items()):
                out.append(f"{self.name}{self._fmt_labels(self.label_names, k)} {v}")
        return out


class Summary(_Metric):
    """Windowless summary: running count/sum + streaming quantile estimate
    over a bounded reservoir (good enough for SLO checks; the reference
    uses client_golang summaries with decay)."""

    RESERVOIR = 1024

    def __init__(self, name, help_, label_names=(), quantiles=(0.5, 0.9, 0.99)):
        super().__init__(name, help_, label_names)
        self.quantiles = quantiles
        self._stats: Dict[Tuple[str, ...], Dict] = {}

    def observe(self, value: float, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            s = self._stats.setdefault(k, {"count": 0, "sum": 0.0, "res": []})
            s["count"] += 1
            s["sum"] += value
            res = s["res"]
            if len(res) < self.RESERVOIR:
                res.append(value)
            else:
                # Reservoir sampling keeps the estimate unbiased.
                i = _RNG.randrange(s["count"])
                if i < self.RESERVOIR:
                    res[i] = value

    def quantile(self, q: float, **labels) -> float:
        with self._lock:
            s = self._stats.get(self._key(labels))
            if not s or not s["res"]:
                return math.nan
            xs = sorted(s["res"])
            idx = min(len(xs) - 1, max(0, int(math.ceil(q * len(xs))) - 1))
            return xs[idx]

    def render(self) -> List[str]:
        out = self._header("summary")
        with self._lock:
            for k, s in sorted(self._stats.items()):
                xs = sorted(s["res"])
                for q in self.quantiles:
                    if xs:
                        idx = min(len(xs) - 1, max(0, int(math.ceil(q * len(xs))) - 1))
                        val = xs[idx]
                    else:
                        val = math.nan
                    names = self.label_names + ("quantile",)
                    values = k + (str(q),)
                    out.append(f"{self.name}{self._fmt_labels(names, values)} {val}")
                out.append(
                    f"{self.name}_sum{self._fmt_labels(self.label_names, k)} {s['sum']}"
                )
                out.append(
                    f"{self.name}_count{self._fmt_labels(self.label_names, k)} {s['count']}"
                )
        return out


#: client_golang's DefBuckets (5ms..10s), extended both ways for the
#: latency SLOs: 0.075 fills the sub-100ms band the micro-tick
#: pod-to-bind objective reads (0.01/0.025/0.05/0.075/0.1 give p99
#: resolution under the 0.1s target), and the 30/60/120 tail keeps a
#: saturated series honest — before it, any latency beyond 10s
#: rendered as a CLAMPED p99 of exactly 10.0 (BENCH_r06's
#: solve_phase_latency), indistinguishable from a measurement.
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.075, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
    10.0, 30.0, 60.0, 120.0,
)


def _fmt_float(v: float) -> str:
    """Bucket-bound formatting like client_golang: '0.005', '1', '10'."""
    return f"{v:g}"


def bucket_quantile(bounds, counts, total, q: float) -> float:
    """histogram_quantile over raw (non-cumulative) per-bucket counts:
    linear within the bucket holding rank q*total; observations beyond
    the highest finite bound report that bound. Shared by the live
    Histogram and the retention plane's windowed bucket DELTAS
    (utils/timeseries.quantile_over_time) so a windowed p99 and a
    lifetime p99 can never disagree about what interpolation means."""
    if total <= 0:
        return math.nan
    rank = q * total
    cum = 0.0
    lo = 0.0
    for ub, c in zip(bounds, counts):
        if c and cum + c >= rank:
            return lo + (ub - lo) * max(0.0, min(1.0, (rank - cum) / c))
        cum += c
        lo = ub
    return bounds[-1]


class Histogram(_Metric):
    """Cumulative-bucket histogram (the Prometheus exposition model's
    native latency type): per label set, one count per `le` bucket plus
    running sum/count. Unlike Summary, bucket counts aggregate across
    scrapes and instances, which is why the SLO-feeding latency series
    use this type. Internal state lives in `_stats` keyed like
    Summary's, so histogram and summary series are interchangeable to
    readers such as high_latency_requests / reset_request_latency."""

    def __init__(self, name, help_, label_names=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._stats: Dict[Tuple[str, ...], Dict] = {}

    def observe(self, value: float, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            s = self._stats.get(k)
            if s is None:
                s = self._stats[k] = {
                    "count": 0,
                    "sum": 0.0,
                    "buckets": [0] * len(self.buckets),
                }
            s["count"] += 1
            s["sum"] += value
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    s["buckets"][i] += 1
                    break
            # value > highest bound: only the implicit +Inf bucket
            # (== count) observes it.

    def count(self, **labels) -> int:
        with self._lock:
            s = self._stats.get(self._key(labels))
            return s["count"] if s else 0

    def quantile(self, q: float, **labels) -> float:
        """Bucket-interpolated quantile (histogram_quantile semantics):
        linear within the bucket holding rank q*count; observations
        beyond the highest finite bound report that bound."""
        with self._lock:
            s = self._stats.get(self._key(labels))
            if not s or s["count"] == 0:
                return math.nan
            counts = list(s["buckets"])
            total = s["count"]
        return bucket_quantile(self.buckets, counts, total, q)

    def snapshot(self) -> Dict[Tuple[str, ...], Tuple[int, float, Tuple[int, ...]]]:
        """Point-in-time (count, sum, raw per-bucket counts) per series
        — what the retention sampler rings so windowed quantiles can be
        interpolated from bucket deltas (utils/timeseries.py)."""
        with self._lock:
            return {
                k: (s["count"], s["sum"], tuple(s["buckets"]))
                for k, s in self._stats.items()
            }

    def render(self) -> List[str]:
        out = self._header("histogram")
        bnames = self.label_names + ("le",)
        with self._lock:
            for k, s in sorted(self._stats.items()):
                cum = 0
                for ub, c in zip(self.buckets, s["buckets"]):
                    cum += c
                    out.append(
                        f"{self.name}_bucket"
                        f"{self._fmt_labels(bnames, k + (_fmt_float(ub),))}"
                        f" {cum}"
                    )
                # The +Inf bucket is total count by construction.
                out.append(
                    f"{self.name}_bucket"
                    f"{self._fmt_labels(bnames, k + ('+Inf',))} {s['count']}"
                )
                out.append(
                    f"{self.name}_sum{self._fmt_labels(self.label_names, k)}"
                    f" {s['sum']}"
                )
                out.append(
                    f"{self.name}_count{self._fmt_labels(self.label_names, k)}"
                    f" {s['count']}"
                )
        return out


class Registry:
    def __init__(self):
        self._lock = sanitizer.lock("metrics.registry")
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            return self._metrics.setdefault(metric.name, metric)

    def get(self, name: str) -> Optional[_Metric]:
        """The registered metric by name, or None (the SLO engine's
        series lookup — utils/slo.py)."""
        with self._lock:
            return self._metrics.get(name)

    def all(self) -> List[_Metric]:
        """Every registered metric (the retention sampler's sweep —
        utils/timeseries.py)."""
        with self._lock:
            return list(self._metrics.values())

    def counter(self, name, help_="", labels=()) -> Counter:
        return self.register(Counter(name, help_, labels))  # type: ignore

    def gauge(self, name, help_="", labels=()) -> Gauge:
        return self.register(Gauge(name, help_, labels))  # type: ignore

    def summary(self, name, help_="", labels=()) -> Summary:
        return self.register(Summary(name, help_, labels))  # type: ignore

    def histogram(
        self, name, help_="", labels=(), buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self.register(
            Histogram(name, help_, labels, buckets)
        )  # type: ignore

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


DEFAULT = Registry()
