"""Capacity & fragmentation observability plane — the host half.

The dense pass lives in ops/capacity.py (jitted, KT006 twin, ktshape
contract); this module owns everything around it: the probe-shape set
(configured slice shapes + the backlog's observed shape quantiles),
the always-on metric series, the fragmentation trend ring, and the
``/debug/capacity`` snapshot. It must stay importable by a pure
control-plane process — jax is only imported inside :meth:`sample`
(the scheduler daemons are the only callers), exactly like
utils/profiler.py splits from ops/ledger.py.

Series (KT005 family ``CAPACITY_METRICS`` + standard suffixes):

- ``cluster_fragmentation_score`` — histogram of the kernel's
  capacity-weighted stranded fraction per sample ([0, 1] ratio bucket
  ladder, like the duty-cycle series) so the SLO engine can quantile
  it.
- ``node_utilization_ratio{resource}`` — histogram over LIVE nodes'
  charged/capacity ratios (cpu/mem/pods). Refreshed at most once per
  ``UTIL_REFRESH_S`` — it is O(nodes) python observes, and per-node
  distribution drift is a dashboard signal, not a per-tick one.
- ``cluster_headroom_pods{shape}`` — gauge: pods of each probe shape
  that still fit.
- ``slice_alloc_success_rate`` — histogram of the per-sample fraction
  of live probes whose gang bound clears minMember.
- ``scheduler_backlog_pressure`` — gauge: pending depth x oldest
  unbound pod age (seconds), from the FIFO depth and the SLI
  lifecycle collector's age watermark.
- ``capacity_zero_headroom_ticks_total`` — counter of samples where
  the backlog was non-empty while some live probe had ZERO headroom
  (capacity starvation: pods waiting that no reshuffling can place) —
  the SLO engine's zero-headroom burn objective reads it.

Sampling cadence: the scheduler daemons call :func:`sample_session` /
:func:`sample_cluster` once per resolved micro-tick inside their
``capacity`` phase span, plus an idle-tick refresh throttled to
``daemon.CAPACITY_IDLE_REFRESH_S`` (PR 9 staleness rule: telemetry
keeps moving on an idle cluster). See docs/architecture.md "Capacity
& fragmentation".
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils.profiler import RATIO_BUCKETS

FRAG_SCORE = metrics.DEFAULT.histogram(
    "cluster_fragmentation_score",
    "Capacity-weighted stranded fraction of aggregate free capacity "
    "across the probe-shape set (0 = perfectly packable, 1 = every "
    "free byte stranded)",
    buckets=RATIO_BUCKETS,
)
NODE_UTIL = metrics.DEFAULT.histogram(
    "node_utilization_ratio",
    "Per-live-node charged/capacity ratio, one observation per node "
    "per refresh",
    labels=("resource",),
    buckets=RATIO_BUCKETS,
)
HEADROOM = metrics.DEFAULT.gauge(
    "cluster_headroom_pods",
    "Pods of each probe shape that still fit cluster-wide (greedy "
    "per-node integral fit, mask-reduced over live nodes)",
    labels=("shape",),
)
SLICE_ALLOC = metrics.DEFAULT.histogram(
    "slice_alloc_success_rate",
    "Per-sample fraction of live probe shapes whose all-or-nothing "
    "gang bound (headroom >= minMember) is satisfiable right now",
    buckets=RATIO_BUCKETS,
)
BACKLOG_PRESSURE = metrics.DEFAULT.gauge(
    "scheduler_backlog_pressure",
    "Pending-backlog pressure watermark: FIFO depth x oldest unbound "
    "pod age in seconds (0 on an idle cluster)",
)
ZERO_HEADROOM = metrics.DEFAULT.counter(
    "capacity_zero_headroom_ticks_total",
    "Capacity samples taken while the backlog was non-empty and some "
    "live probe shape had zero cluster-wide headroom",
)

#: Default slice probes (cpu milli, mem MiB, minMember). Deliberately
#: spans a single small pod, a mid gang, and an 8-member accelerator
#: slice shape; operators tune via configure().
DEFAULT_SLICE_SHAPES: Tuple[Tuple[str, float, float, int], ...] = (
    ("slice-1x250m", 250.0, 256.0, 1),
    ("slice-4x500m", 500.0, 512.0, 4),
    ("slice-8x2000m", 2000.0, 2048.0, 8),
)

#: Seconds between O(nodes) utilization-histogram refreshes.
UTIL_REFRESH_S = 1.0

#: Fragmentation trend ring length (/debug/capacity's sparkline feed).
TREND_LEN = 120

#: Stranded-node table size in the snapshot.
TOP_K_STRANDED = 8

#: Backlog shapes remembered for the quantile probes.
SHAPE_WINDOW = 512


def _pow2(n: int, minimum: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


class CapacityMonitor:
    """Process-global capacity sampler: owns probe assembly, the dense
    kernel call, metric feeding, and the snapshot served by
    ``GET /debug/capacity``. Thread-safe; sampling never raises (the
    daemons call it on the hot tick path)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._slice_shapes = DEFAULT_SLICE_SHAPES
        self._recent_shapes: deque = deque(maxlen=SHAPE_WINDOW)
        self._trend: deque = deque(maxlen=TREND_LEN)
        self.samples = 0
        self._last_util_mono = 0.0
        self._last = None  # latest snapshot body (dict) or None

    # -- configuration ---------------------------------------------------

    def configure(
        self, slice_shapes: Sequence[Tuple[str, float, float, int]]
    ) -> None:
        """Replace the configured slice probes: (name, cpu milli,
        mem MiB, minMember) tuples."""
        with self._lock:
            self._slice_shapes = tuple(
                (str(n), float(c), float(m), int(k))
                for n, c, m, k in slice_shapes
            )

    def reset(self) -> None:
        with self._lock:
            self._slice_shapes = DEFAULT_SLICE_SHAPES
            self._recent_shapes.clear()
            self._trend.clear()
            self.samples = 0
            self._last_util_mono = 0.0
            self._last = None

    def warm(self, n_nodes: int = 0) -> None:
        """Pre-compile the kernel for the shape buckets a live daemon
        will hit: the node count's pow2 lattice row, crossed with the
        probe-count bucket both before and after the three
        backlog-quantile probes join. The cold XLA compile is ~1.5s;
        daemons kick this onto a background thread at start so it
        never lands in-band on a solve tick (and never GIL-starves
        the commit worker's decision-sink announce)."""
        try:
            from kubernetes_tpu.ops.capacity import capacity_report

            npad = _pow2(max(int(n_nodes), 1), 128)
            with self._lock:
                q = len(self._slice_shapes)
            for qp in sorted({_pow2(max(q, 1), 4), _pow2(q + 3, 4)}):
                out = capacity_report(
                    *(np.zeros(npad, np.float32) for _ in range(6)),
                    np.zeros(npad, bool),
                    np.zeros(npad, bool),
                    np.zeros(qp, np.float32),
                    np.zeros(qp, np.float32),
                    np.ones(qp, np.int32),
                    np.zeros(qp, bool),
                )
                np.asarray(out[-1])  # block until compiled
        except Exception:
            pass

    # -- probe assembly ---------------------------------------------------

    def note_backlog_shapes(
        self, shapes: Sequence[Tuple[float, float]]
    ) -> None:
        """Record observed pending-pod shapes (cpu milli, mem MiB) —
        the backlog-quantile probes are drawn from this window."""
        with self._lock:
            self._recent_shapes.extend(
                (float(c), float(m)) for c, m in shapes
            )

    def probe_set(self) -> List[Tuple[str, float, float, int]]:
        """Configured slice shapes + backlog shape quantiles (p50/p90/
        max over the recent-shape window, requests ceil'd so the
        columns stay integral)."""
        with self._lock:
            probes = list(self._slice_shapes)
            shapes = list(self._recent_shapes)
        if shapes:
            arr = np.asarray(shapes, dtype=np.float64)
            for tag, q in (("p50", 50.0), ("p90", 90.0), ("max", 100.0)):
                cpu = float(np.ceil(np.percentile(arr[:, 0], q)))
                mem = float(np.ceil(np.percentile(arr[:, 1], q)))
                probes.append((f"backlog-{tag}", cpu, mem, 1))
        return probes

    # -- sampling ----------------------------------------------------------

    def sample(
        self,
        cols: Dict[str, np.ndarray],
        node_names: Sequence[Optional[str]],
        backlog_depth: int = 0,
        oldest_age_s: float = 0.0,
    ) -> Optional[dict]:
        """One capacity sample over NODE_SCHEMA-style occupancy columns
        (cpu_cap/mem_cap/pods_cap/cpu_fit/mem_fit/pods_used f32[N],
        over/sched b8[N]; padding rows carry sched=False). Returns the
        snapshot body, or None if the kernel path failed — it NEVER
        raises (telemetry must not take down a tick)."""
        try:
            return self._sample(
                cols, node_names, int(backlog_depth), float(oldest_age_s)
            )
        except Exception:
            return None

    def _sample(self, cols, node_names, backlog_depth, oldest_age_s):
        from kubernetes_tpu.ops.capacity import capacity_report

        probes = self.probe_set()
        q = len(probes)
        qp = _pow2(max(q, 1), 4)
        probe_cpu = np.zeros(qp, np.float32)
        probe_mem = np.zeros(qp, np.float32)
        probe_min = np.ones(qp, np.int32)
        probe_live = np.zeros(qp, bool)
        for i, (_name, cpu, mem, minm) in enumerate(probes):
            probe_cpu[i] = cpu
            probe_mem[i] = mem
            probe_min[i] = max(int(minm), 1)
            probe_live[i] = True

        n = int(np.asarray(cols["cpu_cap"]).shape[0])
        npad = _pow2(max(n, 1), 128)

        def col(name, dtype):
            a = np.asarray(cols[name]).astype(dtype, copy=False)
            if a.shape[0] != npad:
                a = np.pad(a, (0, npad - a.shape[0]))
            return a

        args = (
            col("cpu_cap", np.float32),
            col("mem_cap", np.float32),
            col("pods_cap", np.float32),
            col("cpu_fit", np.float32),
            col("mem_fit", np.float32),
            col("pods_used", np.float32),
            col("over", bool),
            col("sched", bool),
            probe_cpu,
            probe_mem,
            probe_min,
            probe_live,
        )
        (
            util_cpu,
            util_mem,
            util_pods,
            fit_int,
            headroom,
            frag,
            slice_ok,
            stranded,
            frag_score,
            stranded_cpu,
            stranded_mem,
        ) = (np.asarray(x) for x in capacity_report(*args))

        now = time.monotonic()
        live = args[7][:npad] & ~args[6][:npad]
        live_idx = np.flatnonzero(live)
        score = float(frag_score)
        pressure = float(backlog_depth) * max(float(oldest_age_s), 0.0)

        # Probe table + headroom gauges.
        table = []
        n_ok = 0
        zero_headroom = False
        for i, (name, cpu, mem, minm) in enumerate(probes):
            h = int(headroom[i])
            ok = bool(slice_ok[i])
            n_ok += 1 if ok else 0
            zero_headroom = zero_headroom or h == 0
            HEADROOM.set(float(h), shape=name)
            table.append(
                {
                    "shape": name,
                    "cpu_milli": float(cpu),
                    "mem_mib": float(mem),
                    "min_member": int(minm),
                    "headroom_pods": h,
                    "fragmentation": round(float(frag[i]), 6),
                    "allocatable": ok,
                }
            )
        alloc_rate = (n_ok / q) if q else 0.0

        # Stranded top-k by leftover cpu.
        free_cpu = np.maximum(args[0] - args[3], 0.0) * live
        free_mem = np.maximum(args[1] - args[4], 0.0) * live
        stranded_idx = np.flatnonzero(stranded)
        order = stranded_idx[np.argsort(-free_cpu[stranded_idx])]
        top = []
        for j in order[:TOP_K_STRANDED]:
            name = (
                node_names[j]
                if j < len(node_names) and node_names[j] is not None
                else f"node[{j}]"
            )
            top.append(
                {
                    "node": str(name),
                    "free_cpu_milli": float(free_cpu[j]),
                    "free_mem_mib": float(free_mem[j]),
                }
            )

        # Series: always-on scalars every sample; the O(nodes)
        # utilization histogram at most once per UTIL_REFRESH_S.
        FRAG_SCORE.observe(score)
        SLICE_ALLOC.observe(alloc_rate)
        BACKLOG_PRESSURE.set(pressure)
        if backlog_depth > 0 and zero_headroom:
            ZERO_HEADROOM.inc()
        with self._lock:
            refresh_util = (
                now - self._last_util_mono >= UTIL_REFRESH_S
            )
            if refresh_util:
                self._last_util_mono = now
        if refresh_util:
            for resource, ratios in (
                ("cpu", util_cpu),
                ("mem", util_mem),
                ("pods", util_pods),
            ):
                for v in ratios[live_idx]:
                    NODE_UTIL.observe(float(v), resource=resource)

        def util_summary(ratios):
            vals = ratios[live_idx]
            if not len(vals):
                return {"mean": 0.0, "p50": 0.0, "p99": 0.0}
            return {
                "mean": round(float(vals.mean()), 6),
                "p50": round(float(np.percentile(vals, 50)), 6),
                "p99": round(float(np.percentile(vals, 99)), 6),
            }

        node_util = {}
        for j in live_idx:
            name = (
                node_names[j]
                if j < len(node_names) and node_names[j] is not None
                else None
            )
            if name is not None:
                node_util[str(name)] = [
                    round(float(util_cpu[j]), 4),
                    round(float(util_mem[j]), 4),
                    round(float(util_pods[j]), 4),
                ]

        body = {
            "kind": "CapacityReport",
            "sampled": True,
            "fragmentation_score": round(score, 6),
            "slice_alloc_success_rate": round(alloc_rate, 6),
            "stranded_cpu_fraction": round(float(stranded_cpu), 6),
            "stranded_mem_fraction": round(float(stranded_mem), 6),
            "stranded_nodes": top,
            "stranded_node_count": int(len(stranded_idx)),
            "live_nodes": int(len(live_idx)),
            "probes": table,
            "utilization": {
                "cpu": util_summary(util_cpu),
                "mem": util_summary(util_mem),
                "pods": util_summary(util_pods),
            },
            "node_utilization": node_util,
            "backlog": {
                "depth": int(backlog_depth),
                "oldest_age_s": round(max(float(oldest_age_s), 0.0), 3),
                "pressure": round(pressure, 3),
            },
        }
        with self._lock:
            self.samples += 1
            self._trend.append(round(score, 6))
            body["samples"] = self.samples
            body["trend"] = list(self._trend)
            self._last = body
        return body

    # -- surfaces ----------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/debug/capacity`` body. ``sampled: false`` on a cold
        cluster — the ktctl miss contract keys on it."""
        with self._lock:
            if self._last is None:
                return {
                    "kind": "CapacityReport",
                    "sampled": False,
                    "samples": 0,
                    "probes": [],
                    "stranded_nodes": [],
                    "trend": [],
                }
            return dict(self._last)


def session_columns(session) -> Tuple[Dict[str, np.ndarray], List]:
    """Occupancy columns straight off a SolverSession's host mirror —
    the already-staged matrices (``session.h`` is the device carry's
    numpy twin, kept in sync by the same scatter updates)."""
    h = session.h
    cols = {
        "cpu_cap": h["cpu_cap"],
        "mem_cap": h["mem_cap"],
        "pods_cap": h["pods_cap"],
        "cpu_fit": h["cpu_fit"],
        "mem_fit": h["mem_fit"],
        "pods_used": h["pods_used"],
        "over": h["over"],
        "sched": h["sched"],
    }
    return cols, list(session.node_names)


def cluster_columns(nodes, assigned) -> Tuple[Dict[str, np.ndarray], List]:
    """Occupancy columns from watch-cache object lists (the plain
    BatchScheduler path, which keeps no session). Terminal-phase
    (Succeeded/Failed) and Terminating pods are EXCLUDED — their
    capacity is free or about to be (filterNonRunningPods semantics,
    same rule the snapshot/session staging applies)."""
    from kubernetes_tpu import native
    from kubernetes_tpu.models.columnar import (
        MIB,
        RESOURCE_CPU,
        RESOURCE_MEMORY,
        RESOURCE_PODS,
        mem_to_mib_ceil,
        node_is_ready,
        pod_resource_limits,
    )
    from kubernetes_tpu.models.objects import pod_is_terminating

    names = [n.metadata.name for n in nodes]
    index = {name: j for j, name in enumerate(names)}
    n = len(nodes)
    cpu_cap = np.zeros(n, np.float32)
    mem_cap = np.zeros(n, np.float32)
    pods_cap = np.zeros(n, np.float32)
    sched = np.zeros(n, bool)
    for j, node in enumerate(nodes):
        cap = node.status.capacity or {}
        if RESOURCE_CPU in cap:
            cpu_cap[j] = cap[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in cap:
            mem_cap[j] = cap[RESOURCE_MEMORY].value() // MIB
        if RESOURCE_PODS in cap:
            pods_cap[j] = cap[RESOURCE_PODS].value()
        sched[j] = node_is_ready(node)

    occupants = [
        p
        for p in assigned
        if p.spec.node_name
        and p.status.phase not in ("Succeeded", "Failed")
        and not pod_is_terminating(p)
    ]
    a = len(occupants)
    a_idx = np.full(a, -1, np.int32)
    a_cpu = np.zeros(a, np.float32)
    a_mem = np.zeros(a, np.float32)
    for i, p in enumerate(occupants):
        j = index.get(p.spec.node_name)
        a_idx[i] = -1 if j is None else j
        cpu, mem = pod_resource_limits(p)
        a_cpu[i] = cpu
        a_mem[i] = mem_to_mib_ceil(mem)
    cpu_fit = np.zeros(n, np.float32)
    mem_fit = np.zeros(n, np.float32)
    over = np.zeros(n, bool)
    cpu_used = np.zeros(n, np.float32)
    mem_used = np.zeros(n, np.float32)
    pods_used = np.zeros(n, np.float32)
    native.greedy_fit(
        a_idx, a_cpu, a_mem, cpu_cap, mem_cap,
        cpu_fit, mem_fit, over, cpu_used, mem_used, pods_used,
    )
    cols = {
        "cpu_cap": cpu_cap,
        "mem_cap": mem_cap,
        "pods_cap": pods_cap,
        "cpu_fit": cpu_fit,
        "mem_fit": mem_fit,
        "pods_used": pods_used,
        "over": over,
        "sched": sched,
    }
    return cols, names


DEFAULT = CapacityMonitor()
