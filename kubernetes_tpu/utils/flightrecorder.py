"""Scheduling flight recorder: a bounded per-decision ring.

The batched solvers already materialize the dense pod x node
feasibility mask and score matrix on device — this module is where the
readback of those arrays lands as auditable records. Each batch-daemon
tick appends one ``Decision`` per drained pod (outcome, chosen node,
and — for a bounded subset — per-node predicate verdicts plus the
winner's score decomposition) and one ``SolveRecord`` (mode, duration,
wave/Sinkhorn convergence telemetry), both carrying the tick's trace
id so ``/debug/decisions`` and ``/debug/solves`` join against
``/debug/traces``.

Bounds: the decision ring holds at most ``_CONFIG["ring"]`` entries
(default 4096, newest win) and per-node verdicts are captured for at
most ``explain_limit`` pods per tick with ``explain_top_k`` feasible
candidates each — a 50k-pod drain records 50k outcomes but never 50k
verdict tables. Everything here is host-side bookkeeping off the jit
hot path; the device readback itself lives in ops (solver.explain_rows
/ pipeline.explain_backlog).

Reference lineage: the per-predicate failure reasons kubernetes
surfaced through FailedScheduling events (generic_scheduler.go
FitError.Error), upgraded from a flattened string to queryable
records.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Tuple

from kubernetes_tpu.utils import metrics, sanitizer

#: Decision outcome EVENTS recorded, by outcome: one per drained pod
#: per tick (bound / unschedulable / bind_error / bind_conflict /
#: gang_rejected) PLUS one per preemption verdict (preempt_*) — a pod
#: the solve left unbound and the preemption pass then nominated
#: counts once under each, mirroring preemption_solve_outcomes_total.
#: The sum over outcomes therefore exceeds the ring's entry count; it
#: is an event counter, not a ring gauge.
DECISIONS_TOTAL = metrics.DEFAULT.counter(
    "scheduler_decisions_total",
    "Decision outcome events recorded by the flight recorder (solve "
    "outcomes plus preemption verdicts), by outcome",
    ("outcome",),
)

#: Final Sinkhorn column-mass residual (log domain) of the most recent
#: sinkhorn solve: 0 = every node's demand fit its capacity when the
#: price loop stopped. ktlint KT005: unit-less by nature (allowlisted).
SINKHORN_RESIDUAL = metrics.DEFAULT.gauge(
    "scheduler_sinkhorn_residual",
    "Final Sinkhorn column-mass residual (log domain) of the latest solve",
)

#: Device solve iterations per solve, by mode: waves for the wave
#: family, total Sinkhorn price iterations for sinkhorn. Buckets are
#: powers of two — iteration counts, not seconds.
SOLVE_ITERATIONS = metrics.DEFAULT.histogram(
    "scheduler_solve_iterations",
    "Device solve iterations per solve (waves / Sinkhorn price updates)",
    ("mode",),
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096),
)


_LAST_SOLVE_LOCK = sanitizer.lock("flightrecorder.lastsolve")
_LAST_SOLVE: Optional[dict] = None


def observe_solve_telemetry(
    mode: str,
    iterations: int,
    residual: Optional[float] = None,
    waves: Optional[int] = None,
) -> None:
    """One solve's convergence telemetry: iteration histogram (always)
    plus the residual gauge (sinkhorn family). Shared by the batch
    wrappers, the pipelined path, and the incremental session so the
    series never depend on which path ran. The figures are also parked
    for take_last_solve_telemetry() so the daemon that just ran the
    solve can stamp them onto its SolveRecord (the wave/sinkhorn batch
    wrappers return placements only)."""
    global _LAST_SOLVE
    SOLVE_ITERATIONS.observe(float(iterations), mode=mode)
    if residual is not None:
        SINKHORN_RESIDUAL.set(float(residual))
    with _LAST_SOLVE_LOCK:
        _LAST_SOLVE = {
            "mode": mode,
            "iterations": int(iterations),
            "waves": int(waves if waves is not None else iterations),
            "residual": None if residual is None else float(residual),
        }


def take_last_solve_telemetry() -> Optional[dict]:
    """Pop the most recent solve's telemetry (None when nothing is
    parked). Consume-once: each solve's figures stamp at most one
    SolveRecord, so a later tick can never inherit stale numbers."""
    global _LAST_SOLVE
    with _LAST_SOLVE_LOCK:
        tele, _LAST_SOLVE = _LAST_SOLVE, None
        return tele


#: Decision sinks: callables invoked (pod_key, outcome) for every
#: Decision the recorder logs — the SLI collector (utils/sli.py) joins
#: its "decision" lifecycle milestone here. Called OUTSIDE the ring
#: lock; sinks must be fast and never raise (raises are swallowed).
_DECISION_SINKS: List = []


def add_decision_sink(fn) -> None:
    """Sinks MUST be idempotent per pod key: a decision is announced
    once early (notify_decision_sinks, pre-explain) and again when the
    finished records land (record())."""
    _DECISION_SINKS.append(fn)


def notify_decision_sinks(pods_outcomes) -> None:
    """Early decision announcement: the daemons call this the moment a
    tick's outcomes are known, BEFORE the bounded explain readback —
    whose first-bucket XLA compile can outlast a fast pod's entire
    lifecycle, which would lose the SLI decision milestone (the track
    drains on Running)."""
    for pod, outcome in pods_outcomes:
        for sink in _DECISION_SINKS:
            try:
                sink(pod, outcome)
            except Exception:
                pass  # a broken sink must not sink the tick


_CONFIG = {
    # Decision ring bound (newest win). 4096 decisions with bounded
    # verdicts is a few MB — sized so a burst drain can't evict the
    # whole recent history before an operator looks.
    "ring": 4096,
    # Solve-record ring bound (one entry per tick, much smaller rows).
    "solve_ring": 512,
    # Per-pod verdict caps: feasible candidates kept with full score
    # decomposition / infeasible nodes listed individually (the rest
    # fold into reasonCounts).
    "explain_top_k": 3,
    "explain_failed_nodes": 16,
    # Pods per tick that get per-node verdicts (0 disables verdict
    # capture; outcome records always land).
    "explain_limit": 64,
}


def configure(
    ring: Optional[int] = None,
    solve_ring: Optional[int] = None,
    explain_top_k: Optional[int] = None,
    explain_failed_nodes: Optional[int] = None,
    explain_limit: Optional[int] = None,
) -> None:
    if ring is not None:
        _CONFIG["ring"] = int(ring)
    if solve_ring is not None:
        _CONFIG["solve_ring"] = int(solve_ring)
    if explain_top_k is not None:
        _CONFIG["explain_top_k"] = int(explain_top_k)
    if explain_failed_nodes is not None:
        _CONFIG["explain_failed_nodes"] = int(explain_failed_nodes)
    if explain_limit is not None:
        _CONFIG["explain_limit"] = int(explain_limit)


def explain_top_k() -> int:
    return _CONFIG["explain_top_k"]


def explain_failed_nodes() -> int:
    return _CONFIG["explain_failed_nodes"]


def explain_limit() -> int:
    return _CONFIG["explain_limit"]


def _wall_stamp() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(time.time()))


@dataclass
class Decision:
    """One pod's scheduling decision in one tick."""

    pod: str  # "namespace/name"
    tick: int
    trace_id: str
    mode: str
    outcome: str
    node: str = ""  # chosen node ("" when unschedulable)
    group: str = ""  # PodGroup key when gang-scheduled
    # Explain verdicts (populated for at most explain_limit pods/tick):
    # top-k feasible candidates with score decomposition + individually
    # listed infeasible nodes; the remainder aggregate in reason_counts.
    verdicts: List[dict] = field(default_factory=list)
    reason_counts: Dict[str, int] = field(default_factory=dict)
    feasible_nodes: int = -1  # -1 = verdicts not captured
    total_nodes: int = 0
    # Preemption verdict (amended by the preemption pass).
    nominated_node: str = ""
    victims: Tuple[str, ...] = ()
    reason: str = ""
    time: str = field(default_factory=_wall_stamp)

    def attach(self, entry: dict) -> None:
        """Fold one ops.pipeline.explain_backlog entry into this
        decision (the per-node verdict table)."""
        self.feasible_nodes = int(entry.get("feasibleNodes", 0))
        self.total_nodes = int(entry.get("totalNodes", 0))
        self.verdicts = list(entry.get("nodes", ()))
        self.reason_counts = dict(entry.get("reasonCounts", {}))

    def to_dict(self) -> dict:
        d = {
            "pod": self.pod,
            "tick": self.tick,
            "traceId": self.trace_id,
            "mode": self.mode,
            "outcome": self.outcome,
            "time": self.time,
        }
        if self.node:
            d["node"] = self.node
        if self.group:
            d["group"] = self.group
        if self.feasible_nodes >= 0:
            d["feasibleNodes"] = self.feasible_nodes
            d["totalNodes"] = self.total_nodes
            d["nodes"] = self.verdicts
            d["reasonCounts"] = self.reason_counts
        if self.nominated_node:
            d["nominatedNode"] = self.nominated_node
            d["victims"] = list(self.victims)
        if self.reason:
            d["reason"] = self.reason
        return d


@dataclass
class SolveRecord:
    """One batch tick's solve, with convergence telemetry."""

    tick: int
    trace_id: str
    mode: str
    pods: int
    duration_s: float
    waves: int = 0
    sinkhorn_iterations: int = 0
    sinkhorn_residual: Optional[float] = None
    incremental: bool = False
    time: str = field(default_factory=_wall_stamp)

    def to_dict(self) -> dict:
        d = {
            "tick": self.tick,
            "traceId": self.trace_id,
            "mode": self.mode,
            "pods": self.pods,
            "duration_s": round(self.duration_s, 6),
            "time": self.time,
        }
        if self.incremental:
            d["incremental"] = True
        if self.waves:
            d["waves"] = self.waves
        if self.sinkhorn_iterations:
            d["sinkhornIterations"] = self.sinkhorn_iterations
        if self.sinkhorn_residual is not None:
            d["sinkhornResidual"] = round(self.sinkhorn_residual, 6)
        return d


class FlightRecorder:
    """Bounded rings of decisions and solve records (newest win)."""

    def __init__(self):
        self._lock = sanitizer.lock("flightrecorder.ring")
        self._decisions: List[Decision] = []
        self._solves: List[SolveRecord] = []
        self._tick = 0

    def next_tick(self) -> int:
        with self._lock:
            self._tick += 1
            return self._tick

    def record(self, decisions: Iterable[Decision]) -> None:
        decisions = list(decisions)
        with self._lock:
            self._decisions.extend(decisions)
            cap = _CONFIG["ring"]
            if len(self._decisions) > cap:
                del self._decisions[: len(self._decisions) - cap]
        for d in decisions:
            DECISIONS_TOTAL.inc(outcome=d.outcome)
            for sink in _DECISION_SINKS:
                try:
                    sink(d.pod, d.outcome)
                except Exception:
                    pass  # a broken sink must not sink the tick

    def record_solve(self, rec: SolveRecord) -> None:
        with self._lock:
            self._solves.append(rec)
            cap = _CONFIG["solve_ring"]
            if len(self._solves) > cap:
                del self._solves[: len(self._solves) - cap]

    def record_preemption(
        self,
        pod: str,
        outcome: str,
        node: str = "",
        victims: Tuple[str, ...] = (),
        reason: str = "",
    ) -> None:
        """Fold a preemption verdict into the pod's most recent
        decision (the preemption pass runs right after the tick's
        decisions land), or append a standalone record when none
        exists (e.g. the decision already rotated out of the ring)."""
        with self._lock:
            amended = False
            for d in reversed(self._decisions):
                if d.pod == pod:
                    d.outcome = outcome
                    d.nominated_node = node
                    d.victims = tuple(victims)
                    d.reason = reason
                    amended = True
                    break
            if not amended:
                self._decisions.append(
                    Decision(
                        pod=pod, tick=self._tick, trace_id="", mode="",
                        outcome=outcome, nominated_node=node,
                        victims=tuple(victims), reason=reason,
                    )
                )
                cap = _CONFIG["ring"]
                if len(self._decisions) > cap:
                    del self._decisions[: len(self._decisions) - cap]
        DECISIONS_TOTAL.inc(outcome=outcome)

    def ring_stats(self) -> Tuple[int, int]:
        """(recorded decisions, configured capacity) — the healthz
        flight-recorder subcheck."""
        with self._lock:
            return len(self._decisions), _CONFIG["ring"]

    def clear(self) -> None:
        with self._lock:
            self._decisions.clear()
            self._solves.clear()

    @staticmethod
    def _pod_matches(key: str, pod: str) -> bool:
        """Match a decision's 'ns/name' key against a query that may be
        the full key or a bare pod name."""
        return key == pod or ("/" not in pod and key.endswith("/" + pod))

    def decisions(self, pod: str = "", limit: int = 64) -> dict:
        with self._lock:
            entries = list(self._decisions)
        limit = max(0, limit)  # limit=0 means none, not one
        out = []
        for d in reversed(entries):  # newest first
            if len(out) >= limit:
                break
            if pod and not self._pod_matches(d.pod, pod):
                continue
            out.append(d.to_dict())
        return {"kind": "DecisionList", "decisions": out}

    def solves(self, limit: int = 64) -> dict:
        with self._lock:
            entries = list(self._solves)
        return {
            "kind": "SolveList",
            "solves": [r.to_dict() for r in reversed(entries)][
                : max(0, limit)
            ],
        }


DEFAULT = FlightRecorder()


def render_decisions_json(pod: str = "", limit: int = 64) -> str:
    import json

    return json.dumps(DEFAULT.decisions(pod=pod, limit=limit))


def render_solves_json(limit: int = 64) -> str:
    import json

    return json.dumps(DEFAULT.solves(limit=limit))


# -- rendering (shared by `ktctl explain` and the check.sh smoke) ------


def format_decision(d: dict) -> str:
    """Render one decision dict as the per-node 'why/why not' table."""
    head = (
        f"DECISION {d.get('pod', '')}  tick {d.get('tick', 0)}"
        f"  mode {d.get('mode', '') or '-'}  outcome {d.get('outcome', '')}"
    )
    if d.get("node"):
        head += f" -> {d['node']}"
    if d.get("traceId"):
        head += f"  trace {d['traceId']}"
    lines = [head]
    if d.get("group"):
        lines.append(f"  pod group: {d['group']}")
    if d.get("nominatedNode"):
        victims = ", ".join(d.get("victims", ())) or "<none>"
        lines.append(f"  nominated {d['nominatedNode']} evicting [{victims}]")
    if d.get("reason"):
        lines.append(f"  reason: {d['reason']}")
    nodes = d.get("nodes", ())
    if "feasibleNodes" in d:
        lines.append(
            f"  {d['feasibleNodes']}/{d.get('totalNodes', 0)} nodes feasible"
        )
    if nodes:
        width = max(len(v.get("node", "")) for v in nodes) + 2
        for v in nodes:
            if v.get("ok"):
                comps = v.get("components", {})
                detail = f"score {v.get('score', 0)}"
                if comps:
                    detail += (
                        " ("
                        + ", ".join(f"{k} {val}" for k, val in comps.items())
                        + ")"
                    )
                lines.append(
                    f"  {v.get('node', ''):<{width}}feasible    {detail}"
                )
            else:
                lines.append(
                    f"  {v.get('node', ''):<{width}}infeasible  "
                    + ", ".join(v.get("reasons", ()))
                )
    counts = d.get("reasonCounts")
    if counts:
        lines.append(
            "  why not: "
            + ", ".join(f"{k} x{v}" for k, v in sorted(counts.items()))
        )
    return "\n".join(lines)
