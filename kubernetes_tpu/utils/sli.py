"""Cluster SLI telemetry plane: pod-lifecycle watermarks + fan-out lag
+ device telemetry.

The reference gates cluster health on *measured service levels*: pod
startup latency observed through watch events (test/e2e/density.go
computes create -> Running watermarks from a watch, never by polling)
and the HighLatencyRequests apiserver gate (test/e2e/util.go:1286,
mirrored in server/httpserver.py). This module is the production-side
equivalent — always-on collectors that turn the event streams the
system already emits into scrapeable SLI series, so the SLO engine
(utils/slo.py), bench.py, and ``ktctl slo`` all read one truth.

Three collector families live here:

- **Lifecycle SLIs** (``LifecycleSLICollector``): one subscriber on the
  kvstore's event dispatcher (the same feed the PR-6 watch cache rides
  — ``KVStore.subscribe``; zero polling, zero extra copies) turns pod
  events into milestone watermarks exported as the
  ``pod_startup_latency_seconds{milestone}`` histogram:

    created   ADDED event for an unbound pod (the track's t0)
    decision  the scheduling flight recorder logged a Decision for the
              pod (PR-5 join: flightrecorder.record() notifies sinks)
    bound     first MODIFIED carrying spec.nodeName — "binding visible
              to a watch client", density.go's definition
    running   first MODIFIED carrying status.phase == Running (the
              kubelet's status write becoming watch-visible)

  Tracks are bounded (``MAX_TRACKED``, oldest evicted) and drain on
  the running milestone or DELETED, so a long-lived daemon never
  accumulates state for pods that will not progress.

- **Watch fan-out lag**: ``observe_watch_lag`` records how many store
  versions a watch connection's delivered burst trails the watch
  cache's applied watermark by (``watch_fanout_lag_versions``); the
  slow-consumer drop counter and per-resource queue-depth gauge live
  next to the drop site in store/watch.py. ``observe_informer_staleness``
  is the consumer-side mirror: seconds since each scheduler informer
  last processed a delta.

- **Device/solver telemetry**: host<->device transfer bytes
  (``note_transfer``, fed by ops/pipeline.py and ops/incremental.py
  from the staged buffer sizes), the XLA compile-cache sentinel the
  PR-7 recompilation test watches (``_solve_xla._cache_size()``)
  promoted to a gauge + compile counter, and live device-memory
  gauges — all sampled per solve tick by the batch daemons
  (``observe_device_telemetry``), next to the existing
  ``scheduler_phase_seconds`` histograms.

Everything here is host-side bookkeeping measured in microseconds per
event; tests/test_sli.py pins the collector + per-tick telemetry at
<5% of the bulk-churn drill's per-pod budget so it can stay always-on.

Scope note (same as the flight recorder's): the collector is
per-process and its tracks live where the STORE lives. In the
in-process cluster topology (tests, LocalCluster, local-up) the
scheduler daemons share that process, so the flight-recorder decision
sink finds the tracks and the ``decision`` milestone lands. A batch
daemon deployed in its OWN process against a remote apiserver records
decisions locally — bound/running milestones still land apiserver-side
via store events, but ``pod_decision_latency`` reads no_data there
(joining it across processes needs decision events on the API, a
follow-up).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from kubernetes_tpu.utils import flightrecorder, metrics, sanitizer

_LOG = logging.getLogger("kubernetes_tpu.sli")

#: Store key prefix of the pod resource (registry.ResourceInfo.prefix
#: shape) — the collector filters the firehose on it first thing.
POD_PREFIX = "/registry/pods/"
_PREFIX_LEN = len(POD_PREFIX)

#: Pod lifecycle milestone watermarks, measured from the watch-visible
#: ADDED event (density.go's pod-startup measurement, as an always-on
#: histogram instead of a bench-private loop).
STARTUP_LATENCY = metrics.DEFAULT.histogram(
    "pod_startup_latency_seconds",
    "Pod lifecycle milestone latency from watch-visible creation "
    "(milestone: decision | bound | running)",
    ("milestone",),
)

#: Store versions a watch connection's delivered burst trails the
#: watch cache's applied watermark by (0 = the consumer is current).
#: Buckets are powers of two — version counts, not seconds.
WATCH_LAG = metrics.DEFAULT.histogram(
    "watch_fanout_lag_versions",
    "Store versions a watch delivery trails the applied watermark by",
    ("resource",),
    buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 1024, 4096, 16384, 65536),
)

#: Seconds since a scheduler informer last processed a delta or relist
#: (set per solve tick). Large values under churn mean the daemon is
#: deciding on a stale cluster view.
INFORMER_STALENESS = metrics.DEFAULT.gauge(
    "scheduler_informer_staleness_seconds",
    "Seconds since the scheduler informer last processed a delta",
    ("resource",),
)

#: Host<->device transfer volume of the solve pipelines, from the
#: staged buffer sizes (direction: h2d | d2h).
TRANSFER_BYTES = metrics.DEFAULT.counter(
    "solver_device_transfer_bytes_total",
    "Host<->device bytes staged by the solve pipelines",
    ("direction",),
)

#: The PR-7 recompilation sentinel as a live metric: entries in the
#: solver's XLA executable cache, and a counter of compiles observed
#: (cache growth between ticks). Steady growth under steady load means
#: shape-bucket padding regressed and ticks are stalling on compiles.
XLA_CACHE_ENTRIES = metrics.DEFAULT.gauge(
    "solver_xla_compile_cache_entries",
    "Compiled executables in the solver's XLA jit cache",
)
XLA_COMPILES = metrics.DEFAULT.counter(
    "solver_xla_compiles_total",
    "XLA solver compiles observed (compile-cache growth between ticks)",
)

#: Live device memory (kind: in_use | peak | limit), when the backend
#: reports it (TPU does; CPU hosts usually return nothing).
DEVICE_MEMORY = metrics.DEFAULT.gauge(
    "device_memory_bytes",
    "Accelerator memory reported by the backend, by kind",
    ("kind",),
)


def nbytes_of(cols) -> int:
    """Total ndarray bytes in a dict or dataclass of columns (the
    pipeline's staged host buffers)."""
    if isinstance(cols, dict):
        vals = cols.values()
    else:
        vals = vars(cols).values() if hasattr(cols, "__dict__") else ()
    return sum(getattr(v, "nbytes", 0) for v in vals)


def note_transfer(direction: str, nbytes: int) -> None:
    if nbytes > 0:
        TRANSFER_BYTES.inc(float(nbytes), direction=direction)


def observe_watch_lag(resource: str, lag_versions: int) -> None:
    WATCH_LAG.observe(float(max(0, lag_versions)), resource=resource)


_XLA_SEEN = {"entries": 0}
#: Guards the _XLA_SEEN read-modify-write: two daemons sampling the
#: same process concurrently (leader pairs, batch+incremental) must
#: not double-count or swallow a compile-cache growth window.
_XLA_LOCK = sanitizer.lock("sli.xla")
_DEVICE_CACHE: List = []  # resolved once; per-tick stats read off it


def observe_device_telemetry() -> None:
    """Per-tick device telemetry sample: XLA compile-cache size (gauge
    + growth counter) and device memory. Never raises — a backend
    without memory stats (CPU) just skips those gauges."""
    try:
        from kubernetes_tpu.ops.solver import (
            _solve_with_state_xla,
            _solve_xla,
        )

        entries = int(_solve_xla._cache_size()) + int(
            _solve_with_state_xla._cache_size()
        )
    except Exception:
        entries = -1
    if entries >= 0:
        XLA_CACHE_ENTRIES.set(entries)
        with _XLA_LOCK:
            grown = entries - _XLA_SEEN["entries"]
            # Track shrinks too (cache cleared in tests) so the next
            # growth counts from the new floor instead of being
            # swallowed.
            _XLA_SEEN["entries"] = entries
        if grown > 0:
            XLA_COMPILES.inc(grown)
    try:
        if not _DEVICE_CACHE:
            import jax

            _DEVICE_CACHE.append(jax.local_devices()[0])
        stats = _DEVICE_CACHE[0].memory_stats() or {}
    except Exception:
        return
    for key, kind in (
        ("bytes_in_use", "in_use"),
        ("peak_bytes_in_use", "peak"),
        ("bytes_limit", "limit"),
    ):
        if key in stats:
            DEVICE_MEMORY.set(float(stats[key]), kind=kind)


class LifecycleSLICollector:
    """Watch-fed pod-lifecycle milestone collector (informer-style:
    state is kept current by events alone — it never lists or polls).

    Feed it by attaching to a store (``attach``: the kvstore dispatcher
    invokes ``_on_store_event`` for every event, in version order, on
    its own thread) and by the flight-recorder decision sink registered
    at module import (``note_decision``). Thread-safe; observations
    happen outside the track lock."""

    #: Bound on concurrently tracked (created-but-not-Running) pods;
    #: the oldest track is evicted at the cap, so a flood of pods that
    #: never progress cannot grow the collector without bound.
    MAX_TRACKED = 65536

    def __init__(self):
        self._lock = sanitizer.lock("sli.collector")
        # pod key ("ns/name") -> [created_mono, decided, bound, running]
        self._tracks: Dict[str, List] = {}
        self.enabled = True

    # -- wiring --------------------------------------------------------

    def attach(self, store) -> None:
        """Subscribe to a kvstore's event dispatcher (the same feed the
        apiserver watch cache rides). Idempotent per store: KVStore
        subscribers are append-only, so attach once per store."""
        store.subscribe(self._on_store_event)

    # -- event feed (dispatcher thread) --------------------------------

    def _on_store_event(self, version, etype, key, obj, prev) -> None:
        # Hot path: runs on the store dispatcher thread for EVERY pod
        # event — locals bound, untracked pods bail before any parsing,
        # the lock is taken only when state actually changes (dict
        # reads are GIL-atomic; the dispatcher is the sole writer of
        # store-event transitions).
        if not self.enabled or not key.startswith(POD_PREFIX):
            return
        pod_key = key[_PREFIX_LEN:]
        tracks = self._tracks
        if etype == "DELETED":
            if pod_key in tracks:
                with self._lock:
                    tracks.pop(pod_key, None)
            return
        if not isinstance(obj, dict):
            return
        spec = obj.get("spec")
        if etype == "ADDED":
            if spec and spec.get("nodeName"):
                return  # born bound (static pod / replay): no startup story
            now = time.monotonic()
            with self._lock:
                if (
                    len(tracks) >= self.MAX_TRACKED
                    and pod_key not in tracks
                ):
                    tracks.pop(next(iter(tracks)))
                tracks[pod_key] = [now, False, False, False]
            return
        # MODIFIED: bound / running transitions (tracked pods only).
        if pod_key not in tracks:
            return
        bound = bool(spec and spec.get("nodeName"))
        status = obj.get("status")
        running = bool(status) and status.get("phase") == "Running"
        if not (bound or running):
            return
        now = time.monotonic()
        observe_bound = observe_running = False
        with self._lock:
            t = tracks.get(pod_key)
            if t is None:
                return
            created = t[0]
            if bound and not t[2]:
                t[2] = observe_bound = True
            if running and not t[3]:
                t[3] = observe_running = True
            if t[3]:
                del tracks[pod_key]  # lifecycle complete: drain
        if observe_bound:
            STARTUP_LATENCY.observe(now - created, milestone="bound")
        if observe_running:
            STARTUP_LATENCY.observe(now - created, milestone="running")

    # -- decision join (flight-recorder sink, scheduler thread) --------

    def note_decision(self, pod_key: str, outcome: str = "") -> None:
        """The flight recorder logged a Decision for this pod: stamp
        the decision milestone (first one wins — retries re-decide but
        the SLI is time-to-first-decision)."""
        now = time.monotonic()
        with self._lock:
            t = self._tracks.get(pod_key)
            if t is None or t[1]:
                return
            t[1] = True
            created = t[0]
        STARTUP_LATENCY.observe(now - created, milestone="decision")

    # -- introspection -------------------------------------------------

    def tracked_count(self) -> int:
        with self._lock:
            return len(self._tracks)

    #: Bound on the oldest-unbound scan below — tracks are insertion-
    #: ordered so the oldest unbound pod sits near the front; a cap
    #: keeps the per-tick capacity sample O(1) even at MAX_TRACKED.
    _AGE_SCAN_LIMIT = 1024

    def oldest_unbound_age_s(self) -> float:
        """Age (seconds) of the oldest tracked pod that has not reached
        the bound milestone — the backlog-pressure age watermark
        (utils/capacity.py multiplies it by the FIFO depth). 0.0 when
        nothing is waiting."""
        now = time.monotonic()
        with self._lock:
            for i, t in enumerate(self._tracks.values()):
                if i >= self._AGE_SCAN_LIMIT:
                    break
                if not t[2]:  # not yet bound
                    return max(now - t[0], 0.0)
        return 0.0

    def reset(self) -> None:
        with self._lock:
            self._tracks.clear()


DEFAULT = LifecycleSLICollector()

# The PR-5 join: every Decision the flight recorder logs stamps the
# pod's "decision" milestone (registered once at import; flightrecorder
# never imports sli, so there is no cycle).
flightrecorder.add_decision_sink(DEFAULT.note_decision)
