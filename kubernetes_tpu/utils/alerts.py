"""Multi-window multi-burn-rate alerting over the retention plane.

The SLO engine (utils/slo.py) answers "is the objective met right
now"; this module answers the operator question "is the error budget
burning fast enough that a human should move" — the Site Reliability
Workbook ch. 5 recipe: a rule fires only when BOTH a long and a short
window exceed the threshold (the long window proves significance, the
short window proves the burn is still happening, and their conjunction
is what keeps a recovered burn from paging for hours). Two window
pairs ship by default — fast (1h/5m at 14.4x budget burn) catches
budget-exhausting incidents in minutes, slow (6h/30m at 6x) catches
smolder — scaled uniformly by ``clock_scale`` so soak/CI runs exercise
the same rules on compressed clocks (``KT_ALERT_SCALE``).

Each :class:`AlertRule` names a retained series and a measurement kind
(``quantile`` / ``counter_rate`` / ``gauge_max`` — windowed queries
against utils/timeseries.py, never lifetime cumulatives), and runs a
``pending -> firing -> resolved`` state machine: ``for_s`` hold-down
before firing (flap suppression on top of the window conjunction),
``resolve_s`` clear-hysteresis before resolving. Every transition
increments ``alert_transitions_total{rule,state}``, updates
``alerts_firing{rule}``, appends to the bounded transition log (the
soak oracle's firing timeline), and posts a cluster Event through the
attached poster — exactly once per transition.

Default rules cover the signals each telemetry plane owes an operator:
bind latency, watch fan-out lag + drop storms, replication follower
lag, lease renewal latency, backlog pressure, and fragmentation burn.

Surfaces: ``GET /debug/alerts`` / ``ktctl alerts`` render
:func:`AlertEngine.snapshot`; the engine evaluates as a sampler hook
(timeseries.SAMPLER) so rule evaluation shares the retention cadence.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.utils import metrics, sanitizer, timeseries

FIRING = metrics.DEFAULT.gauge(
    "alerts_firing",
    "1 while the named alert rule is in the firing state",
    labels=("rule",),
)
TRANSITIONS = metrics.DEFAULT.counter(
    "alert_transitions_total",
    "Alert state-machine transitions by entered state",
    labels=("rule", "state"),
)


@dataclass(frozen=True)
class BurnWindow:
    """One (long, short) window pair. ``burn`` is the budget-burn
    multiplier applied to counter_rate thresholds (the SRE Workbook
    factors); quantile/gauge watermarks compare against the bare
    threshold — their target IS the line."""

    long_s: float
    short_s: float
    burn: float = 1.0


#: SRE Workbook ch. 5 defaults: 14.4x over 1h/5m exhausts a 30d budget
#: in ~2 days (page-worthy); 6x over 6h/30m in ~5 days (ticket-worthy).
FAST = BurnWindow(long_s=3600.0, short_s=300.0, burn=14.4)
SLOW = BurnWindow(long_s=21600.0, short_s=1800.0, burn=6.0)


@dataclass(frozen=True)
class AlertRule:
    """One declarative burn-rate rule over one retained series."""

    name: str
    series: str
    threshold: float
    #: quantile (windowed histogram quantile) | counter_rate (windowed
    #: per-second rate) | gauge_max (windowed max watermark).
    kind: str = "quantile"
    percentile: float = 0.99
    labels: Tuple[Tuple[str, str], ...] = ()
    windows: Tuple[BurnWindow, ...] = (FAST, SLOW)
    #: page -> humans move now; ticket -> next business day.
    severity: str = "ticket"
    #: Hold-down: the condition must hold this long before pending
    #: promotes to firing (0 = fire immediately).
    for_s: float = 60.0
    #: Hysteresis: the condition must stay clear this long before
    #: firing resolves (0 = resolve immediately).
    resolve_s: float = 120.0
    description: str = ""


DEFAULT_RULES: Tuple[AlertRule, ...] = (
    AlertRule(
        "bind_latency_burn", "pod_startup_latency_seconds", threshold=1.0,
        kind="quantile", labels=(("milestone", "bound"),), severity="page",
        description="windowed p99 create->bound above the 1s scheduling "
        "SLO in both burn windows",
    ),
    AlertRule(
        "watch_fanout_lag", "watch_fanout_lag_versions", threshold=4096.0,
        kind="quantile",
        description="watch deliveries trailing the applied watermark — "
        "consumers are reading the past",
    ),
    AlertRule(
        "watch_drop_storm", "watch_streams_dropped_total", threshold=0.02,
        kind="counter_rate", severity="page",
        description="slow-consumer watch drops burning the relist "
        "budget (threshold is drops/s; burn factors scale it)",
    ),
    AlertRule(
        "replication_follower_lag", "replication_follower_lag_versions",
        threshold=1024.0, kind="gauge_max",
        description="a kvstore follower trailing the leader's commit "
        "index — the pre-quorum-loss signal the HA plane owes",
    ),
    AlertRule(
        "lease_renew_latency", "lease_renew_latency_seconds", threshold=1.0,
        kind="quantile",
        description="lease CAS round-trips creeping toward the lease "
        "window; holders demote themselves when renews can't land",
    ),
    AlertRule(
        "backlog_pressure", "scheduler_backlog_pressure", threshold=256.0,
        kind="gauge_max",
        description="pending-pod backlog watermark (depth x oldest "
        "age) sustained above the capacity plane's pressure line",
    ),
    AlertRule(
        "fragmentation_burn", "cluster_fragmentation_score", threshold=0.5,
        kind="quantile",
        description="cluster fragmentation score burning: free "
        "capacity exists but is unusable shards — defrag is owed",
    ),
)


def _match(label_set: Dict[str, str], labels: Tuple[Tuple[str, str], ...]):
    return all(label_set.get(k) == v for k, v in labels)


class AlertEngine:
    """The rule evaluator + per-rule state machines. One engine per
    process (module DEFAULT); re-entrant callers share state under the
    engine lock. ``clock_scale`` multiplies every window, hold-down,
    and hysteresis (soak/CI compress hours into seconds without
    forking the rules)."""

    MAX_TRANSITIONS = 512

    def __init__(
        self,
        retention: Optional[timeseries.Retention] = None,
        rules: Tuple[AlertRule, ...] = DEFAULT_RULES,
        clock_scale: Optional[float] = None,
    ):
        self.retention = retention if retention is not None else timeseries.DEFAULT
        self.rules = tuple(rules)
        if clock_scale is None:
            clock_scale = float(os.environ.get("KT_ALERT_SCALE", "1.0"))
        self.clock_scale = clock_scale
        self._lock = sanitizer.lock("alerts.engine")
        self._state: Dict[str, dict] = {}
        self._transitions: List[dict] = []
        self._evaluations = 0
        self._post_event: Optional[Callable[..., None]] = None

    # -- wiring --------------------------------------------------------

    def configure(
        self,
        rules: Optional[Tuple[AlertRule, ...]] = None,
        clock_scale: Optional[float] = None,
        retention: Optional[timeseries.Retention] = None,
    ) -> "AlertEngine":
        """Re-point the engine (soak/bench/tests); state resets —
        rules with different windows must not inherit hold-downs."""
        with self._lock:
            if rules is not None:
                self.rules = tuple(rules)
            if clock_scale is not None:
                self.clock_scale = float(clock_scale)
            if retention is not None:
                self.retention = retention
            self._state.clear()
            self._transitions.clear()
            self._evaluations = 0
        return self

    def attach_events(self, client, source: str = "alert-engine") -> None:
        """Post transition Events through `client.record_event` (the
        broadcaster dedupes repeats; a failed post never blocks the
        state machine)."""

        def post(rule: AlertRule, old: str, new: str, value) -> None:
            involved = {
                "kind": "Alert",
                "metadata": {"name": rule.name, "namespace": "default"},
            }
            v = "n/a" if value is None else f"{value:.4g}"
            client.record_event(
                involved,
                reason=f"Alert{new.capitalize()}",
                message=(
                    f"alert {rule.name} {old} -> {new} "
                    f"(value {v}, threshold {rule.threshold:g}, "
                    f"severity {rule.severity})"
                ),
                source=source,
            )

        self._post_event = post

    # -- evaluation ----------------------------------------------------

    def _measure(
        self, rule: AlertRule, window_s: float, labels: Dict[str, str],
        now: Optional[float],
    ) -> Optional[float]:
        r = self.retention
        if rule.kind == "quantile":
            return r.quantile_over_time(
                rule.series, rule.percentile, window_s, labels, now=now
            )
        if rule.kind == "counter_rate":
            return r.rate(rule.series, window_s, labels, now=now)
        return r.max_over_time(rule.series, window_s, labels, now=now)

    def _worst(
        self, rule: AlertRule, window_s: float, now: Optional[float],
    ) -> Optional[float]:
        """Worst measured value across the rule's matching label sets
        (the slo engine's worst-set semantics)."""
        sets = [
            ls
            for ls in self.retention.label_sets(rule.series)
            if _match(ls, rule.labels)
        ]
        worst = None
        for ls in sets:
            v = self._measure(rule, window_s, ls, now)
            if v is not None and (worst is None or v > worst):
                worst = v
        return worst

    def _condition(
        self, rule: AlertRule, now: Optional[float],
    ) -> Tuple[bool, Optional[float], Optional[dict]]:
        """(active, worst short-window value, tripped window info):
        active iff ANY window pair has BOTH its long and short windows
        above the (burn-scaled) threshold."""
        scale = self.clock_scale
        value = None
        for w in rule.windows:
            eff = rule.threshold * (
                w.burn if rule.kind == "counter_rate" else 1.0
            )
            v_long = self._worst(rule, w.long_s * scale, now)
            if v_long is None or v_long <= eff:
                continue
            v_short = self._worst(rule, w.short_s * scale, now)
            if value is None or (v_short is not None and v_short > value):
                value = v_short
            if v_short is not None and v_short > eff:
                return True, v_short, {
                    "longS": w.long_s, "shortS": w.short_s,
                    "burn": w.burn, "threshold": eff,
                }
        if value is None:
            # Nothing tripped: report the fastest window's current
            # reading for the snapshot (may be None — no data).
            value = self._worst(
                rule, rule.windows[0].short_s * scale, now
            ) if rule.windows else None
        return False, value, None

    def _transition(
        self, st: dict, rule: AlertRule, new: str, now: float, value,
    ) -> dict:
        old = st["state"]
        st["state"] = new
        st["since"] = now
        row = {
            "rule": rule.name,
            "from": old,
            "to": new,
            "t_mono": now,
            "wall": time.time(),
            "value": value,
        }
        self._transitions.append(row)
        if len(self._transitions) > self.MAX_TRANSITIONS:
            del self._transitions[: -self.MAX_TRANSITIONS]
        TRANSITIONS.inc(rule=rule.name, state=new)
        FIRING.set(1.0 if new == "firing" else 0.0, rule=rule.name)
        post = self._post_event
        if post is not None:
            try:
                post(rule, old, new, value)
            except Exception:
                pass  # events are observability, never control flow
        return row

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation pass over every rule; returns the transitions
        it caused. Runs as a timeseries.SAMPLER hook, so by default
        alerting costs exactly one pass per retention sweep."""
        t = time.monotonic() if now is None else now
        out: List[dict] = []
        with self._lock:
            self._evaluations += 1
            for rule in self.rules:
                active, value, hit = self._condition(rule, now)
                st = self._state.setdefault(
                    rule.name,
                    {"state": "inactive", "since": t, "clear_since": None},
                )
                st["value"] = value
                st["window"] = hit
                state = st["state"]
                if active:
                    st["clear_since"] = None
                    if state in ("inactive", "resolved"):
                        if rule.for_s * self.clock_scale > 0:
                            out.append(
                                self._transition(st, rule, "pending", t, value)
                            )
                        else:
                            out.append(
                                self._transition(st, rule, "firing", t, value)
                            )
                    elif state == "pending" and (
                        t - st["since"] >= rule.for_s * self.clock_scale
                    ):
                        out.append(
                            self._transition(st, rule, "firing", t, value)
                        )
                else:
                    if state == "pending":
                        # Flap suppressed: the hold-down ate the blip.
                        out.append(
                            self._transition(st, rule, "inactive", t, value)
                        )
                    elif state == "firing":
                        if st["clear_since"] is None:
                            st["clear_since"] = t
                        if (
                            t - st["clear_since"]
                            >= rule.resolve_s * self.clock_scale
                        ):
                            out.append(
                                self._transition(st, rule, "resolved", t, value)
                            )
        return out

    # -- introspection -------------------------------------------------

    @property
    def sampled(self) -> bool:
        """The miss contract: an unmeasured cluster (no evaluations,
        or a retention plane that never sampled) reads unsampled."""
        with self._lock:
            evals = self._evaluations
        return evals > 0 and self.retention.sampled

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(
                name
                for name, st in self._state.items()
                if st["state"] == "firing"
            )

    def transitions(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._transitions]

    def snapshot(self) -> dict:
        """The /debug/alerts payload (ktctl alerts' data source)."""
        now = time.monotonic()
        with self._lock:
            rules = []
            for rule in self.rules:
                st = self._state.get(rule.name)
                row = {
                    "name": rule.name,
                    "series": rule.series,
                    "kind": rule.kind,
                    "severity": rule.severity,
                    "threshold": rule.threshold,
                    "state": st["state"] if st else "inactive",
                    "windows": [
                        {"longS": w.long_s, "shortS": w.short_s,
                         "burn": w.burn}
                        for w in rule.windows
                    ],
                }
                if rule.kind == "quantile":
                    row["percentile"] = rule.percentile
                if rule.labels:
                    row["labels"] = dict(rule.labels)
                if rule.description:
                    row["description"] = rule.description
                if st is not None:
                    row["sinceS"] = round(max(0.0, now - st["since"]), 3)
                    if st.get("value") is not None:
                        row["value"] = round(st["value"], 6)
                    if st.get("window") is not None:
                        row["trippedWindow"] = st["window"]
                rules.append(row)
            return {
                "kind": "AlertReport",
                "sampled": self._evaluations > 0 and self.retention.sampled,
                "clockScale": self.clock_scale,
                "evaluations": self._evaluations,
                "firing": sorted(
                    n for n, st in self._state.items()
                    if st["state"] == "firing"
                ),
                "rules": rules,
                "transitions": [dict(r) for r in self._transitions[-64:]],
            }


#: Process-global engine over the process-global retention store.
DEFAULT = AlertEngine()


def ensure_started(
    interval_s: Optional[float] = None, client=None,
) -> AlertEngine:
    """Boot the health plane: start the retention sampler and ride its
    cadence with DEFAULT's evaluation (idempotent; daemons, local-up,
    soak, and bench all call this). With a client, transition Events
    post to the cluster."""
    if client is not None:
        DEFAULT.attach_events(client)
    sampler = timeseries.ensure_started(interval_s=interval_s)
    sampler.add_hook(_evaluate_default)
    return DEFAULT


def _evaluate_default() -> None:
    DEFAULT.evaluate()
