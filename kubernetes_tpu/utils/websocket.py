"""Minimal RFC 6455 websocket framing (server + client, text frames).

Reference: pkg/apiserver/watch.go:45-102 serves watches over BOTH
chunked JSON and websocket (golang.org/x/net/websocket); this is the
stdlib-only equivalent for the same wire role. Scope is deliberately
the watch protocol's needs: handshake, unfragmented text/close frames,
client-side masking (clients MUST mask; servers MUST NOT).
"""

from __future__ import annotations

import base64
import hashlib
import os
import struct
from typing import Optional, Tuple

GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def handshake_headers(client_key: str) -> list:
    return [
        ("Upgrade", "websocket"),
        ("Connection", "Upgrade"),
        ("Sec-WebSocket-Accept", accept_key(client_key)),
    ]


def encode_frame(payload: bytes, opcode: int = OP_TEXT, mask: bool = False) -> bytes:
    """One unfragmented frame (FIN set). Clients mask, servers don't."""
    head = bytes([0x80 | opcode])
    n = len(payload)
    mask_bit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mask_bit | n])
    elif n < 65536:
        head += bytes([mask_bit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mask_bit | 127]) + struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return head + key + masked
    return head + payload


def read_exact(stream, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            raise ConnectionError("websocket stream closed mid-frame")
        buf += chunk
    return buf


def decode_frame(stream) -> Tuple[int, bytes]:
    """Read one frame -> (opcode, payload). Raises ConnectionError on
    EOF."""
    b0, b1 = read_exact(stream, 2)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    if n == 126:
        (n,) = struct.unpack(">H", read_exact(stream, 2))
    elif n == 127:
        (n,) = struct.unpack(">Q", read_exact(stream, 8))
    key = read_exact(stream, 4) if masked else None
    payload = read_exact(stream, n)
    if key:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return opcode, payload


class WebSocketClient:
    """Tiny client for tests + in-repo consumers: connect, iterate text
    payloads. `headers` are extra handshake headers (auth)."""

    def __init__(
        self,
        host: str,
        port: int,
        path: str,
        timeout: float = 30.0,
        headers: Optional[dict] = None,
    ):
        import socket as socketlib
        import threading

        self._wlock = threading.Lock()
        self.sock = socketlib.create_connection((host, port), timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        extra = "".join(
            f"{name}: {value}\r\n" for name, value in (headers or {}).items()
        )
        req = (
            f"GET {path} HTTP/1.1\r\n"
            f"Host: {host}:{port}\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n"
            f"{extra}\r\n"
        )
        self.sock.sendall(req.encode())
        self.rfile = self.sock.makefile("rb")
        status = self.rfile.readline()
        if b"101" not in status:
            raise ConnectionError(f"websocket handshake refused: {status!r}")
        expect = accept_key(key)
        got = ""
        while True:
            line = self.rfile.readline().strip()
            if not line:
                break
            name, _, value = line.decode().partition(":")
            if name.strip().lower() == "sec-websocket-accept":
                got = value.strip()
        if got != expect:
            raise ConnectionError("websocket accept key mismatch")

    def recv_text(self) -> Optional[str]:
        """Next text payload; None on clean close."""
        while True:
            op, payload = self.recv()
            if op == OP_TEXT:
                return payload.decode()
            if op == OP_CLOSE:
                return None

    def recv(self) -> Tuple[int, bytes]:
        """Next (opcode, payload), answering pings transparently."""
        while True:
            op, payload = decode_frame(self.rfile)
            if op == OP_PING:
                self.send(payload, OP_PONG)
                continue
            return op, payload

    def send(self, payload: bytes, opcode: int = OP_BINARY) -> None:
        # Lock: concurrent senders (relay pumps answer PINGs while the
        # other direction streams data) must not interleave mid-frame.
        with self._wlock:
            self.sock.sendall(encode_frame(payload, opcode, mask=True))

    def clear_timeout(self) -> None:
        """Remove the connect-time socket timeout: long-lived tunnels
        must survive idle periods."""
        self.sock.settimeout(None)

    def close(self) -> None:
        """GRACEFUL close: send a CLOSE frame only. The socket stays
        open so in-flight inbound frames still deliver; call abort()
        (relays do, after a grace period) to release the transport."""
        try:
            self.send(b"", OP_CLOSE)
        except OSError:
            pass

    def abort(self) -> None:
        """Hard-close the transport (unblocks a reader on another
        thread)."""
        try:
            self.sock.close()
        except OSError:
            pass


class ServerEndpoint:
    """Server-side websocket endpoint over a handler's rfile/wfile
    (post-handshake), with the same recv/send surface as the client —
    so relay helpers work with either end. `raw_socket` (the handler's
    connection) enables abort()."""

    def __init__(self, rfile, wfile, raw_socket=None):
        import threading

        self.rfile = rfile
        self.wfile = wfile
        self.raw_socket = raw_socket
        self._wlock = threading.Lock()

    def recv(self) -> Tuple[int, bytes]:
        while True:
            op, payload = decode_frame(self.rfile)
            if op == OP_PING:
                self.send(payload, OP_PONG)
                continue
            return op, payload

    def send(self, payload: bytes, opcode: int = OP_BINARY) -> None:
        with self._wlock:
            self.wfile.write(encode_frame(payload, opcode))  # servers don't mask
            self.wfile.flush()

    def close(self) -> None:
        try:
            self.send(b"", OP_CLOSE)
        except OSError:
            pass

    def abort(self) -> None:
        if self.raw_socket is not None:
            import socket as socketlib

            try:
                self.raw_socket.shutdown(socketlib.SHUT_RDWR)
            except OSError:
                pass


def _abort_later(end, delay: float = 3.0) -> None:
    """Daemon timer backstop: hard-close an endpoint if the graceful
    CLOSE didn't finish the job. Daemonized so lingering timers can't
    hold the process open after a tunnel ends."""
    import threading

    timer = threading.Timer(delay, end.abort)
    timer.daemon = True
    timer.start()


def relay_ws_tcp(ws_end, sock) -> None:
    """Bidirectional pump: websocket endpoint <-> TCP socket. Blocks
    until either side closes. Clears the socket's timeout first (idle
    tunnels must not be torn down by a connect-time timeout)."""
    import socket as socketlib
    import threading

    sock.settimeout(None)
    done = threading.Event()

    def tcp_to_ws():
        try:
            while not done.is_set():
                data = sock.recv(65536)
                if not data:
                    break
                ws_end.send(data, OP_BINARY)
        except (ConnectionError, OSError):
            pass
        finally:
            done.set()
            # Graceful first: the CLOSE frame propagates shutdown
            # through relay chains WITHOUT discarding in-flight bytes
            # (a hard abort RSTs kernel-buffered data). The delayed
            # abort is only the backstop that unblocks OUR reader if
            # the peer never answers the CLOSE.
            ws_end.close()
            _abort_later(ws_end)

    t = threading.Thread(target=tcp_to_ws, daemon=True)
    t.start()
    try:
        while not done.is_set():
            op, payload = ws_end.recv()
            if op == OP_CLOSE:
                break
            if payload:
                sock.sendall(payload)
    except (ConnectionError, OSError):
        pass
    finally:
        done.set()
        try:
            sock.shutdown(socketlib.SHUT_RDWR)
        except OSError:
            pass
        sock.close()
        ws_end.close()
        ws_end.abort()  # peer already finished; safe to hard-close


def relay_ws_ws(a, b) -> None:
    """Bidirectional pump between two websocket endpoints."""
    import threading

    done = threading.Event()

    def pump(src, dst):
        try:
            while not done.is_set():
                op, payload = src.recv()
                if op == OP_CLOSE:
                    break
                dst.send(payload, op)
        except (ConnectionError, OSError):
            pass
        finally:
            done.set()
            for end in (src, dst):
                end.close()  # graceful: CLOSE frames propagate
                _abort_later(end)  # delayed hard-close backstop

    t = threading.Thread(target=pump, args=(b, a), daemon=True)
    t.start()
    pump(a, b)
    t.join(timeout=4)
