"""Cluster DNS addon: service discovery by name.

Reference: cluster/addons/dns — skydns fed by kube2sky watching
services, so `<service>.<namespace>.svc.<domain>` resolves to the
service's portal (cluster) IP. Here both halves live in one small UDP
server: a service Informer keeps the name table, and a minimal DNS
responder answers A queries from it (NXDOMAIN otherwise).

Accepted names (trailing dot optional):
    <service>.<namespace>.svc.<domain>     e.g. web.default.svc.cluster.local
    <service>.<namespace>                  the short form kube2sky also served
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Dict, Optional, Tuple

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Service

DEFAULT_DOMAIN = "cluster.local"

_FLAG_RESPONSE = 0x8000
_FLAG_RD = 0x0100
_FLAG_RA = 0x0080
RCODE_NXDOMAIN = 3
QTYPE_A = 1
QCLASS_IN = 1


def _decode_service(wire: dict) -> Service:
    return serde.from_wire(Service, wire)


def parse_query(data: bytes) -> Optional[Tuple[int, int, str, int, bytes]]:
    """-> (txid, flags, qname, qtype, question_bytes) or None."""
    if len(data) < 12:
        return None
    txid, flags, qdcount, _an, _ns, _ar = struct.unpack(">HHHHHH", data[:12])
    if qdcount < 1:
        return None
    labels = []
    pos = 12
    while pos < len(data):
        n = data[pos]
        if n == 0:
            pos += 1
            break
        if n > 63 or pos + 1 + n > len(data):
            return None
        labels.append(data[pos + 1 : pos + 1 + n].decode(errors="replace"))
        pos += 1 + n
    if pos + 4 > len(data):
        return None
    qtype, qclass = struct.unpack(">HH", data[pos : pos + 4])
    if qclass != QCLASS_IN:
        return None
    return txid, flags, ".".join(labels), qtype, data[12 : pos + 4]


def build_response(
    txid: int,
    flags: int,
    question: bytes,
    ip: Optional[str],
    ttl: int = 30,
    name_exists: Optional[bool] = None,
) -> bytes:
    """NXDOMAIN only when the NAME is unknown; an existing name queried
    with an unsupported qtype gets NOERROR with zero answers (resolvers
    negative-cache NXDOMAIN for the whole name, breaking the A lookup a
    dual-stack client runs in parallel)."""
    exists = name_exists if name_exists is not None else bool(ip)
    rcode = 0 if exists else RCODE_NXDOMAIN
    out_flags = _FLAG_RESPONSE | (flags & _FLAG_RD) | _FLAG_RA | rcode
    answers = 1 if ip else 0
    head = struct.pack(">HHHHHH", txid, out_flags, 1, answers, 0, 0)
    body = question
    if ip:
        # Answer: name pointer to the question at offset 12 (0xC00C),
        # TYPE A, CLASS IN, TTL, RDLENGTH 4, then the address.
        body += struct.pack(
            ">HHHIH", 0xC00C, QTYPE_A, QCLASS_IN, ttl, 4
        ) + socket.inet_aton(ip)
    return head + body


class ClusterDNS:
    """UDP DNS server over the live service table."""

    def __init__(
        self,
        client,
        domain: str = DEFAULT_DOMAIN,
        bind: str = "127.0.0.1",
        port: int = 0,
        resync_period: float = 5.0,
    ):
        self.domain = domain.strip(".")
        self.resync_period = resync_period
        self._table: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.services = Informer(
            client,
            "services",
            decode=_decode_service,
            on_add=self._upsert,
            on_update=self._upsert,
            on_delete=self._remove,
        )
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind((bind, port))
        self.sock.settimeout(0.2)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.sock.getsockname()[1]

    def publish(self, client, cluster_ip: str = "10.0.0.10",
                namespace: str = "default", host: str = "127.0.0.1") -> None:
        """Register the kube-dns Service + Endpoints (the reference's
        skydns-svc.yaml pins the well-known 10.0.0.10). A real-portal
        kube-proxy then serves DNS at VIP:53/UDP for every process on
        the host. Selector-less, so the endpoints controller leaves
        the manual endpoints alone. Idempotent across restarts."""
        from kubernetes_tpu.server.api import APIError

        svc = {
            "kind": "Service",
            "apiVersion": "v1",
            "metadata": {
                "name": "kube-dns",
                "namespace": namespace,
                "labels": {"kubernetes.io/cluster-service": "true"},
            },
            "spec": {
                "clusterIP": cluster_ip,
                "ports": [{"name": "dns", "port": 53, "protocol": "UDP"}],
            },
        }
        try:
            client.get("services", "kube-dns", namespace=namespace)
        except APIError as e:
            if e.code != 404:
                raise
            client.create("services", svc, namespace=namespace)
        endpoints = {
            "kind": "Endpoints",
            "apiVersion": "v1",
            "metadata": {"name": "kube-dns", "namespace": namespace},
            "subsets": [
                {
                    # The reachable address of the host running this
                    # addon — loopback only works on single-host
                    # clusters; multi-host composition passes the
                    # master's address.
                    "addresses": [{"ip": host}],
                    "ports": [{"name": "dns", "port": self.port,
                               "protocol": "UDP"}],
                }
            ],
        }
        try:
            client.create("endpoints", endpoints, namespace=namespace)
        except APIError as e:
            if e.code != 409:
                raise
            client.update("endpoints", endpoints, namespace=namespace)

    # -- service table (the kube2sky half) ----------------------------

    def _key(self, svc: Service) -> str:
        return f"{svc.metadata.name}.{svc.metadata.namespace or 'default'}"

    def _upsert(self, svc: Service) -> None:
        ip = svc.spec.cluster_ip
        with self._lock:
            if ip and ip != "None":
                self._table[self._key(svc)] = ip
            else:
                self._table.pop(self._key(svc), None)  # headless

    def _remove(self, svc: Service) -> None:
        with self._lock:
            self._table.pop(self._key(svc), None)

    def resolve(self, qname: str) -> Optional[str]:
        name = qname.rstrip(".").lower()
        suffix = f".svc.{self.domain}"
        if name.endswith(suffix):
            name = name[: -len(suffix)]
        if name.count(".") != 1:
            return None  # must be <service>.<namespace>
        with self._lock:
            return self._table.get(name)

    # -- the skydns half ----------------------------------------------

    def start(self) -> "ClusterDNS":
        self.services.start()
        self.services.wait_for_sync()
        # Prime by full rebuild from the synced store: the reflector
        # signals sync BEFORE its ADDED callbacks drain, so relying on
        # the callbacks alone can briefly answer NXDOMAIN for
        # pre-existing services. (Event callbacks then keep the table
        # hot; the serve loop's periodic rebuild heals re-list gaps.)
        self._rebuild()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.services.stop()
        if self._thread:
            self._thread.join(timeout=2)
        self.sock.close()

    def _rebuild(self) -> None:
        """Reconcile the table against the informer store. Event
        callbacks alone are not enough: a watch drop + re-list REPLACES
        the store without firing DELETED for objects that vanished in
        the gap, and the start()-time prime races concurrent deletes —
        either would leave a deleted service resolving forever."""
        fresh: Dict[str, str] = {}
        for svc in self.services.store.list():
            ip = svc.spec.cluster_ip
            if ip and ip != "None":
                fresh[self._key(svc)] = ip
        with self._lock:
            self._table = fresh

    def _serve(self) -> None:
        import time

        last_sync = time.monotonic()
        while not self._stop.is_set():
            if time.monotonic() - last_sync > self.resync_period:
                self._rebuild()
                last_sync = time.monotonic()
            try:
                data, addr = self.sock.recvfrom(512)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                parsed = parse_query(data)
                if parsed is None:
                    continue
                txid, flags, qname, qtype, question = parsed
                resolved = self.resolve(qname)
                ip = resolved if qtype == QTYPE_A else None
                self.sock.sendto(
                    build_response(
                        txid, flags, question, ip,
                        name_exists=resolved is not None,
                    ),
                    addr,
                )
            except Exception:
                pass  # one bad packet must not kill the resolver
