"""Cluster-level log aggregation addon.

Reference: cluster/addons/fluentd-elasticsearch — a per-node fluentd
tails every container's logs into Elasticsearch so operators can
search across the whole cluster (including pods that have since been
restarted or deleted). Here the aggregator rides the stack's own
surfaces instead of host-path tailing: a pod informer discovers
running containers, and each poll pulls fresh lines through the
apiserver's pod-log subresource (which relays to the owning kubelet)
— the same route `ktctl logs` takes, so whatever runtime backs the
kubelet is automatically covered.

Retention is a bounded global ring: entries survive their pod's
deletion until capacity evicts them (the ES-index analog, sized for a
dev cluster not a datacenter).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Pod
from kubernetes_tpu.server.api import APIError


@dataclass
class LogEntry:
    namespace: str
    pod: str
    container: str
    line: str


class ClusterLogAggregator:
    """Poll-based cluster log collector with substring search."""

    def __init__(self, client, poll_interval: float = 1.0, capacity: int = 100_000):
        self.client = client
        self.poll_interval = poll_interval
        self._entries: deque = deque(maxlen=capacity)
        # (ns, pod, container) -> number of lines already ingested.
        self._offsets: Dict[Tuple[str, str, str], int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.pods = Informer(
            client, "pods", decode=lambda w: serde.from_wire(Pod, w)
        )

    def start(self) -> "ClusterLogAggregator":
        self.pods.start()
        self.pods.wait_for_sync()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.pods.stop()
        if self._thread:
            self._thread.join(timeout=3)

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.collect_once()
            except Exception:
                pass  # crash containment, like every other loop

    def collect_once(self) -> int:
        """One sweep over running pods; returns lines ingested."""
        ingested = 0
        live_keys = set()
        for pod in self.pods.store.list():
            if pod.status.phase not in ("Running", "Succeeded", "Failed"):
                continue
            if not pod.spec.node_name:
                continue
            ns = pod.metadata.namespace or "default"
            for c in pod.spec.containers:
                key = (ns, pod.metadata.name, c.name)
                live_keys.add(key)
                try:
                    text = self.client.pod_logs(
                        pod.metadata.name, namespace=ns, container=c.name
                    )
                except APIError:
                    continue  # kubelet not serving this pod's logs yet
                except Exception:
                    continue  # transport hiccup; retry next sweep
                lines = text.splitlines()
                if text and not text.endswith("\n") and lines:
                    # Trailing unterminated fragment: leave it for the
                    # sweep after its newline arrives — counting it now
                    # would pin the offset past the completed line.
                    lines = lines[:-1]
                seen = self._offsets.get(key, 0)
                if len(lines) < seen:
                    seen = 0  # log rotated/truncated: re-ingest
                fresh = lines[seen:]
                if not fresh:
                    continue
                with self._lock:
                    for line in fresh:
                        self._entries.append(
                            LogEntry(ns, pod.metadata.name, c.name, line)
                        )
                self._offsets[key] = len(lines)
                ingested += len(fresh)
        # Deleted pods keep their RING entries (retention is the whole
        # point) but not their offset bookkeeping — under churn the
        # offsets dict would otherwise grow one key per ever-seen pod.
        for key in list(self._offsets):
            if key not in live_keys:
                del self._offsets[key]
        return ingested

    def search(
        self,
        substring: str = "",
        namespace: Optional[str] = None,
        pod: Optional[str] = None,
        limit: int = 1000,
    ) -> List[LogEntry]:
        """Newest-last substring search across every collected line —
        the Kibana-query analog."""
        out: List[LogEntry] = []
        with self._lock:
            for e in self._entries:
                if substring and substring not in e.line:
                    continue
                if namespace is not None and e.namespace != namespace:
                    continue
                if pod is not None and e.pod != pod:
                    continue
                out.append(e)
        return out[-limit:]
