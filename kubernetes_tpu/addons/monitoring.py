"""Cluster monitoring addon: the heapster analog.

Reference: cluster/addons/cluster-monitoring — heapster scrapes every
node's cAdvisor through the kubelet, aggregates node/pod resource
series, and serves a REST model that dashboards (InfluxDB/Grafana in
the reference) consume. Here one small daemon plays heapster's role:

- a node Informer tracks the fleet; every `resolution` seconds each
  node's kubelet /stats is pulled THROUGH the apiserver node proxy
  (the same path `ktctl top` reads once — this keeps history);
- per-node and per-pod time series are kept in bounded ring buffers
  (window seconds of history);
- a heapster-model-shaped REST API serves them:
    GET /api/v1/model/nodes
    GET /api/v1/model/nodes/{node}/metrics
    GET /api/v1/model/nodes/{node}/metrics/{metric}
    GET /api/v1/model/namespaces/{ns}/pods
    GET /api/v1/model/namespaces/{ns}/pods/{pod}/metrics/{metric}
  each metric endpoint returning {"metrics": [{"timestamp", "value"}],
  "latestTimestamp"} like heapster's model API;
- publish() registers the monitoring-heapster Service + Endpoints in
  kube-system (like the reference addon's service manifest), so
  consumers discover it by name.

Node metrics: pods, containers, memory_rss_bytes, disk_used_fraction.
Pod metrics: memory_rss_bytes, restarts, uptime_seconds.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Deque, Dict, Optional, Tuple
from urllib.parse import urlparse

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Node, Pod

NODE_METRICS = ("pods", "containers", "memory_rss_bytes", "disk_used_fraction")
POD_METRICS = ("memory_rss_bytes", "restarts", "uptime_seconds")


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


class _Series:
    """Bounded (timestamp, value) ring."""

    def __init__(self, window: float, resolution: float):
        self.points: Deque[Tuple[float, float]] = deque(
            maxlen=max(2, int(window / max(resolution, 0.1)))
        )

    def add(self, ts: float, value: float) -> None:
        self.points.append((ts, value))

    def render(self) -> dict:
        pts = [
            {"timestamp": _iso(t), "value": v} for t, v in self.points
        ]
        return {
            "metrics": pts,
            "latestTimestamp": pts[-1]["timestamp"] if pts else "",
        }


class ClusterMonitor:
    def __init__(
        self,
        client,
        server_url: str,
        resolution: float = 5.0,
        window: float = 600.0,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self.client = client
        self.server_url = server_url.rstrip("/")
        self.resolution = resolution
        self.window = window
        # Deletion hooks prune the series map: under pod churn every
        # revision mints new names, and without pruning both memory
        # and the model listings grow forever (heapster expires stale
        # entries the same way).
        self.nodes = Informer(
            client, "nodes",
            decode=lambda w: serde.from_wire(Node, w),
            on_delete=lambda n: self._prune("node", n.metadata.name),
        )
        self.pods = Informer(
            client, "pods",
            decode=lambda w: serde.from_wire(Pod, w),
            on_delete=lambda p: self._prune(
                "pod", f"{p.metadata.namespace}/{p.metadata.name}"
            ),
        )
        self._lock = threading.Lock()
        # (scope, key, metric) -> _Series; scope "node" keys by node
        # name, scope "pod" keys by "namespace/name".
        self._series: Dict[Tuple[str, str, str], _Series] = {}
        self._tombstones: Dict[Tuple[str, str], float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        monitor = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):  # noqa: N802
                pass

            def do_GET(self):  # noqa: N802
                try:
                    code, body = monitor._serve(urlparse(self.path).path)
                except Exception as e:
                    code, body = 500, {"error": str(e)}
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True

    # -- scraping -----------------------------------------------------

    def _scrape_node(self, name: str) -> None:
        url = f"{self.server_url}/api/v1/nodes/{name}/proxy/stats"
        with urllib.request.urlopen(url, timeout=5) as resp:
            stats = json.loads(resp.read())
        now = time.time()  # per-scrape stamp, not round-start
        pods = stats.get("pods", {})
        containers = sum(len(cs) for cs in pods.values())
        rss = sum(
            c.get("rssBytes", 0) for cs in pods.values() for c in cs
        )
        disk = stats.get("disk", {}).get("usedFraction", 0.0)
        self._add("node", name, "pods", now, len(pods))
        self._add("node", name, "containers", now, containers)
        self._add("node", name, "memory_rss_bytes", now, rss)
        self._add("node", name, "disk_used_fraction", now, disk)
        # Pod attribution: stats key by uid; the pod cache maps uids to
        # namespace/name (heapster does the same join via the API).
        by_uid = {
            p.metadata.uid: p
            for p in self.pods.store.list()
            if p.metadata.uid
        }
        for uid, cs in pods.items():
            pod = by_uid.get(uid)
            if pod is None:
                continue
            key = f"{pod.metadata.namespace}/{pod.metadata.name}"
            self._add(
                "pod", key, "memory_rss_bytes", now,
                sum(c.get("rssBytes", 0) for c in cs),
            )
            self._add(
                "pod", key, "restarts", now,
                sum(c.get("restartCount", 0) for c in cs),
            )
            self._add(
                "pod", key, "uptime_seconds", now,
                max((c.get("uptimeSeconds", 0) for c in cs), default=0),
            )

    def _prune(self, scope: str, key: str) -> None:
        with self._lock:
            for k in [
                k for k in self._series if k[0] == scope and k[1] == key
            ]:
                del self._series[k]
            # Tombstone: an in-flight scrape that joined against the
            # pre-delete pod cache must not resurrect the series after
            # this one-and-only prune (the DELETE event never refires).
            now = time.time()
            self._tombstones[(scope, key)] = now
            # Sweep expired tombstones here (deletes are the only
            # source of growth): under revision churn names never
            # return, so _add's rebirth branch would never collect
            # them and the map would grow forever.
            horizon = now - 2 * self.resolution
            for k in [k for k, t in self._tombstones.items() if t < horizon]:
                del self._tombstones[k]

    def _add(self, scope: str, key: str, metric: str, ts: float, v: float):
        with self._lock:
            dead = self._tombstones.get((scope, key))
            if dead is not None:
                if ts <= dead + 2 * self.resolution:
                    return  # stale in-flight scrape of a deleted object
                del self._tombstones[(scope, key)]  # genuinely reborn
            s = self._series.get((scope, key, metric))
            if s is None:
                s = self._series[(scope, key, metric)] = _Series(
                    self.window, self.resolution
                )
            s.add(ts, float(v))

    def _loop(self) -> None:
        # Scrapes run in parallel: one dead kubelet must not stall the
        # whole round by its timeout (sequential polling of N nodes
        # with K down costs K x 5s per round and gaps every series).
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=8) as pool:
            while not self._stop.is_set():
                futures = [
                    pool.submit(self._scrape_node, node.metadata.name)
                    for node in self.nodes.store.list()
                ]
                for f in futures:
                    try:
                        f.result(timeout=10)
                    except Exception:
                        pass  # node gone / kubelet down: skip this round
                self._stop.wait(self.resolution)

    # -- model API ----------------------------------------------------

    def _serve(self, path: str) -> Tuple[int, object]:
        parts = tuple(p for p in path.split("/") if p)
        if parts == ("healthz",):
            return 200, {"ok": True}
        if parts[:3] != ("api", "v1", "model"):
            return 404, {"error": "try /api/v1/model/..."}
        rest = parts[3:]
        with self._lock:
            if rest == ("nodes",):
                names = sorted(
                    {k for s, k, _m in self._series if s == "node"}
                )
                return 200, {"items": names}
            if len(rest) >= 2 and rest[0] == "nodes":
                node = rest[1]
                if rest[2:] == ("metrics",) or not rest[2:]:
                    return 200, {"items": list(NODE_METRICS)}
                if len(rest) == 4 and rest[2] == "metrics":
                    s = self._series.get(("node", node, rest[3]))
                    if s is None:
                        return 404, {"error": f"no series {rest[3]!r} for {node!r}"}
                    return 200, s.render()
            if len(rest) >= 3 and rest[0] == "namespaces" and rest[2] == "pods":
                ns = rest[1]
                if len(rest) == 3:
                    pods = sorted(
                        k.split("/", 1)[1]
                        for s, k, _m in self._series
                        if s == "pod" and k.startswith(ns + "/")
                    )
                    return 200, {"items": sorted(set(pods))}
                if len(rest) == 6 and rest[4] == "metrics":
                    s = self._series.get(("pod", f"{ns}/{rest[3]}", rest[5]))
                    if s is None:
                        return 404, {"error": "no such series"}
                    return 200, s.render()
                if len(rest) == 5 and rest[4] == "metrics":
                    return 200, {"items": list(POD_METRICS)}
        return 404, {"error": f"unknown model path {path!r}"}

    # -- lifecycle ----------------------------------------------------

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "ClusterMonitor":
        self.nodes.start()
        self.pods.start()
        self.nodes.wait_for_sync(10)
        self.pods.wait_for_sync(10)
        threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True,
        ).start()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.nodes.stop()
        self.pods.stop()
        if self._thread:
            self._thread.join(timeout=5)

    def publish(
        self,
        client,
        cluster_ip: str = "10.0.0.11",
        namespace: str = "kube-system",
        host: str = "127.0.0.1",
    ) -> None:
        """Register monitoring-heapster Service + Endpoints (the
        reference addon's manifests, cluster/addons/cluster-monitoring)."""
        from kubernetes_tpu.server.api import APIError

        svc = {
            "kind": "Service",
            "apiVersion": "v1",
            "metadata": {
                "name": "monitoring-heapster",
                "namespace": namespace,
                "labels": {"kubernetes.io/cluster-service": "true"},
            },
            "spec": {
                "clusterIP": cluster_ip,
                "ports": [{"port": 80, "protocol": "TCP"}],
            },
        }
        try:
            client.create("services", svc, namespace=namespace)
        except APIError as e:
            if e.code != 409:
                raise
        ep = {
            "kind": "Endpoints",
            "apiVersion": "v1",
            "metadata": {
                "name": "monitoring-heapster", "namespace": namespace,
            },
            "subsets": [
                {
                    "addresses": [{"ip": host}],
                    "ports": [{"port": self.port, "protocol": "TCP"}],
                }
            ],
        }
        try:
            client.create("endpoints", ep, namespace=namespace)
        except APIError as e:
            if e.code != 409:
                raise
            client.update("endpoints", ep, namespace=namespace)
