"""Cluster addons (reference: cluster/addons/ — DNS, monitoring, ...)."""

from kubernetes_tpu.addons.dns import ClusterDNS

__all__ = ["ClusterDNS"]
