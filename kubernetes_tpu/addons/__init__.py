"""Cluster addons (reference: cluster/addons/ — DNS, logging,
monitoring)."""

from kubernetes_tpu.addons.dns import ClusterDNS
from kubernetes_tpu.addons.logging import ClusterLogAggregator
from kubernetes_tpu.addons.monitoring import ClusterMonitor

__all__ = ["ClusterDNS", "ClusterLogAggregator", "ClusterMonitor"]
