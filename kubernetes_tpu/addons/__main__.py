"""Addon runner: DNS + monitoring as a standalone process.

Reference: cluster addons run as cluster workloads deployed by
cluster/addons manifests; here (no container images) they run as one
daemon process per cluster, started by cluster/kube-up.py or by hand:

    python -m kubernetes_tpu.addons --server http://master:8080 \\
        --dns --monitoring --publish
"""

from __future__ import annotations

import argparse
import signal
import threading


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu-addons")
    p.add_argument("--server", "-s", default="http://127.0.0.1:8080")
    p.add_argument("--dns", action="store_true")
    p.add_argument("--dns-ip", default="10.0.0.10")
    p.add_argument("--dns-port", type=int, default=0)
    p.add_argument("--monitoring", action="store_true")
    p.add_argument("--monitoring-ip", default="10.0.0.11")
    p.add_argument("--monitoring-port", type=int, default=0)
    p.add_argument(
        "--publish", action="store_true",
        help="register kube-dns / monitoring-heapster Services",
    )
    p.add_argument(
        "--endpoint-host", default="127.0.0.1",
        help="the address OTHER hosts reach this addon process at "
        "(published in the Services' Endpoints; loopback only works "
        "on single-host clusters)",
    )
    args = p.parse_args(argv)

    from kubernetes_tpu.client import Client, HTTPTransport

    def client():
        return Client(HTTPTransport(args.server))

    daemons = []
    if args.dns:
        from kubernetes_tpu.addons.dns import ClusterDNS

        dns = ClusterDNS(client(), port=args.dns_port).start()
        if args.publish:
            dns.publish(
                client(), cluster_ip=args.dns_ip, host=args.endpoint_host
            )
        daemons.append(dns)
        print(f"dns serving on udp port {dns.port}")
    if args.monitoring:
        from kubernetes_tpu.addons.monitoring import ClusterMonitor

        mon = ClusterMonitor(
            client(), args.server, port=args.monitoring_port
        ).start()
        if args.publish:
            mon.publish(
                client(),
                cluster_ip=args.monitoring_ip,
                host=args.endpoint_host,
            )
        daemons.append(mon)
        print(f"monitoring model api on port {mon.port}")
    if not daemons:
        p.error("nothing to run: pass --dns and/or --monitoring")

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    for d in daemons:
        d.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
