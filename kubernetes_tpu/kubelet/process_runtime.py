"""Process-based container runtime: pods are real OS processes.

The TPU-native analog of the reference's DockerManager
(pkg/kubelet/dockertools/manager.go:1201-1315): each pod starts an
infra anchor — the native `pause` binary (native/pause.c, equivalent of
third_party/pause/pause.asm) — then one subprocess per container.
Containers are compared by a hash of their runtime-relevant spec
(computePodContainerChanges' hash check, manager.go:1287+): a changed
spec kills and recreates the container with an incremented restart
count. stdout/stderr stream to per-container log files — the substrate
for the kubelet's /logs endpoint and `ktctl logs`.

"Image" semantics: a process runtime has no registry; the container's
`command` + `args` are the process. A container without a command runs
the pause binary (a well-behaved forever-process), which keeps
reference manifests (image-only nginx pods) runnable in integration
tests.

Restart-crossing state: each container writes a JSON record
(pid, spec hash, restart count, log path) under
<root>/pods/<uid>/<name>.json. A restarted kubelet's runtime ADOPTS
live recorded processes instead of orphaning them — the reference
reconstructs the same way from `docker ps` (kubelet.go:1154-1160).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import signal
import subprocess
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.models.objects import Pod
from kubernetes_tpu.kubelet.runtime import ContainerRuntime, RuntimeContainer


def _spec_hash(spec) -> str:
    ident = json.dumps(
        {
            "image": spec.image,
            "command": spec.command,
            "args": spec.args,
            "env": [(e.name, e.value) for e in spec.env],
            "workingDir": spec.working_dir,
        },
        sort_keys=True,
    )
    return hashlib.sha1(ident.encode()).hexdigest()[:16]


@dataclass
class _Proc:
    """One live (or exited) container process."""

    pid: int
    popen: Optional[subprocess.Popen]  # None for adopted processes
    spec_hash: str
    name: str
    image: str
    log_path: str
    restart_count: int = 0
    started_at: float = 0.0
    exit_code: Optional[int] = None  # None while running

    def poll(self) -> Optional[int]:
        if self.exit_code is not None:
            return self.exit_code
        if self.popen is not None:
            rc = self.popen.poll()
            if rc is not None:
                self.exit_code = rc
            return self.exit_code
        # Adopted process: liveness via /proc; exit code unknowable.
        try:
            os.kill(self.pid, 0)
            return None
        except OSError:
            self.exit_code = 0
            return 0


class ProcessRuntime(ContainerRuntime):
    """Real-process runtime rooted at `root_dir` (logs + pod records)."""

    # Containers run with host networking: servers they start listen on
    # the host's loopback, so the kubelet reports that as the pod IP
    # (reference HostNetwork semantics).
    host_network_ip = "127.0.0.1"

    def __init__(self, root_dir: str, node_name: str = ""):
        self.root = root_dir
        self.node_name = node_name
        os.makedirs(os.path.join(self.root, "pods"), exist_ok=True)
        self._lock = threading.RLock()
        self._pods: Dict[str, Dict[str, _Proc]] = {}
        self._anchors: Dict[str, _Proc] = {}
        # Per-NAMESPACE service env injected into containers (the
        # kubelet keeps this current from its service informer;
        # reference: pkg/kubelet/envvars FromServices, filtered to the
        # pod's namespace by getServiceEnvVarMap). Captured at
        # container START, like the reference — service churn does not
        # restart running containers.
        self.service_env: Dict[str, Dict[str, str]] = {}
        # Cluster DNS surface (kubelet --cluster-dns/--cluster-domain;
        # the reference writes these into pod resolv.conf, here they
        # reach apps as env).
        self.cluster_dns: str = ""
        self.cluster_domain: str = "cluster.local"
        # "uid/name" -> restart count to apply at next (re)start; set
        # by restart_container, consumed by sync_pod.
        self._restart_counts: Dict[str, int] = {}
        self._adopt_existing()

    # -- anchor (pause) -----------------------------------------------

    def _pause_path(self) -> Optional[str]:
        from kubernetes_tpu import native

        path = native.pause_binary()
        if path is None:
            try:
                subprocess.run(
                    ["make", "-C", os.path.join(
                        os.path.dirname(native.__file__), "..", "..", "native"
                    ), "pause"],
                    check=True, capture_output=True,
                )
            except (OSError, subprocess.CalledProcessError):
                return None
            path = native.pause_binary()
        return path

    def _pod_dir(self, uid: str) -> str:
        return os.path.join(self.root, "pods", uid)

    # -- restart survival ---------------------------------------------

    def _record(self, uid: str, proc: _Proc) -> None:
        os.makedirs(self._pod_dir(uid), exist_ok=True)
        with open(os.path.join(self._pod_dir(uid), f"{proc.name}.json"), "w") as f:
            json.dump(
                {
                    "pid": proc.pid,
                    "hash": proc.spec_hash,
                    "name": proc.name,
                    "image": proc.image,
                    "log": proc.log_path,
                    "restartCount": proc.restart_count,
                    "anchor": proc.name == "_pause",
                },
                f,
            )

    def _adopt_existing(self) -> None:
        """Adopt recorded processes that survived a kubelet restart."""
        base = os.path.join(self.root, "pods")
        for uid in os.listdir(base):
            pod_dir = os.path.join(base, uid)
            if not os.path.isdir(pod_dir):
                continue
            for fname in os.listdir(pod_dir):
                if not fname.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(pod_dir, fname)) as f:
                        rec = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
                pid = rec.get("pid", 0)
                try:
                    os.kill(pid, 0)
                except OSError:
                    continue  # process gone; record is stale
                proc = _Proc(
                    pid=pid,
                    popen=None,
                    spec_hash=rec.get("hash", ""),
                    name=rec.get("name", ""),
                    image=rec.get("image", ""),
                    log_path=rec.get("log", ""),
                    restart_count=rec.get("restartCount", 0),
                    started_at=time.monotonic(),
                )
                if rec.get("anchor"):
                    self._anchors[uid] = proc
                else:
                    self._pods.setdefault(uid, {})[proc.name] = proc

    # -- process management -------------------------------------------

    def _start_anchor(self, uid: str) -> None:
        if uid in self._anchors and self._anchors[uid].poll() is None:
            return
        pause = self._pause_path()
        log = os.path.join(self._pod_dir(uid), "_pause.log")
        os.makedirs(self._pod_dir(uid), exist_ok=True)
        if pause is None:
            # Toolchain-less fallback: python as the anchor.
            import sys

            argv = [sys.executable, "-c", "import signal;signal.pause()"]
        else:
            argv = [pause]
        with open(log, "ab") as lf:
            popen = subprocess.Popen(
                argv,
                stdout=lf,
                stderr=lf,
                start_new_session=True,  # pod = its own process group
            )
        proc = _Proc(
            pid=popen.pid,
            popen=popen,
            spec_hash="anchor",
            name="_pause",
            image="pause",
            log_path=log,
            started_at=time.monotonic(),
        )
        self._anchors[uid] = proc
        self._record(uid, proc)

    def _container_argv(self, spec) -> List[str]:
        if spec.command:
            return list(spec.command) + list(spec.args)
        if spec.args:
            # Image entrypoint unknown in a process runtime; args alone
            # are run through the shell for convenience.
            return ["/bin/sh", "-c", " ".join(spec.args)]
        pause = self._pause_path()
        if pause is not None:
            return [pause]
        import sys

        return [sys.executable, "-c", "import signal;signal.pause()"]

    #: Accelerator/runtime plumbing that must NOT leak into pods. A
    #: workload process inheriting the node's TPU attachment env dials
    #: the device tunnel at interpreter start (this box's sitecustomize
    #: gates on PALLAS_AXON_POOL_IPS) and stalls ~30s contending with
    #: the solver for the chip — the process-runtime analog of
    #: containers not inheriting the kubelet's device handles.
    _HOST_ONLY_ENV = (
        "PALLAS_AXON_POOL_IPS",
        "JAX_PLATFORMS",
        "XLA_FLAGS",
        "TPU_WORKER_HOSTNAMES",
    )

    def _env_for(self, pod: Pod, spec) -> Dict[str, str]:
        env = dict(os.environ)
        for k in self._HOST_ONLY_ENV:
            env.pop(k, None)
        # Service discovery env first (envvars.go FromServices; the
        # POD'S NAMESPACE only), then pod identity, then the
        # container's OWN env — user-declared variables win.
        env.update(
            self.service_env.get(pod.metadata.namespace or "default", {})
        )
        env["KUBERNETES_POD_NAME"] = pod.metadata.name
        env["KUBERNETES_POD_NAMESPACE"] = pod.metadata.namespace or "default"
        env["KUBERNETES_CONTAINER_NAME"] = spec.name
        if self.node_name:
            env["KUBERNETES_NODE_NAME"] = self.node_name
        if self.cluster_dns:
            env["KUBERNETES_CLUSTER_DNS"] = self.cluster_dns
            env["KUBERNETES_CLUSTER_DOMAIN"] = self.cluster_domain
        # Where this pod's mounted volumes live (host-network process
        # runtime: volumes are directories under the kubelet root,
        # <volumes-dir>/<escaped-plugin>/<volume-name>).
        uid = pod.metadata.uid or pod.metadata.name
        env["KUBERNETES_VOLUMES_DIR"] = os.path.join(
            self.root, "pods", uid, "volumes"
        )
        for e in spec.env:
            env[e.name] = e.value
        return env

    @staticmethod
    def _run_as(spec) -> Dict[str, int]:
        """SecurityContext -> Popen credential kwargs (the reference's
        securitycontext provider maps the same field onto the docker
        HostConfig User, pkg/securitycontext/provider.go). Privileged
        and capabilities have no process-level analog here; the
        SecurityContextDeny admission plugin polices them upstream."""
        ctx = getattr(spec, "security_context", None)
        if ctx is None or ctx.run_as_user is None:
            return {}
        return {
            "user": int(ctx.run_as_user),
            "group": int(ctx.run_as_user),
            "extra_groups": [],
        }

    def _start_container(
        self, pod: Pod, uid: str, spec, restart_count: int
    ) -> _Proc:
        log = os.path.join(self._pod_dir(uid), f"{spec.name}.log")
        os.makedirs(self._pod_dir(uid), exist_ok=True)
        argv = self._container_argv(spec)
        with open(log, "ab") as lf:
            try:
                popen = subprocess.Popen(
                    argv,
                    stdout=lf,
                    stderr=lf,
                    env=self._env_for(pod, spec),
                    cwd=spec.working_dir or None,
                    start_new_session=True,
                    **self._run_as(spec),
                )
            except OSError as e:
                # Start failure = immediately-exited container (the
                # reference surfaces docker run errors the same way).
                lf.write(f"start error: {e}\n".encode())
                proc = _Proc(
                    pid=0,
                    popen=None,
                    spec_hash=_spec_hash(spec),
                    name=spec.name,
                    image=spec.image,
                    log_path=log,
                    restart_count=restart_count,
                    started_at=time.monotonic(),
                    exit_code=127,
                )
                return proc
        proc = _Proc(
            pid=popen.pid,
            popen=popen,
            spec_hash=_spec_hash(spec),
            name=spec.name,
            image=spec.image,
            log_path=log,
            restart_count=restart_count,
            started_at=time.monotonic(),
        )
        self._record(uid, proc)
        return proc

    @staticmethod
    def _kill_proc(proc: _Proc, grace: float = 0.5) -> None:
        if proc.poll() is not None or proc.pid <= 0:
            return
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except OSError:
            try:
                os.kill(proc.pid, signal.SIGTERM)
            except OSError:
                return
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                break
            # Adopted processes have no popen to reap; poll via kill(0).
            if proc.popen is None:
                try:
                    os.kill(proc.pid, 0)
                except OSError:
                    break
            time.sleep(0.02)
        else:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
        if proc.popen is not None:
            try:
                proc.popen.wait(timeout=1)
            except subprocess.TimeoutExpired:
                pass

    # -- ContainerRuntime ---------------------------------------------

    def _to_rc(self, proc: _Proc) -> RuntimeContainer:
        rc = proc.poll()
        return RuntimeContainer(
            name=proc.name,
            image=proc.image,
            container_id=f"proc://{proc.pid}",
            state="running" if rc is None else "exited",
            exit_code=rc or 0,
            restart_count=proc.restart_count,
            started_at=proc.started_at,
        )

    def sync_pod(self, pod: Pod) -> List[RuntimeContainer]:
        uid = pod.metadata.uid or pod.metadata.name
        with self._lock:
            self._start_anchor(uid)
            containers = self._pods.setdefault(uid, {})
            desired = {c.name: c for c in pod.spec.containers}
            for name in list(containers):
                if name not in desired:
                    self._kill_proc(containers[name])
                    self._remove_record(uid, name)
                    del containers[name]
            for name, spec in desired.items():
                cur = containers.get(name)
                if cur is None:
                    count = self._restart_counts.pop(f"{uid}/{name}", 0)
                    containers[name] = self._start_container(
                        pod, uid, spec, count
                    )
                elif cur.spec_hash != _spec_hash(spec):
                    # Spec changed: kill + recreate (hash check,
                    # manager.go computePodContainerChanges).
                    self._kill_proc(cur)
                    containers[name] = self._start_container(
                        pod, uid, spec, cur.restart_count + 1
                    )
            return [self._to_rc(p) for p in containers.values()]

    def restart_container(self, pod_uid: str, name: str) -> None:
        with self._lock:
            cur = self._pods.get(pod_uid, {}).get(name)
            if cur is None or cur.poll() is None:
                return  # still running
            # Restart with the same argv: re-spawn from the recorded
            # spec is impossible without the Pod, so the kubelet calls
            # sync_pod right after; we just clear the exited process so
            # the next sync recreates it with restart_count + 1.
            self._kill_proc(cur)
            self._remove_record(pod_uid, name)
            del self._pods[pod_uid][name]
            self._restart_counts[f"{pod_uid}/{name}"] = cur.restart_count + 1

    def kill_pod(self, pod_uid: str) -> None:
        # Detach under the lock, kill OUTSIDE it: _kill_proc waits up
        # to the grace period per process, and holding the runtime-wide
        # lock through that would stall every other pod's sync and the
        # kubelet HTTP endpoints.
        with self._lock:
            doomed = list(self._pods.pop(pod_uid, {}).values())
            anchor = self._anchors.pop(pod_uid, None)
            if anchor is not None:
                doomed.append(anchor)
            # Drop queued restart counts: a later pod reusing this key
            # (manifest pods key by name) must start from 0.
            prefix = pod_uid + "/"
            for key in [k for k in self._restart_counts if k.startswith(prefix)]:
                del self._restart_counts[key]
        for proc in doomed:
            self._kill_proc(proc)
        shutil.rmtree(self._pod_dir(pod_uid), ignore_errors=True)

    def list_pods(self) -> Dict[str, List[RuntimeContainer]]:
        with self._lock:
            out = {
                uid: [self._to_rc(p) for p in cs.values()]
                for uid, cs in self._pods.items()
            }
            for uid, anchor in self._anchors.items():
                out.setdefault(uid, [])
            return out

    def exec_probe(
        self, pod: Pod, container: str, command: List[str], timeout: float = 1.0
    ) -> bool:
        rc, _ = self.exec_in_container(
            pod.metadata.uid or pod.metadata.name, container, command,
            pod=pod, timeout=timeout,
        )
        return rc == 0

    # -- kubelet-API surface (logs / exec / run) ----------------------

    def exec_in_container(
        self,
        pod_uid: str,
        container: str,
        command: List[str],
        pod: Optional[Pod] = None,
        timeout: float = 10.0,
    ) -> Tuple[int, str]:
        """Run a command in the container's context (env, cwd). The
        reference execs inside the container's namespaces
        (pkg/kubelet/server.go /exec); a process runtime's context is
        the container's environment."""
        with self._lock:
            proc = self._pods.get(pod_uid, {}).get(container)
        spec = None
        if pod is not None:
            spec = next(
                (c for c in pod.spec.containers if c.name == container), None
            )
        if pod is not None and spec is not None:
            env = self._env_for(pod, spec)  # full container env
        else:
            env = dict(os.environ)
            env["KUBERNETES_CONTAINER_NAME"] = container
            if pod is not None:
                env["KUBERNETES_POD_NAME"] = pod.metadata.name
                env["KUBERNETES_POD_NAMESPACE"] = (
                    pod.metadata.namespace or "default"
                )
        if proc is not None:
            env["KUBERNETES_CONTAINER_PID"] = str(proc.pid)
        try:
            done = subprocess.run(
                command,
                capture_output=True,
                env=env,
                cwd=(spec.working_dir or None) if spec is not None else None,
                timeout=timeout,
                text=True,
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            return 127, str(e)
        return done.returncode, done.stdout + done.stderr

    def read_logs(
        self, pod_uid: str, container: str, tail_lines: Optional[int] = None
    ) -> str:
        with self._lock:
            proc = self._pods.get(pod_uid, {}).get(container)
        path = (
            proc.log_path
            if proc is not None
            else os.path.join(self._pod_dir(pod_uid), f"{container}.log")
        )
        try:
            with open(path, "r", errors="replace") as f:
                data = f.read()
        except OSError:
            return ""
        if tail_lines is not None and tail_lines >= 0:
            if tail_lines == 0:
                return ""  # kubectl --tail=0: suppress output
            lines = data.splitlines(keepends=True)
            data = "".join(lines[-tail_lines:])
        return data

    def fail_container(self, pod_uid: str, name: str, exit_code: int = 137) -> None:
        """Kill one container's process (liveness-probe kill path; the
        restart-policy sync brings it back)."""
        with self._lock:
            cur = self._pods.get(pod_uid, {}).get(name)
            if cur is not None:
                self._kill_proc(cur)

    # -- helpers ------------------------------------------------------

    def _remove_record(self, uid: str, name: str) -> None:
        try:
            os.unlink(os.path.join(self._pod_dir(uid), f"{name}.json"))
        except OSError:
            pass

    def anchor_pid(self, pod_uid: str) -> Optional[int]:
        with self._lock:
            anchor = self._anchors.get(pod_uid)
            return anchor.pid if anchor is not None else None
