"""The kubelet daemon.

Reference: pkg/kubelet/kubelet.go (syncLoop :1657, syncPod :1092),
pod_workers.go (per-pod serialized workers), status_manager.go
(apiserver writeback), prober (liveness/readiness), and node
registration/heartbeats (cmd/kubelet/app/server.go + NodeStatus).

Sources of truth:
- apiserver watch filtered to spec.nodeName == this node (the
  reference's apiserver source, pkg/kubelet/config/apiserver.go);
- optional static-pod manifest dir (file source, config/file.go) —
  mirrored to the apiserver as "<name>-<node>" pods like mirror pods.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import (
    ContainerStatus,
    Node,
    NodeAddress,
    NodeCondition,
    Pod,
    PodCondition,
    now_iso,
)
from kubernetes_tpu.models.quantity import parse_quantity
from kubernetes_tpu.kubelet.runtime import ContainerRuntime, FakeRuntime
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import faults, metrics, tracing

_LOG = logging.getLogger("kubernetes_tpu.kubelet")

# Histogram (was a summary): bucketed sync latencies aggregate across
# every kubelet in the fleet, which a per-instance summary can't.
_SYNC_LATENCY = metrics.DEFAULT.histogram(
    "kubelet_sync_pod_latency_seconds", "Pod sync latency", ("node",)
)
_PODS_RUNNING = metrics.DEFAULT.gauge(
    "kubelet_running_pods", "Pods running on this node", ("node",)
)


def _decode_pod(wire: dict) -> Pod:
    return serde.from_wire(Pod, wire)


def _proc_rss(pid: str) -> int:
    """Resident set bytes from /proc (cadvisor-stats analog)."""
    try:
        with open(f"/proc/{int(pid)}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


class _SyncPool:
    """Per-pod serialized sync over a small ELASTIC worker pool.

    The reference dedicates a goroutine per pod (pod_workers.go:91-123);
    goroutines are cheap, Python threads are not — spawning one per pod
    update was measurably expensive at 100 kubelets x 30 pods. The pool
    keeps the same contract: syncs for one pod never overlap (a pod is
    'running' while synced; updates arriving meanwhile coalesce into one
    re-run with the latest spec), different pods sync concurrently.

    Elasticity is the reference's isolation property on a budget: when
    every worker is busy (a slow volume mount, a wedged probe) and more
    work queues, transient workers spawn up to `max_workers`, then
    retire after a few idle seconds — so two stuck pods can't starve
    the other 28 on the node, without carrying a thread per pod."""

    def __init__(self, sync_fn, workers: int = 2, max_workers: int = 16):
        import queue

        from kubernetes_tpu.utils import sanitizer

        self._sync = sync_fn
        self._q: "queue.Queue[Optional[str]]" = queue.Queue()
        self._lock = sanitizer.lock("kubelet.syncpool")
        self._pending: Dict[str, Pod] = {}  # key -> latest un-synced spec
        self._running: set = set()  # keys currently inside sync_fn
        self._max = max_workers
        self._nworkers = 0
        self._idle = 0
        self._stopping = False
        for _ in range(workers):
            self._spawn_locked(transient=False)

    def _spawn_locked(self, transient: bool) -> None:
        # caller holds self._lock (or init, pre-concurrency)
        self._nworkers += 1
        threading.Thread(
            target=self._worker, args=(transient,), daemon=True
        ).start()

    def update(self, key: str, pod: Pod) -> None:
        with self._lock:
            if self._stopping:
                return
            queued = key in self._pending
            self._pending[key] = pod
            if queued or key in self._running:
                return  # will be picked up by the queued entry / re-run
            if self._idle == 0 and self._nworkers < self._max:
                self._spawn_locked(transient=True)
            # Enqueue UNDER the lock: a timing-out transient worker's
            # retire path checks queue emptiness under this same lock,
            # so a key can never land unseen between its last check and
            # its exit (which would strand the pod until some other
            # pod's update spawned a worker).
            self._q.put(key)

    def forget(self, key: str) -> None:
        with self._lock:
            self._pending.pop(key, None)

    def _worker(self, transient: bool) -> None:
        import queue

        while True:
            with self._lock:
                self._idle += 1
            try:
                key = self._q.get(timeout=5.0 if transient else None)
            except queue.Empty:
                # Idle timeout: retire — unless work raced in (update()
                # enqueues under the same lock, so this check is
                # ordered against every put).
                with self._lock:
                    self._idle -= 1
                    if not self._q.empty():
                        continue
                    self._nworkers -= 1
                return
            with self._lock:
                self._idle -= 1
            if key is None:
                with self._lock:
                    self._nworkers -= 1
                return
            with self._lock:
                if key in self._running:
                    # Owned by another worker (duplicate token: forget()
                    # dropped the pending entry, then update() re-enqueued
                    # the same key). Leave _pending intact — the owner's
                    # finally-path sees it and requeues, preserving the
                    # 'syncs for one pod never overlap' contract.
                    pod = None
                else:
                    pod = self._pending.pop(key, None)
                    if pod is not None:
                        self._running.add(key)
            if pod is None:
                continue
            try:
                self._sync(pod)
            except Exception:
                # Crash containment (util.HandleCrash) — with evidence.
                _LOG.exception("pod sync for %s crashed", key)
            finally:
                with self._lock:
                    self._running.discard(key)
                    requeue = key in self._pending
                if requeue:
                    self._q.put(key)

    def stop(self) -> None:
        with self._lock:
            self._stopping = True
            n = self._nworkers
        for _ in range(n):
            self._q.put(None)


class Kubelet:
    def __init__(
        self,
        client,
        node_name: str,
        runtime: Optional[ContainerRuntime] = None,
        cpu: str = "4",
        memory: str = "8Gi",
        max_pods: int = 110,
        labels: Optional[Dict[str, str]] = None,
        heartbeat_period: float = 5.0,
        sync_period: float = 3.0,
        manifest_dir: Optional[str] = None,
        manifest_url: Optional[str] = None,
        root_dir: Optional[str] = None,
        mounter=None,
        serve_http: bool = False,
        http_port: int = 0,
    ):
        self.client = client
        self.node_name = node_name
        self.runtime = runtime or FakeRuntime()
        # HTTP API (reference kubelet port 10250, pkg/kubelet/server.go).
        self.http: Optional[object] = None
        self._serve_http = serve_http
        self._http_port = http_port
        # Volume subsystem: active when a root dir is configured
        # (reference: kubelet --root-dir, default /var/lib/kubelet).
        self.volumes = None
        if root_dir:
            from kubernetes_tpu.volumes import VolumeHost, VolumePluginManager

            self.volumes = VolumePluginManager(
                VolumeHost(
                    root_dir=root_dir,
                    client=client,
                    mounter=mounter,
                    node_name=node_name,
                )
            )
        self.cpu = cpu
        self.memory = memory
        self.max_pods = max_pods
        self.labels = labels or {}
        self.heartbeat_period = heartbeat_period
        self.sync_period = sync_period
        self.manifest_dir = manifest_dir
        self.manifest_url = manifest_url
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._sync_pool = _SyncPool(self._sync_pod, workers=2)
        # Terminating pods this kubelet has acknowledged (uid -> True
        # once the Killing event went out): dedup so the grace window's
        # repeated syncs emit one event, not one per resync tick.
        self._terminating: Dict[str, bool] = {}
        # Last status wire-form successfully WRITTEN per pod uid (the
        # reference's status_manager.go map). Dedup must compare
        # against what we know reached the apiserver — comparing
        # against a locally mutated pod object let one failed write
        # (409 during the bind/status race) suppress every retry.
        self._last_status: Dict[str, dict] = {}
        self._hb_node: Optional[Node] = None  # cached across heartbeats
        self._volumes_mounted: set = set()
        from kubernetes_tpu.kubelet.probes import ProbeTracker

        self._probes = ProbeTracker()
        # Resource managers (container GC / disk / OOM watcher —
        # pkg/kubelet/{container_gc,image_manager,disk_manager,
        # oom_watcher}.go). GC and disk need an artifact root, which
        # only real runtimes have (ProcessRuntime.root).
        from kubernetes_tpu.kubelet.managers import (
            ContainerGC,
            DiskManager,
            OOMWatcher,
        )

        self._oom = OOMWatcher(client, node_name)
        self.disk = None
        self.container_gc = None
        self.image_manager = None
        runtime_root = getattr(self.runtime, "root", None)
        if runtime_root:
            self.disk = DiskManager(runtime_root)
            self.container_gc = ContainerGC(
                runtime_root,
                self.runtime,
                min_age_s=30.0,
                disk=self.disk,
                desired_uids=self._desired_uids,
            )
        # Image GC needs an image substrate, which only runtimes with a
        # store carry (SandboxRuntime.images; reference:
        # image_manager.go against docker's image list).
        if getattr(self.runtime, "images", None) is not None:
            from kubernetes_tpu.kubelet.managers import ImageManager

            self.image_manager = ImageManager(
                self.runtime.images,
                high_bytes=256 * 1024 * 1024,
                low_bytes=192 * 1024 * 1024,
            )
        self.housekeeping_period = 10.0
        self.pods = Informer(
            client,
            "pods",
            field_selector=f"spec.nodeName={node_name}",
            decode=_decode_pod,
            on_add=self._dispatch,
            on_update=self._dispatch,
            on_delete=self._handle_delete,
        )
        # Service informer feeding service-discovery env vars into
        # containers (reference: kubelet.go makeEnvironmentVariables +
        # pkg/kubelet/envvars). Only runtimes that inject env carry the
        # attribute (ProcessRuntime.service_env).
        self.services: Optional[Informer] = None
        if hasattr(self.runtime, "service_env"):
            from kubernetes_tpu.models.objects import Service

            self.services = Informer(
                client,
                "services",
                decode=lambda w: serde.from_wire(Service, w),
                on_add=self._services_changed,
                on_update=self._services_changed,
                on_delete=self._services_changed,
            )

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "Kubelet":
        if self._serve_http:
            from kubernetes_tpu.kubelet.server import KubeletServer

            self.http = KubeletServer(self, port=self._http_port).start()
        self.register_node()
        if self.services is not None:
            self.services.start()
            self.services.wait_for_sync()
            self._services_changed(None)
        self.pods.start()
        self.pods.wait_for_sync()
        targets = [self._heartbeat_loop, self._resync_loop]
        if self.container_gc is not None:
            targets.append(self._housekeeping_loop)
        for target in targets:
            t = threading.Thread(target=target, daemon=True)
            t.start()
            self._threads.append(t)
        if self.manifest_dir:
            t = threading.Thread(target=self._manifest_loop, daemon=True)
            t.start()
            self._threads.append(t)
        if self.manifest_url:
            t = threading.Thread(target=self._manifest_url_loop, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        self._sync_pool.stop()
        self.pods.stop()
        if self.services is not None:
            self.services.stop()
        if self.http is not None:
            self.http.stop()
        for t in self._threads:
            t.join(timeout=2)

    # -- node registration + heartbeat (NodeStatus) -------------------

    def _fill_status(self, node: Node) -> None:
        node.status.conditions = [self._ready_condition()]
        node.status.capacity = {
            "cpu": parse_quantity(self.cpu),
            "memory": parse_quantity(self.memory),
            "pods": parse_quantity(str(self.max_pods)),
        }
        node.status.addresses = [
            NodeAddress(type="InternalIP", address="127.0.0.1")
        ]
        if self.http is not None:
            node.status.daemon_endpoints.kubelet_endpoint.port = self.http.port

    def register_node(self) -> None:
        node = Node()
        node.metadata.name = self.node_name
        node.metadata.labels = dict(self.labels)
        self._fill_status(node)
        try:
            self.client.create("nodes", node)
        except APIError as e:
            if e.code != 409:
                raise
            self._heartbeat()  # already registered: refresh status

    def _ready_condition(self) -> NodeCondition:
        return NodeCondition(
            type="Ready",
            status="True",
            last_heartbeat_time=now_iso(),
            reason="KubeletReady",
            message="kubelet is posting ready status",
        )

    def _heartbeat(self) -> None:
        # One RPC per beat, not two: status PUTs are server-side
        # read-modify-writes (no client resourceVersion CAS), so the
        # node object from the last beat is reusable — the GET is only
        # needed on the first beat or after an error (node deleted /
        # apiserver restarted). At 100 kubelets the get+put pair doubled
        # heartbeat traffic exactly when delayed beats read as death.
        if faults.enabled() and faults.fire(
            faults.KUBELET_HEARTBEAT_DROP, self.node_name
        ):
            return  # chaos seam: a lost beat, not a dead kubelet
        node = self._hb_node
        if node is None:
            try:
                node = self.client.get("nodes", self.node_name)
            except APIError:
                self.register_node()
                return
        self._fill_status(node)
        try:
            self._hb_node = self.client.update_status("nodes", node)
        except APIError:
            self._hb_node = None  # refetch (or re-register) next beat

    def _heartbeat_loop(self) -> None:
        # Phase jitter: a fleet of kubelets started together would
        # otherwise beat in lockstep — at 1000 nodes the synchronized
        # herd of status PUTs convoys on the apiserver (the reference
        # spreads --node-status-update-frequency load the same way).
        import random as _random

        if self._stop.wait(_random.uniform(0, self.heartbeat_period)):
            return
        try:
            self._heartbeat()
        except Exception:
            _LOG.debug("node heartbeat failed; retrying", exc_info=True)
        while not self._stop.wait(self.heartbeat_period):
            try:
                self._heartbeat()
            except Exception:
                _LOG.debug("node heartbeat failed; retrying", exc_info=True)

    def _services_changed(self, _obj) -> None:
        """Recompute the runtime's PER-NAMESPACE service env maps
        (captured by containers at START; churn never restarts running
        ones). Namespaced like the reference (getServiceEnvVarMap
        filters to the pod's namespace) — one global map would leak
        env vars across namespaces and let same-named services in
        different namespaces clobber each other."""
        from kubernetes_tpu.kubelet.envvars import from_services

        try:
            by_ns: Dict[str, list] = {}
            for svc in self.services.store.list():
                by_ns.setdefault(
                    svc.metadata.namespace or "default", []
                ).append(svc)
            self.runtime.service_env = {
                ns: from_services(svcs) for ns, svcs in by_ns.items()
            }
        except Exception:
            # No retry can fix a deterministic recompute bug — at least
            # make it visible instead of freezing env at a stale value.
            import traceback

            print(
                f"kubelet {self.node_name}: service env recompute failed:",
                file=sys.stderr,
            )
            traceback.print_exc()

    def _desired_uids(self) -> set:
        return {
            p.metadata.uid or p.metadata.name for p in self.pods.store.list()
        }

    def _housekeeping_loop(self) -> None:
        """Container GC + image GC + disk reclaim + OOM-dedup prune."""
        while not self._stop.wait(self.housekeeping_period):
            try:
                self.container_gc.gc()
                if self.image_manager is not None:
                    in_use = {
                        c.image
                        for cs in self.runtime.list_pods().values()
                        for c in cs
                    }
                    self.image_manager.gc(in_use)
                self._oom.prune(self.runtime.list_pods())
            except Exception:
                _LOG.exception("housekeeping pass failed")

    # -- HTTP API data (reference /spec + /stats, cadvisor-backed) ----

    def node_spec(self) -> dict:
        """Machine spec (reference GET /spec/, cadvisor MachineInfo)."""
        return {
            "nodeName": self.node_name,
            "capacity": {
                "cpu": self.cpu,
                "memory": self.memory,
                "pods": str(self.max_pods),
            },
            "labels": dict(self.labels),
        }

    def node_stats(self) -> dict:
        """Node + per-pod container stats (reference GET /stats/...;
        process runtimes report real RSS from /proc)."""
        pods = {}
        for uid, containers in self.runtime.list_pods().items():
            stats = []
            for c in containers:
                entry = {
                    "name": c.name,
                    "state": c.state,
                    "restartCount": c.restart_count,
                    "uptimeSeconds": round(
                        max(0.0, time.monotonic() - c.started_at), 3
                    ),
                }
                if c.container_id.startswith("proc://"):
                    entry["rssBytes"] = _proc_rss(c.container_id[7:])
                stats.append(entry)
            pods[uid] = stats
        out = {"nodeName": self.node_name, "pods": pods}
        if self.disk is not None:
            usage = self.disk.usage()
            out["disk"] = {
                "capacityBytes": usage.capacity_bytes,
                "availableBytes": usage.available_bytes,
                "usedFraction": round(usage.used_fraction, 4),
            }
        return out

    # -- pod sync -----------------------------------------------------

    def _key(self, pod: Pod) -> str:
        return f"{pod.metadata.namespace}/{pod.metadata.name}"

    def _dispatch(self, pod: Pod) -> None:
        self._sync_pool.update(self._key(pod), pod)

    def _handle_delete(self, pod: Pod) -> None:
        uid = pod.metadata.uid or pod.metadata.name
        self.runtime.kill_pod(uid)
        if self.volumes is not None:
            try:
                self.volumes.teardown_pod_volumes(uid)
            except Exception:
                # Retried by the resync tick's orphan GC (the uid is no
                # longer desired, and on-disk volume dirs re-surface it
                # via volumes.list_pod_uids) — but a teardown that keeps
                # failing must be visible, not silent.
                _LOG.exception("volume teardown for pod %s failed", uid)
        self._volumes_mounted.discard(uid)
        self._probes.forget(uid + "/")
        self._last_status.pop(uid, None)
        self._terminating.pop(uid, None)
        self._sync_pool.forget(self._key(pod))

    def _resync_loop(self) -> None:
        """Periodic full resync + orphan GC (syncLoop tick). Initial
        phase jitter: see _heartbeat_loop."""
        import random as _random

        if self._stop.wait(_random.uniform(0, self.sync_period)):
            return
        while not self._stop.wait(self.sync_period):
            try:
                pods = self.pods.store.list()
                known_uids = set()
                for pod in pods:
                    known_uids.add(pod.metadata.uid or pod.metadata.name)
                    self._dispatch(pod)
                # Orphan GC over the UNION of runtime pods and on-disk
                # volume dirs: after a kubelet restart the runtime may
                # have forgotten a pod whose volumes still exist.
                orphans = set(self.runtime.list_pods())
                if self.volumes is not None:
                    orphans.update(self.volumes.list_pod_uids())
                for uid in orphans - known_uids:
                    try:
                        self.runtime.kill_pod(uid)
                        if self.volumes is not None:
                            self.volumes.teardown_pod_volumes(uid)
                    except Exception:
                        # One bad orphan must not stall the tick — but
                        # a teardown that fails every pass (wedged
                        # mount, permission rot) needs evidence, not
                        # silence; the next tick retries it anyway.
                        _LOG.exception("orphan teardown for %s failed", uid)
                    self._volumes_mounted.discard(uid)
                _PODS_RUNNING.set(len(pods), node=self.node_name)
            except Exception:
                _LOG.exception("pod resync tick failed")

    def _sync_pod(self, pod: Pod) -> None:
        """One reconciliation of a single pod (kubelet.go:1092), under
        a sync-loop trace so a pod's kubelet-side story lands in the
        same /debug/traces surface as its scheduling."""
        # record_threshold_s: a no-op resync sync (fake runtimes,
        # already-converged pods) finishes in microseconds and runs for
        # EVERY pod EVERY tick — recording those would flood the shared
        # trace ring and evict the scheduling traces. Syncs that did
        # real work (mounts, container starts, status writes) clear
        # 10ms easily and are kept.
        with tracing.trace(
            "kubelet_sync_pod", pod=pod.metadata.name,
            record_threshold_s=0.01,
        ) as sp:
            sp.note(node=self.node_name)
            self._sync_pod_inner(pod)

    @staticmethod
    def _deletion_deadline(pod: Pod) -> Optional[float]:
        """Epoch seconds of the graceful-delete deadline (the apiserver
        stamps deletionTimestamp = delete time + grace)."""
        import calendar

        ts = pod.metadata.deletion_timestamp
        if not ts:
            return None
        try:
            return calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
        except ValueError:
            return 0.0  # unparseable stamp: treat as already expired

    def _sync_terminating(self, pod: Pod) -> None:
        """Graceful termination (reference: killPod with grace →
        status-manager force delete). The pod stays Terminating —
        containers running, capacity charged — until the stamped
        deadline, then this kubelet kills it and confirms with a
        grace-0 delete so watchers see exactly one DELETED."""
        uid = pod.metadata.uid or pod.metadata.name
        # Chaos seam: the confirm path stalls (wedged volume teardown,
        # slow runtime kill) — grace handling and the exactly-one-
        # DELETED contract must survive the lag, not race it.
        faults.fire(faults.KUBELET_TERMINATING_STALL, uid)
        if not self._terminating.get(uid):
            self._terminating[uid] = True
            try:
                self.client.record_event(
                    pod, "Killing",
                    f"Stopping pod {pod.metadata.name} "
                    f"(grace {pod.metadata.deletion_grace_period_seconds or 0}s)",
                    source=f"kubelet/{self.node_name}",
                )
            except Exception:
                _LOG.exception("Killing event for %s failed to record", uid)
        deadline = self._deletion_deadline(pod)
        if deadline is not None and time.time() < deadline:
            return  # grace still running; the resync tick re-checks
        self.runtime.kill_pod(uid)
        if self.volumes is not None:
            try:
                self.volumes.teardown_pod_volumes(uid)
            except Exception:
                _LOG.exception("volume teardown for pod %s failed", uid)
        self._volumes_mounted.discard(uid)
        try:
            self.client.delete(
                "pods", pod.metadata.name,
                namespace=pod.metadata.namespace or "default",
                grace_period_seconds=0,
            )
        except APIError as e:
            if e.code != 404:  # already gone is success
                _LOG.warning(
                    "force delete of terminated pod %s failed: %s", uid, e
                )

    def _sync_pod_inner(self, pod: Pod) -> None:
        import copy as _copy

        start = time.monotonic()
        if pod.metadata.deletion_timestamp:
            self._sync_terminating(pod)
            return
        if pod.status.phase in ("Succeeded", "Failed"):
            return
        uid = pod.metadata.uid or pod.metadata.name
        # Work on a private status: the incoming pod is the informer
        # store's own object (server state) and must not carry local
        # mutations — a locally flipped phase would poison both the
        # terminal-phase early-return above and status dedup below.
        pod = _copy.copy(pod)
        pod.status = _copy.deepcopy(pod.status)

        # Volumes first (kubelet.go:1135 mountExternalVolumes): a pod
        # whose volumes can't materialize must not start containers.
        # Mounted once per pod instance — re-running every resync tick
        # would hammer the apiserver (secret/claim GETs) and rewrite
        # secret files non-atomically under running containers.
        if (
            self.volumes is not None
            and pod.spec.volumes
            and uid not in self._volumes_mounted
        ):
            try:
                self.volumes.mount_pod_volumes(pod)
            except Exception:
                _LOG.exception("volume mount for pod %s failed", uid)
                return  # retried by the resync tick
            self._volumes_mounted.add(uid)

        # Probes may demand restarts before the runtime sync.
        with tracing.span("probes"):
            self._run_probes(pod, uid)

        with tracing.span("runtime_sync"):
            containers = self.runtime.sync_pod(pod)
        for c in containers:
            self._probes.note_started(f"{uid}/{c.name}", c.started_at)
        self._oom.observe(pod, containers)

        # Restart policy (dockertools/manager.go:1287+), decided PER
        # CONTAINER: Always restarts any exited container; OnFailure
        # only those that exited nonzero (a completed exit-0 workload
        # container must stay completed).
        policy = pod.spec.restart_policy
        restarted = False
        for c in containers:
            if c.state != "exited":
                continue
            if policy == "Always" or (policy == "OnFailure" and c.exit_code != 0):
                self.runtime.restart_container(uid, c.name)
                restarted = True
        if restarted:
            containers = self.runtime.sync_pod(pod)  # refresh statuses

        phase = self._pod_phase(pod, containers)
        statuses = [
            ContainerStatus(
                name=c.name,
                state={c.state: {}},
                ready=self._container_ready(uid, c.name, c.state),
                restart_count=c.restart_count,
                image=c.image,
                container_id=c.container_id,
            )
            for c in containers
        ]
        ready = all(s.ready for s in statuses) and bool(statuses)
        old_wire = serde.to_wire(pod.status)
        pod.status.phase = phase
        pod.status.host_ip = "127.0.0.1"
        # Host-network runtimes (ProcessRuntime) expose containers on
        # the host's own address, so that IS the pod IP — the reference
        # kubelet reports the node IP for HostNetwork pods. Sandboxed
        # fakes keep the deterministic synthetic IP.
        pod.status.pod_ip = (
            getattr(self.runtime, "host_network_ip", "") or self._pod_ip(uid)
        )
        if not pod.status.start_time:
            pod.status.start_time = now_iso()
        # Ready-transition timestamping (telemetry plane): stamp
        # lastTransitionTime when the condition FLIPS and carry the
        # prior stamp when it doesn't — the Running/Ready instant must
        # survive every later status rewrite, and re-stamping each sync
        # would defeat status dedup below (a self-sustaining write
        # loop). pod.status still holds the server's view here (the
        # private copy above), so prev_ready is the stored condition.
        ready_str = "True" if ready else "False"
        prev_ready = next(
            (c for c in pod.status.conditions or () if c.type == "Ready"),
            None,
        )
        transition = (
            prev_ready.last_transition_time
            if prev_ready is not None
            and prev_ready.status == ready_str
            and prev_ready.last_transition_time
            else now_iso()
        )
        pod.status.conditions = [
            PodCondition(
                type="Ready", status=ready_str,
                last_transition_time=transition,
            )
        ]
        pod.status.container_statuses = statuses
        # Status dedup (reference: status_manager.go) — an unchanged
        # write would bounce back through the watch and re-trigger this
        # sync, a self-sustaining hot loop. Two comparisons: against
        # the server's view (old_wire, from the informer object) and
        # against the last write KNOWN to have succeeded — a failed
        # write leaves no record, so the next resync tick retries
        # instead of silently stranding the pod at its server phase.
        new_wire = serde.to_wire(pod.status)
        if new_wire == old_wire:
            self._last_status[uid] = new_wire  # in sync with the server
        elif self._last_status.get(uid) != new_wire:
            try:
                with tracing.span("status_write"):
                    self.client.update_status(
                        "pods", pod,
                        namespace=pod.metadata.namespace or "default",
                    )
                self._last_status[uid] = new_wire
            except APIError:
                self._last_status.pop(uid, None)  # retry next resync
        _SYNC_LATENCY.observe(time.monotonic() - start, node=self.node_name)

    def _pod_ip(self, uid: str) -> str:
        # Deterministic fake pod IP from the uid (dataplane tests use it).
        h = abs(hash(uid))
        return f"10.{(h >> 16) % 256}.{(h >> 8) % 256}.{h % 254 + 1}"

    def _pod_phase(self, pod: Pod, containers) -> str:
        """Phase derivation (reference: kubelet.go GetPodStatus logic)."""
        if not containers:
            return "Pending"
        states = [c.state for c in containers]
        codes = [c.exit_code for c in containers]
        if all(s == "exited" for s in states):
            if pod.spec.restart_policy == "Never":
                return "Failed" if any(codes) else "Succeeded"
            if pod.spec.restart_policy == "OnFailure" and not any(codes):
                return "Succeeded"
        if any(s == "running" for s in states):
            return "Running"
        return "Pending"

    # -- probes -------------------------------------------------------

    def _run_probes(self, pod: Pod, uid: str) -> None:
        """Liveness + readiness probes, all three transports
        (exec/HTTP/TCP — pkg/probe/, prober/prober.go). Liveness
        failures past the threshold kill the container so restart
        policy brings it back; readiness failures only flip the
        container un-ready (and thus the pod out of Endpoints)."""
        from kubernetes_tpu.kubelet.probes import run_probe

        for c in pod.spec.containers:
            key = f"{uid}/{c.name}"
            live = c.liveness_probe
            if live is not None and not self._probes.in_initial_delay(key, live):
                healthy = run_probe(live, pod, c.name, self.runtime)
                if self._probes.liveness(key, healthy):
                    if hasattr(self.runtime, "fail_container"):
                        self.runtime.fail_container(uid, c.name, exit_code=137)
                    self.client.record_event(
                        pod, "Unhealthy",
                        f"Liveness probe failed for {c.name}; restarting",
                        source=f"kubelet/{self.node_name}",
                    )
            readiness = c.readiness_probe
            if readiness is not None:
                if self._probes.in_initial_delay(key, readiness):
                    # Not probed yet -> not ready (readiness defaults
                    # to failure until the first success).
                    if self._probes.ready(key) is None:
                        self._probes.set_ready(key, False)
                else:
                    self._probes.set_ready(
                        key, run_probe(readiness, pod, c.name, self.runtime)
                    )

    def _container_ready(self, uid: str, name: str, state: str) -> bool:
        """running AND (no readiness probe, or latest verdict true)."""
        if state != "running":
            return False
        verdict = self._probes.ready(f"{uid}/{name}")
        return True if verdict is None else verdict

    # -- static pods (file source, config/file.go) --------------------

    _STATIC_SOURCE_ANNOTATION = "kubernetes-tpu.io/static-source"

    def _apply_static(
        self, applied: Dict[str, tuple], key: str, content: str, source: str
    ) -> None:
        """Apply one static-pod manifest (by source key) as a mirror
        pod; edits replace, unchanged content no-ops, failures retry
        next tick (reference: config/{file,http}.go + mirror pods).

        Mirrors are annotated with their SOURCE: with both a manifest
        dir and a manifest URL configured, a same-named pod must not be
        cross-claimed through the 409 branch, or one source's removal
        would delete a mirror the other source then never recreates."""
        try:
            wire = json.loads(content)
        except json.JSONDecodeError:
            return
        name = wire.get("metadata", {}).get("name", "")
        if not name:
            return
        prev = applied.get(key)
        if prev is not None and prev[0] == content:
            return  # unchanged
        mirror = f"{name}-{self.node_name}"
        ns = wire.get("metadata", {}).get("namespace", "default")
        wire["metadata"]["name"] = mirror
        wire["metadata"].setdefault("annotations", {})[
            self._STATIC_SOURCE_ANNOTATION
        ] = source
        wire.setdefault("spec", {})["nodeName"] = self.node_name
        try:
            if prev is not None:
                # Edited: replace the mirror pod. The old applied entry
                # is dropped FIRST — if the new create then fails, a
                # revert to the previous content must not hit the
                # 'unchanged' early-return and strand the pod.
                applied.pop(key, None)
                try:
                    self.client.delete("pods", prev[1], namespace=prev[2])
                except APIError:
                    pass
            self.client.create("pods", wire, namespace=ns)
            applied[key] = (content, mirror, ns)
        except APIError as e:
            if e.code == 409:
                # Adopt our OWN previous mirror (kubelet restart).
                # Anything else — another source's mirror, or an
                # annotation-less user pod that happens to collide with
                # the mirror name — stays theirs: adopting it would let
                # a later manifest edit DELETE a pod we never created.
                try:
                    existing = self.client.get("pods", mirror, namespace=ns)
                    owner = (existing.metadata.annotations or {}).get(
                        self._STATIC_SOURCE_ANNOTATION
                    )
                except APIError:
                    return
                if owner == source:
                    applied[key] = (content, mirror, ns)

    def _remove_static(self, applied: Dict[str, tuple], key: str) -> None:
        _, mirror, ns = applied.pop(key)
        try:
            self.client.delete("pods", mirror, namespace=ns)
        except APIError:
            pass

    def _manifest_loop(self) -> None:
        """Static-pod file source (reference: config/file.go)."""
        applied: Dict[str, tuple] = {}
        while not self._stop.wait(2.0):
            try:
                files = {
                    f for f in os.listdir(self.manifest_dir) if f.endswith(".json")
                }
            except OSError:
                continue
            # Removed manifests: delete their mirror pods.
            for fname in list(applied):
                if fname not in files:
                    self._remove_static(applied, fname)
            for fname in sorted(files):
                path = os.path.join(self.manifest_dir, fname)
                try:
                    with open(path) as f:
                        content = f.read()
                except OSError:
                    continue
                self._apply_static(applied, fname, content, source="file")

    def _manifest_url_loop(self) -> None:
        """Static-pod URL source (reference: config/http.go — the
        kubelet polls --manifest-url for a pod manifest or a list)."""
        import urllib.error
        import urllib.request

        applied: Dict[str, tuple] = {}
        while not self._stop.wait(2.0):
            try:
                with urllib.request.urlopen(self.manifest_url, timeout=10) as r:
                    body = r.read().decode(errors="replace")
            except (urllib.error.URLError, OSError):
                continue  # unreachable: keep the last applied state
            try:
                wire = json.loads(body)
            except json.JSONDecodeError:
                continue
            # Shape-validate before acting: a parseable-but-wrong body
            # ({}, null, an error JSON) must KEEP the last good config
            # like a fetch failure does — only a well-formed Pod or
            # PodList may add/remove static pods. An explicit empty
            # PodList legitimately clears them.
            if not isinstance(wire, dict):
                continue
            if wire.get("kind", "").endswith("List"):
                docs = [d for d in wire.get("items", []) if isinstance(d, dict)]
            elif wire.get("kind") == "Pod":
                docs = [wire]
            else:
                continue
            keys = set()
            for doc in docs:
                meta = doc.get("metadata", {})
                name = meta.get("name", "")
                if not name:
                    continue
                # Namespace in the key: same-named pods in different
                # namespaces are distinct and must not thrash.
                key = f"url:{meta.get('namespace', 'default')}/{name}"
                if key in keys:
                    continue  # duplicate entry in one payload: first wins
                keys.add(key)
                self._apply_static(
                    applied, key, json.dumps(doc, sort_keys=True), source="url"
                )
            for key in list(applied):
                if key not in keys:
                    self._remove_static(applied, key)
