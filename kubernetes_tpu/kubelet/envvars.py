"""Service environment variables — pre-DNS service discovery.

Reference: pkg/kubelet/envvars/envvars.go (FromServices) — every
container gets `{SVC}_SERVICE_HOST`, `{SVC}_SERVICE_PORT`, named-port
variants, and the docker-link-compatible `{SVC}_PORT_*` family for each
service with a cluster IP. Naming matches the reference exactly
(upper-case, '-' -> '_').
"""

from __future__ import annotations

from typing import Dict, List


def _env_name(name: str) -> str:
    return name.upper().replace("-", "_")


def from_services(services: List) -> Dict[str, str]:
    """Service env map in reference order (later services override on
    name collision, like repeated docker -e flags)."""
    out: Dict[str, str] = {}
    for svc in services:
        ip = svc.spec.cluster_ip
        if not ip or ip == "None" or not svc.spec.ports:
            continue
        prefix = _env_name(svc.metadata.name)
        first = svc.spec.ports[0]
        out[f"{prefix}_SERVICE_HOST"] = ip
        out[f"{prefix}_SERVICE_PORT"] = str(first.port)
        for sp in svc.spec.ports:
            if sp.name:
                out[f"{prefix}_SERVICE_PORT_{_env_name(sp.name)}"] = str(sp.port)
        # Docker-compatible link variables (makeLinkVariables).
        for i, sp in enumerate(svc.spec.ports):
            protocol = (sp.protocol or "TCP").upper()
            url = f"{protocol.lower()}://{ip}:{sp.port}"
            if i == 0:
                out[f"{prefix}_PORT"] = url
            pp = f"{prefix}_PORT_{sp.port}_{protocol}"
            out[pp] = url
            out[f"{pp}_PROTO"] = protocol.lower()
            out[f"{pp}_PORT"] = str(sp.port)
            out[f"{pp}_ADDR"] = ip
    return out
