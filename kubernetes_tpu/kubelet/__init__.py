"""Node agent (kubelet equivalent).

Reference: pkg/kubelet/. Watches the apiserver for pods assigned to its
node, drives a pluggable container runtime to match desired state,
writes status back, heartbeats NodeStatus, and runs liveness/readiness
probes. The runtime abstraction mirrors pkg/kubelet/container/runtime.go
with a fake implementation (the reference's own integration strategy:
cmd/integration runs kubelets with FakeDockerClient).
"""

from kubernetes_tpu.kubelet.runtime import ContainerRuntime, FakeRuntime, RuntimeContainer
from kubernetes_tpu.kubelet.agent import Kubelet

__all__ = ["ContainerRuntime", "FakeRuntime", "RuntimeContainer", "Kubelet"]
