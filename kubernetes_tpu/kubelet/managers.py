"""Kubelet resource managers: container GC, disk manager, OOM watcher.

Reference:
- pkg/kubelet/container_gc.go — dead-container artifacts are reaped by
  age/count policy so a busy node doesn't fill its disk with corpses.
  Process-runtime analog: per-container log files and terminal pod
  directories under the kubelet root.
- pkg/kubelet/image_manager.go — image GC frees disk down to a low
  threshold once usage crosses a high threshold. A process runtime has
  no image store; the disk-pressure reclaim applies to the same root
  (oldest dead artifacts first).
- pkg/kubelet/disk_manager.go — disk availability checks.
- pkg/kubelet/oom_watcher.go — records an event when the kernel kills
  a container; here detected from SIGKILL exit codes (137 / -9), the
  observable a process runtime has.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

_LOG = logging.getLogger("kubernetes_tpu.kubelet.managers")


@dataclass
class DiskUsage:
    capacity_bytes: int
    available_bytes: int

    @property
    def used_fraction(self) -> float:
        if self.capacity_bytes <= 0:
            return 0.0
        return 1.0 - self.available_bytes / self.capacity_bytes


class DiskManager:
    """Disk availability for the kubelet root (disk_manager.go)."""

    def __init__(
        self,
        root_dir: str,
        high_threshold: float = 0.90,
        low_threshold: float = 0.80,
        statvfs=os.statvfs,
    ):
        self.root = root_dir
        self.high = high_threshold
        self.low = low_threshold
        self._statvfs = statvfs

    def usage(self) -> DiskUsage:
        try:
            st = self._statvfs(self.root)
        except OSError:
            return DiskUsage(0, 0)
        return DiskUsage(
            capacity_bytes=st.f_frsize * st.f_blocks,
            available_bytes=st.f_frsize * st.f_bavail,
        )

    def over_high_threshold(self) -> bool:
        return self.usage().used_fraction >= self.high

    def under_low_threshold(self) -> bool:
        return self.usage().used_fraction <= self.low


class ContainerGC:
    """Reaps dead container artifacts under <root>/pods (container_gc.go
    policy shape: min age, per-pod and global caps) and, under disk
    pressure, oldest-first until the low threshold is met
    (image_manager.go reclaim shape)."""

    def __init__(
        self,
        root_dir: str,
        runtime,
        min_age_s: float = 0.0,
        max_log_bytes: int = 10 * 1024 * 1024,
        disk: Optional[DiskManager] = None,
        desired_uids=None,
    ):
        self.root = root_dir
        self.runtime = runtime
        self.min_age = min_age_s
        self.max_log_bytes = max_log_bytes
        self.disk = disk
        # Callable returning uids the kubelet still WANTS on this node.
        # A desired pod may have no runtime record yet (e.g. its volume
        # mounts keep failing, so sync returns before the runtime ever
        # sees it) — GC must not eat its directory out from under the
        # retry loop.
        self.desired_uids = desired_uids or (lambda: set())

    def _pod_dirs(self) -> List[str]:
        base = os.path.join(self.root, "pods")
        try:
            return [
                os.path.join(base, d)
                for d in os.listdir(base)
                if os.path.isdir(os.path.join(base, d))
            ]
        except OSError:
            return []

    def _live_uids(self) -> set:
        # Tracked by the runtime (even exited) or still desired by the
        # kubelet = not an orphan.
        return set(self.runtime.list_pods()) | set(self.desired_uids())

    @staticmethod
    def _has_volumes(pod_dir: str) -> bool:
        """Volume data lives under <pod_dir>/volumes (volumes/mount.py
        layout). Deleting THROUGH a mounted volume without the volume
        manager's teardown is never this GC's call."""
        return os.path.isdir(os.path.join(pod_dir, "volumes"))

    def _reap_dir(self, pod_dir: str) -> bool:
        """Remove a dead pod's artifacts. Directories that still hold
        volume data only lose runtime artifacts (logs + records); the
        kubelet's orphan GC owns volume teardown."""
        if self._has_volumes(pod_dir):
            for fname in self._list(pod_dir):
                if fname.endswith((".log", ".json")):
                    try:
                        os.unlink(os.path.join(pod_dir, fname))
                    except OSError:
                        pass
            return False
        shutil.rmtree(pod_dir, ignore_errors=True)
        return True

    def gc(self) -> Dict[str, int]:
        """One housekeeping pass. Returns action counts."""
        stats = {"dirs_removed": 0, "logs_truncated": 0, "pressure_removed": 0}
        live = self._live_uids()
        now = time.time()
        for pod_dir in self._pod_dirs():
            uid = os.path.basename(pod_dir)
            if uid not in live:
                # Dead pod's artifacts: reap after min_age (the
                # kubelet's own orphan GC kills processes; this reaps
                # what's left on disk).
                try:
                    age = now - os.path.getmtime(pod_dir)
                except OSError:
                    continue
                if age >= self.min_age and self._reap_dir(pod_dir):
                    stats["dirs_removed"] += 1
                continue
            # Live pod: cap log growth (reference caps dead containers
            # per pod; a process runtime's unbounded artifact is logs).
            for fname in self._list(pod_dir):
                if not fname.endswith(".log"):
                    continue
                path = os.path.join(pod_dir, fname)
                try:
                    if os.path.getsize(path) > self.max_log_bytes:
                        self._truncate_log(path)
                        stats["logs_truncated"] += 1
                except OSError:
                    pass
        if self.disk is not None and self.disk.over_high_threshold():
            stats["pressure_removed"] = self._reclaim()
        return stats

    @staticmethod
    def _list(path: str) -> List[str]:
        try:
            return os.listdir(path)
        except OSError:
            return []

    def _truncate_log(self, path: str) -> None:
        """Keep the newest half of an oversized log (cheap rotation)."""
        try:
            with open(path, "rb") as f:
                f.seek(-self.max_log_bytes // 2, os.SEEK_END)
                tail = f.read()
            with open(path, "wb") as f:
                f.write(b"[log truncated by container GC]\n")
                f.write(tail)
        except OSError:
            pass

    def _reclaim(self) -> int:
        """Disk pressure: remove oldest DEAD pod artifacts first until
        under the low threshold (image_manager.go LRU reclaim shape)."""
        removed = 0
        live = self._live_uids()
        candidates: List[Tuple[float, str]] = []
        for pod_dir in self._pod_dirs():
            if os.path.basename(pod_dir) in live:
                continue
            try:
                candidates.append((os.path.getmtime(pod_dir), pod_dir))
            except OSError:
                continue
        for _, pod_dir in sorted(candidates):
            if self.disk.under_low_threshold():
                break
            if self._reap_dir(pod_dir):
                removed += 1
        return removed


class ImageManager:
    """Image GC against a runtime's ImageStore (SandboxRuntime.images).

    Reference: pkg/kubelet/image_manager.go GarbageCollect — once image
    disk usage crosses the high threshold, evict least-recently-used
    images NOT used by any live container until usage is back under the
    low threshold. Thresholds here are byte budgets (the reference uses
    percent-of-imagefs; a byte budget is the same policy on a store
    that owns its own directory)."""

    def __init__(self, store, high_bytes: int, low_bytes: int):
        assert low_bytes <= high_bytes
        self.store = store
        self.high_bytes = high_bytes
        self.low_bytes = low_bytes

    def gc(self, in_use: set) -> int:
        """Returns bytes freed. `in_use` = image names of live
        containers (never evicted, image_manager.go:214)."""
        used = self.store.bytes_used()
        if used <= self.high_bytes:
            return 0
        candidates = sorted(
            (
                rec
                for rec in self.store.list_images()
                if rec.get("image") not in in_use
            ),
            key=lambda rec: rec.get("lastUsed", 0.0),
        )
        freed = 0
        for rec in candidates:
            if used - freed <= self.low_bytes:
                break
            freed += self.store.remove(rec["image"])
        return freed


class OOMWatcher:
    """Records an event when a container dies by SIGKILL — the
    process-runtime observable for kernel OOM kills (oom_watcher.go
    records 'SystemOOM' from kmsg via cadvisor)."""

    KILL_CODES = (137, -9)

    def __init__(self, client, node_name: str):
        self.client = client
        self.node_name = node_name
        # (uid, container, container_id) already reported.
        self._seen: set = set()

    def observe(self, pod, containers) -> int:
        """Inspect one pod's runtime containers; record one event per
        killed container incarnation. Returns events recorded."""
        recorded = 0
        uid = pod.metadata.uid or pod.metadata.name
        for c in containers:
            if c.state != "exited" or c.exit_code not in self.KILL_CODES:
                continue
            key = (uid, c.name, c.container_id)
            if key in self._seen:
                continue
            self._seen.add(key)
            try:
                self.client.record_event(
                    pod,
                    "ContainerKilled",
                    f"container {c.name} was killed (exit code {c.exit_code})",
                    source=f"kubelet/{self.node_name}",
                )
                recorded += 1
            except Exception:
                # Drop the dedup key so the next sync retries the
                # write; a sink that keeps failing must leave a trail.
                _LOG.exception(
                    "OOM event for %s/%s failed to record", uid, c.name
                )
                self._seen.discard(key)
        return recorded

    def prune(self, runtime_pods: Dict) -> None:
        """Drop dedup keys for container incarnations the runtime no
        longer tracks — those can never be observed again, so pruning
        them bounds memory WITHOUT re-emitting events for still-exited
        containers (a wholesale clear would)."""
        if len(self._seen) < 4096:
            return
        current = {
            (uid, c.name, c.container_id)
            for uid, containers in runtime_pods.items()
            for c in containers
        }
        self._seen &= current
