"""Sandbox runtime: pods in real Linux namespaces, with an image store.

The second REAL container runtime behind the kubelet's runtime seam
(kubernetes_tpu/kubelet/runtime.py), playing the role rkt plays for the
reference (pkg/kubelet/rkt/rkt.go — the proof that the abstraction in
pkg/kubelet/container/runtime.go:304 supports more than one backend).

What it adds over ProcessRuntime:

- **Pod-level isolation.** Each pod's anchor is created with
  `unshare --pid --fork --kill-child --mount --mount-proc --uts`, so
  the pod owns a PID namespace (containers see only pod processes;
  /proc/1 is the pause anchor), a mount namespace (its own /proc
  mount), and a UTS namespace (hostname == pod name, the reference's
  infra-container hostname semantics, dockertools/manager.go:1202).
  Containers and execs enter those namespaces with `nsenter -t <pid>
  -p -m -u`. PID-namespace teardown is kernel-enforced: when the
  anchor (ns PID 1) dies, every process in the pod is SIGKILLed —
  kill_pod cannot leak processes even if this daemon crashes mid-kill
  (`--kill-child` ties the anchor to our unshare parent too).

- **An image substrate.** Containers "pull" their image on first use
  into an on-disk store (<root>/images/): a manifest plus a layer blob
  of deterministic size, giving image bytes a real existence the
  kubelet's ImageManager (kubelet/managers.py, the image_manager.go
  analog) can garbage-collect by LRU under a disk budget — the piece
  a pure process runtime acknowledged it couldn't support.

Everything else (spec-hash container replacement, restart counts, log
files, adoption across kubelet restarts, service env injection) is
shared with ProcessRuntime by inheritance — the runtime seam only
varies WHERE processes run, not the kubelet contract above it.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import subprocess
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.models.objects import Pod
from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime, _Proc, _spec_hash


def sandbox_supported() -> bool:
    """Namespaces need root + util-linux; probe once, cheaply."""
    if os.geteuid() != 0:
        return False
    if shutil.which("unshare") is None or shutil.which("nsenter") is None:
        return False
    try:
        rc = subprocess.run(
            ["unshare", "--pid", "--fork", "true"],
            capture_output=True, timeout=5,
        ).returncode
    except (OSError, subprocess.TimeoutExpired):
        return False
    return rc == 0


def _hostname_for(pod_name: str) -> str:
    safe = re.sub(r"[^a-zA-Z0-9.-]", "-", pod_name or "pod")[:63]
    return safe or "pod"


class ImageStore:
    """On-disk image storage: <root>/<digest>/{manifest.json,layer.bin}.

    "Pulling" materializes a layer blob whose size is a deterministic
    function of the image name (64KiB-1MiB) — real bytes on the
    kubelet's disk, so disk accounting and image GC are exercised for
    real, without a registry (this box has zero egress; the reference's
    pull path is pkg/kubelet/dockertools/docker.go)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _dir(self, image: str) -> str:
        return os.path.join(self.root, hashlib.sha1(image.encode()).hexdigest()[:16])

    def pull(self, image: str) -> None:
        """Idempotent; refreshes last-used on every call (containers
        starting FROM an image count as using it, image_manager.go
        detectImages)."""
        d = self._dir(image)
        manifest = os.path.join(d, "manifest.json")
        if not os.path.exists(manifest):
            os.makedirs(d, exist_ok=True)
            h = int(hashlib.sha1(image.encode()).hexdigest(), 16)
            size = 65536 + (h % 16) * 65536  # 64KiB..1MiB
            with open(os.path.join(d, "layer.bin"), "wb") as f:
                f.write(b"\0" * size)
            with open(manifest, "w") as f:
                json.dump({"image": image, "bytes": size}, f)
        self.touch(image)

    def touch(self, image: str) -> None:
        try:
            os.utime(os.path.join(self._dir(image), "manifest.json"))
        except OSError:
            pass

    def list_images(self) -> List[dict]:
        out = []
        try:
            entries = os.listdir(self.root)
        except OSError:
            return out
        for e in entries:
            manifest = os.path.join(self.root, e, "manifest.json")
            try:
                with open(manifest) as f:
                    rec = json.load(f)
                rec["lastUsed"] = os.stat(manifest).st_mtime
                out.append(rec)
            except (OSError, ValueError):
                continue
        return out

    def remove(self, image: str) -> int:
        """Returns bytes freed, in the SAME unit bytes_used() counts
        (the manifest's declared layer bytes) — ImageManager.gc's
        watermark math subtracts freed from used, so mixing units
        (declared vs on-disk incl. manifest.json) would drift its
        low-watermark stop condition."""
        d = self._dir(image)
        try:
            with open(os.path.join(d, "manifest.json")) as f:
                freed = int(json.load(f).get("bytes", 0))
        except (OSError, ValueError):
            # Partially-pulled dir (crash between layer.bin and
            # manifest.json): invisible to bytes_used(), but still
            # reclaim the disk.
            freed = 0
        shutil.rmtree(d, ignore_errors=True)
        return freed

    def bytes_used(self) -> int:
        return sum(rec.get("bytes", 0) for rec in self.list_images())


class SandboxRuntime(ProcessRuntime):
    """Namespace-isolated pods rooted at `root_dir`."""

    def __init__(self, root_dir: str, node_name: str = ""):
        super().__init__(root_dir, node_name=node_name)
        self.images = ImageStore(os.path.join(root_dir, "images"))
        # unshare-wrapper pid -> inner (ns PID 1) pid, host view.
        self._inner_pids: Dict[int, int] = {}
        # pod uid -> pod name, for the UTS hostname (set by sync_pod
        # before the anchor starts).
        self._pod_names: Dict[str, str] = {}
        # Adopted containers (kubelet restart) were spawned inside
        # their pod's namespaces iff that pod's anchor is still alive.
        for uid, containers in self._pods.items():
            anchor = self._anchors.get(uid)
            if anchor is not None and anchor.poll() is None:
                for proc in containers.values():
                    proc.sandboxed = True

    # -- namespace plumbing -------------------------------------------

    def _inner_pid(self, anchor: _Proc, timeout: float = 2.0) -> Optional[int]:
        """Host-view pid of the pod's ns PID 1 (the pause under the
        `unshare --fork` wrapper). Polled: the child appears a beat
        after the wrapper starts."""
        cached = self._inner_pids.get(anchor.pid)
        if cached is not None:
            try:
                os.kill(cached, 0)
                return cached
            except OSError:
                self._inner_pids.pop(anchor.pid, None)
        deadline = time.monotonic() + timeout
        path = f"/proc/{anchor.pid}/task/{anchor.pid}/children"
        while time.monotonic() < deadline:
            try:
                with open(path) as f:
                    kids = f.read().split()
            except OSError:
                return None  # wrapper gone
            if kids:
                pid = int(kids[0])
                self._inner_pids[anchor.pid] = pid
                return pid
            time.sleep(0.01)
        return None

    def _nsenter_argv(self, uid: str) -> List[str]:
        """['nsenter', '-t', <pid>, ...] or [] if the pod has no live
        sandbox (fall back to plain host process — degraded, visible
        via container_id prefix)."""
        anchor = self._anchors.get(uid)
        if anchor is None or anchor.poll() is not None:
            return []
        inner = self._inner_pid(anchor)
        if inner is None:
            return []
        return ["nsenter", "-t", str(inner), "--pid", "--mount", "--uts"]

    # -- ProcessRuntime overrides -------------------------------------

    def _start_anchor(self, uid: str) -> None:  # noqa: D102
        if uid in self._anchors and self._anchors[uid].poll() is None:
            return
        pause = self._pause_path()
        if pause is None:
            import sys

            inner = f"exec {sys.executable} -c 'import signal;signal.pause()'"
        else:
            inner = f"exec {pause}"
        log = os.path.join(self._pod_dir(uid), "_pause.log")
        os.makedirs(self._pod_dir(uid), exist_ok=True)
        hostname = _hostname_for(self._pod_names.get(uid, uid))
        argv = [
            "unshare", "--pid", "--fork", "--kill-child",
            "--mount", "--mount-proc", "--uts",
            "sh", "-c", f"hostname {hostname}; {inner}",
        ]
        with open(log, "ab") as lf:
            popen = subprocess.Popen(
                argv, stdout=lf, stderr=lf, start_new_session=True
            )
        proc = _Proc(
            pid=popen.pid,
            popen=popen,
            spec_hash="anchor",
            name="_pause",
            image="pause",
            log_path=log,
            started_at=time.monotonic(),
        )
        self._anchors[uid] = proc
        self._record(uid, proc)

    def sync_pod(self, pod: Pod) -> List:
        uid = pod.metadata.uid or pod.metadata.name
        self._pod_names[uid] = pod.metadata.name
        return super().sync_pod(pod)

    def _start_container(self, pod: Pod, uid: str, spec, restart_count: int) -> _Proc:
        if spec.image:
            self.images.pull(spec.image)
        ns = self._nsenter_argv(uid)
        if not ns:
            return super()._start_container(pod, uid, spec, restart_count)
        # Same spawn as the parent, wrapped in the pod's namespaces.
        log = os.path.join(self._pod_dir(uid), f"{spec.name}.log")
        argv = ns + self._container_argv(spec)
        with open(log, "ab") as lf:
            try:
                popen = subprocess.Popen(
                    argv,
                    stdout=lf,
                    stderr=lf,
                    env=self._env_for(pod, spec),
                    cwd=spec.working_dir or None,
                    start_new_session=True,
                    **self._run_as(spec),
                )
            except OSError as e:
                lf.write(f"start error: {e}\n".encode())
                return _Proc(
                    pid=0, popen=None, spec_hash=_spec_hash(spec),
                    name=spec.name, image=spec.image, log_path=log,
                    restart_count=restart_count,
                    started_at=time.monotonic(), exit_code=127,
                )
        proc = _Proc(
            pid=popen.pid,
            popen=popen,
            spec_hash=_spec_hash(spec),
            name=spec.name,
            image=spec.image,
            log_path=log,
            restart_count=restart_count,
            started_at=time.monotonic(),
        )
        proc.sandboxed = True  # spawned through the pod's namespaces
        self._record(uid, proc)
        return proc

    def _to_rc(self, proc: _Proc):
        """sandbox:// ONLY for containers that actually entered the
        pod's namespaces — a degraded fallback spawn (dead anchor)
        keeps proc://, so the missing isolation stays visible."""
        rc = super()._to_rc(proc)
        if getattr(proc, "sandboxed", False) and rc.container_id.startswith(
            "proc://"
        ):
            rc.container_id = "sandbox://" + rc.container_id[len("proc://"):]
        return rc

    def exec_in_container(
        self,
        pod_uid: str,
        container: str,
        command: List[str],
        pod: Optional[Pod] = None,
        timeout: float = 10.0,
    ) -> Tuple[int, str]:
        """Exec INSIDE the pod's namespaces (the reference execs inside
        the container's namespaces via docker exec / nsenter —
        pkg/kubelet/server.go /exec)."""
        ns = self._nsenter_argv(pod_uid)
        return super().exec_in_container(
            pod_uid, container, ns + list(command), pod=pod, timeout=timeout
        )

    def kill_pod(self, pod_uid: str) -> None:
        with self._lock:
            anchor = self._anchors.get(pod_uid)
            if anchor is not None:
                self._inner_pids.pop(anchor.pid, None)
            self._pod_names.pop(pod_uid, None)
        super().kill_pod(pod_uid)
        # PID-ns teardown: the anchor's death SIGKILLs everything in
        # the pod's namespace — nothing to sweep.
