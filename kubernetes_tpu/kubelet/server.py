"""Kubelet HTTP API.

Reference: pkg/kubelet/server.go:130-144 — the read/exec surface every
node agent serves on port 10250: /pods, /healthz, /stats, /spec,
/run/..., /exec/..., and (apiserver-proxied) container logs. The
apiserver's pod subresources (GET /pods/{p}/log, POST /pods/{p}/exec —
pkg/registry/pod/etcd/etcd.go:42-50) proxy here after resolving the
pod's node.

Deviation from the reference: /exec speaks plain JSON request/response
instead of an SPDY stream upgrade (pkg/util/httpstream) — the v0.19
/run endpoint (non-streaming exec) is the semantic this implements for
both paths.

Routes:
  GET  /healthz
  GET  /pods
  GET  /spec
  GET  /stats                         node + per-pod container stats
  GET  /logs/{ns}/{pod}/{container}?tail=N
  POST /run/{ns}/{pod}/{container}    body {"command": [...]}
  POST /exec/{ns}/{pod}/{container}   alias of /run (JSON, not SPDY)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu.models import serde


class _KubeletHandler(BaseHTTPRequestHandler):
    kubelet = None  # bound by KubeletServer
    disable_nagle_algorithm = True  # keep-alive without Nagle stalls

    def log_message(self, fmt, *args):  # quiet
        pass

    # -- helpers ------------------------------------------------------

    def _send(self, code: int, body, content_type="application/json") -> None:
        data = (
            body.encode()
            if isinstance(body, str)
            else json.dumps(body).encode()
        )
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _pod_and_uid(self, ns: str, name: str):
        for pod in self.kubelet.pods.store.list():
            if (
                pod.metadata.name == name
                and (pod.metadata.namespace or "default") == ns
            ):
                return pod, pod.metadata.uid or pod.metadata.name
        return None, None

    # -- GET ----------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (stdlib naming)
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            if len(parts) == 4 and parts[0] == "portForward":
                self._port_forward(parts[1], parts[2], parts[3])
            elif url.path == "/healthz":
                self._send(200, "ok", "text/plain")
            elif url.path == "/pods":
                items = [
                    serde.to_wire(p) for p in self.kubelet.pods.store.list()
                ]
                self._send(200, {"kind": "PodList", "items": items})
            elif url.path == "/spec":
                self._send(200, self.kubelet.node_spec())
            elif url.path == "/stats":
                self._send(200, self.kubelet.node_stats())
            elif len(parts) == 4 and parts[0] == "logs":
                self._get_logs(parts[1], parts[2], parts[3], url)
            else:
                self._send(404, {"error": f"no route {url.path}"})
        except BrokenPipeError:
            pass
        except Exception as e:  # crash containment per request
            try:
                self._send(500, {"error": str(e)})
            except Exception:  # ktlint: disable=KT003
                pass  # client already gone; the 500 has nowhere to go

    def _get_logs(self, ns: str, name: str, container: str, url) -> None:
        pod, uid = self._pod_and_uid(ns, name)
        if pod is None:
            self._send(404, {"error": f"pod {ns}/{name} not on this node"})
            return
        rt = self.kubelet.runtime
        if not hasattr(rt, "read_logs"):
            self._send(501, {"error": "runtime does not expose logs"})
            return
        q = parse_qs(url.query)
        tail = None
        if "tail" in q or "tailLines" in q:
            try:
                tail = int((q.get("tail") or q.get("tailLines"))[0])
            except (ValueError, TypeError):
                tail = None
        self._send(200, rt.read_logs(uid, container, tail), "text/plain")

    def _port_forward(self, ns: str, name: str, port_s: str) -> None:
        """Websocket tunnel to a container port (reference:
        /portForward on the kubelet, pkg/kubelet/server.go:142, via
        SPDY; here binary websocket frames <-> TCP bytes). A process
        runtime is host-network, so the container's port listens on
        the node's loopback."""
        import socket
        import threading

        from kubernetes_tpu.utils import websocket as ws

        pod, _uid = self._pod_and_uid(ns, name)
        if pod is None:
            self._send(404, {"error": f"pod {ns}/{name} not on this node"})
            return
        key = self.headers.get("Sec-WebSocket-Key")
        if self.headers.get("Upgrade", "").lower() != "websocket" or not key:
            self._send(400, {"error": "port-forward requires websocket upgrade"})
            return
        try:
            port = int(port_s)
        except ValueError:
            self._send(400, {"error": f"invalid port {port_s!r}"})
            return
        try:
            backend = socket.create_connection(("127.0.0.1", port), timeout=5)
        except OSError as e:
            self._send(502, {"error": f"dial container port {port}: {e}"})
            return
        self.send_response(101, "Switching Protocols")
        for hname, value in ws.handshake_headers(key):
            self.send_header(hname, value)
        self.end_headers()
        ws.relay_ws_tcp(
            ws.ServerEndpoint(self.rfile, self.wfile, raw_socket=self.connection),
            backend,
        )
        self.close_connection = True

    # -- POST (run / exec) --------------------------------------------

    def do_POST(self) -> None:  # noqa: N802
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        try:
            if len(parts) == 4 and parts[0] in ("run", "exec"):
                self._run(parts[1], parts[2], parts[3], url)
            else:
                self._send(404, {"error": f"no route {url.path}"})
        except BrokenPipeError:
            pass
        except Exception as e:
            try:
                self._send(500, {"error": str(e)})
            except Exception:  # ktlint: disable=KT003
                pass  # client already gone; the 500 has nowhere to go

    def _run(self, ns: str, name: str, container: str, url) -> None:
        pod, uid = self._pod_and_uid(ns, name)
        if pod is None:
            self._send(404, {"error": f"pod {ns}/{name} not on this node"})
            return
        rt = self.kubelet.runtime
        if not hasattr(rt, "exec_in_container"):
            self._send(501, {"error": "runtime does not support exec"})
            return
        length = int(self.headers.get("Content-Length", 0) or 0)
        command = []
        if length:
            try:
                body = json.loads(self.rfile.read(length))
                command = body.get("command", [])
            except (json.JSONDecodeError, AttributeError):
                pass
        if not command:
            # Reference /run also accepts cmd via query params.
            command = parse_qs(url.query).get("cmd", [])
        if not command:
            self._send(400, {"error": "no command"})
            return
        rc, output = rt.exec_in_container(uid, container, command, pod=pod)
        self._send(200, {"exitCode": rc, "output": output})


class KubeletServer:
    """Owns the kubelet's HTTP listener (reference port 10250; here an
    ephemeral port published via the Node's daemon endpoints)."""

    def __init__(self, kubelet, host: str = "127.0.0.1", port: int = 0):
        handler = type("BoundKubeletHandler", (_KubeletHandler,), {"kubelet": kubelet})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "KubeletServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
