"""Probe executors + per-container probe state.

Reference: pkg/probe/{exec,http,tcp}/ (the three probe transports) and
pkg/kubelet/prober/prober.go (readiness vs liveness semantics):
- liveness failure (after the failure threshold) kills the container so
  restart policy brings it back;
- readiness failure only flips the container un-ready — the pod stays
  running but drops out of service Endpoints (readiness_manager.go).

HTTP probes treat any 2xx/3xx as healthy (pkg/probe/http/http.go:96);
TCP probes succeed when the connect() does (pkg/probe/tcp/tcp.go:40).
A process runtime has host networking, so probes dial 127.0.0.1 unless
the probe names a host.
"""

from __future__ import annotations

import socket
import time
import urllib.error
import urllib.request
from typing import Dict, Optional

from kubernetes_tpu.models.objects import Pod, Probe


def probe_http(host: str, port: int, path: str, timeout: float) -> bool:
    if not path.startswith("/"):
        path = "/" + path
    url = f"http://{host or '127.0.0.1'}:{port}{path}"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return 200 <= resp.status < 400
    except urllib.error.HTTPError as e:
        return 200 <= e.code < 400
    except (urllib.error.URLError, OSError, ValueError):
        return False


def probe_tcp(host: str, port: int, timeout: float) -> bool:
    try:
        with socket.create_connection((host or "127.0.0.1", port), timeout=timeout):
            return True
    except OSError:
        return False


def run_probe(probe: Probe, pod: Pod, container: str, runtime) -> bool:
    """Execute one probe of whatever transport it declares. A probe
    with no action configured is treated as success (prober.go runProbe
    default)."""
    timeout = float(probe.timeout_seconds or 1)
    if probe.exec is not None:
        # The ContainerRuntime seam takes timeout (probe timeoutSeconds).
        return runtime.exec_probe(
            pod, container, probe.exec.command, timeout=timeout
        )
    if probe.http_get is not None:
        return probe_http(
            probe.http_get.host, probe.http_get.port, probe.http_get.path, timeout
        )
    if probe.tcp_socket is not None:
        return probe_tcp("", probe.tcp_socket.port, timeout)
    return True


class ProbeTracker:
    """Per-container probe bookkeeping: initial delay, liveness failure
    threshold, and the latest readiness verdict."""

    FAILURE_THRESHOLD = 3  # v0.19 hard-codes 3 consecutive failures

    def __init__(self):
        self._liveness_failures: Dict[str, int] = {}
        self._readiness: Dict[str, bool] = {}
        self._started: Dict[str, float] = {}

    def note_started(self, key: str, started_at: float) -> None:
        prev = self._started.get(key)
        self._started[key] = started_at
        if prev is not None and started_at > prev:
            # Container restarted: a stale ready=True from the previous
            # incarnation must not keep the pod in Endpoints while the
            # new process is still inside its initial delay. The verdict
            # flips to False (not None: agent's default for "no probe"
            # is ready, which would defeat this) — only containers that
            # HAVE a readiness probe carry a verdict here.
            if key in self._readiness:
                self._readiness[key] = False
            self._liveness_failures.pop(key, None)

    def in_initial_delay(self, key: str, probe: Probe) -> bool:
        started = self._started.get(key)
        if started is None:
            # No recorded start: the container hasn't been synced yet;
            # probing now would count failures against a process that
            # doesn't exist.
            return True
        delay = probe.initial_delay_seconds or 0
        return delay > 0 and (time.monotonic() - started) < delay

    def liveness(self, key: str, healthy: bool) -> bool:
        """Record one liveness result; True = threshold crossed (kill)."""
        if healthy:
            self._liveness_failures.pop(key, None)
            return False
        failures = self._liveness_failures.get(key, 0) + 1
        self._liveness_failures[key] = failures
        if failures >= self.FAILURE_THRESHOLD:
            self._liveness_failures[key] = 0
            return True
        return False

    def set_ready(self, key: str, ready: bool) -> None:
        self._readiness[key] = ready

    def ready(self, key: str) -> Optional[bool]:
        """Latest readiness verdict (None = no probe has run)."""
        return self._readiness.get(key)

    def forget(self, key_prefix: str) -> None:
        for d in (self._liveness_failures, self._readiness, self._started):
            for k in [k for k in d if k.startswith(key_prefix)]:
                del d[k]
