"""Container runtime abstraction + fake implementation.

Reference: pkg/kubelet/container/runtime.go (Runtime interface) and
pkg/kubelet/dockertools/fake_docker_client.go (the fake that backs all
integration testing). The fake tracks desired containers per pod,
honors restart policy, and lets tests inject failures.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.models.objects import Pod


@dataclass
class RuntimeContainer:
    name: str
    image: str
    container_id: str
    state: str = "running"  # running | exited | waiting
    exit_code: int = 0
    restart_count: int = 0
    started_at: float = field(default_factory=time.monotonic)


class ContainerRuntime:
    """What the kubelet needs from a runtime (runtime.go:304)."""

    def sync_pod(self, pod: Pod) -> List[RuntimeContainer]:
        """Start missing containers / replace changed images; exited
        containers are left alone (restart policy is the kubelet's
        call, made per-container via restart_container)."""
        raise NotImplementedError

    def restart_container(self, pod_uid: str, name: str) -> None:
        raise NotImplementedError

    def kill_pod(self, pod_uid: str) -> None:
        raise NotImplementedError

    def list_pods(self) -> Dict[str, List[RuntimeContainer]]:
        """pod uid -> containers (for orphan GC)."""
        raise NotImplementedError

    def exec_probe(
        self, pod: Pod, container: str, command: List[str], timeout: float = 1.0
    ) -> bool:
        """Run a probe; True = healthy. `timeout` is the probe's
        timeoutSeconds (pkg/probe/exec honors it per run)."""
        raise NotImplementedError


class FakeRuntime(ContainerRuntime):
    """In-memory runtime. Containers 'run' instantly; tests can fail
    them (fail_container) or make probes flap (set_probe_result)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pods: Dict[str, Dict[str, RuntimeContainer]] = {}
        self._probe_results: Dict[str, bool] = {}  # "uid/container" -> healthy
        self._next_id = 0
        self.calls: List[str] = []  # recorded operations, oldest first

    def _cid(self) -> str:
        self._next_id += 1
        return f"fake://{self._next_id}"

    # -- ContainerRuntime ---------------------------------------------

    def sync_pod(self, pod: Pod) -> List[RuntimeContainer]:
        uid = pod.metadata.uid or pod.metadata.name
        with self._lock:
            containers = self._pods.setdefault(uid, {})
            desired = {c.name: c for c in pod.spec.containers}
            # Kill containers no longer desired.
            for name in list(containers):
                if name not in desired:
                    self.calls.append(f"kill {uid}/{name}")
                    del containers[name]
            for name, spec in desired.items():
                cur = containers.get(name)
                if cur is None:
                    self.calls.append(f"start {uid}/{name}")
                    containers[name] = RuntimeContainer(
                        name=name, image=spec.image, container_id=self._cid()
                    )
                elif cur.image != spec.image:
                    self.calls.append(f"recreate {uid}/{name}")
                    containers[name] = RuntimeContainer(
                        name=name,
                        image=spec.image,
                        container_id=self._cid(),
                        restart_count=cur.restart_count + 1,
                    )
            return [c for c in containers.values()]

    def restart_container(self, pod_uid: str, name: str) -> None:
        with self._lock:
            cur = self._pods.get(pod_uid, {}).get(name)
            if cur is not None and cur.state == "exited":
                self.calls.append(f"restart {pod_uid}/{name}")
                cur.state = "running"
                cur.exit_code = 0
                cur.restart_count += 1
                cur.container_id = self._cid()

    def kill_pod(self, pod_uid: str) -> None:
        with self._lock:
            if pod_uid in self._pods:
                self.calls.append(f"killpod {pod_uid}")
                del self._pods[pod_uid]

    def list_pods(self) -> Dict[str, List[RuntimeContainer]]:
        with self._lock:
            return {uid: list(cs.values()) for uid, cs in self._pods.items()}

    def exec_probe(
        self, pod: Pod, container: str, command: List[str], timeout: float = 1.0
    ) -> bool:
        uid = pod.metadata.uid or pod.metadata.name
        with self._lock:
            return self._probe_results.get(f"{uid}/{container}", True)

    # -- test hooks ---------------------------------------------------

    def fail_container(self, pod_uid: str, name: str, exit_code: int = 1) -> None:
        with self._lock:
            c = self._pods.get(pod_uid, {}).get(name)
            if c is not None:
                c.state = "exited"
                c.exit_code = exit_code

    def set_probe_result(self, pod_uid: str, container: str, healthy: bool) -> None:
        with self._lock:
            self._probe_results[f"{pod_uid}/{container}"] = healthy
