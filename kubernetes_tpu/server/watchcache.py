"""The apiserver-resident watch cache: an event-fed read path.

Reference: pkg/storage/cacher (the etcd watch cache the reference grew
into) and PAPER.md §1 layer 4 — reads should be served from memory kept
current by the event stream, never by scanning the store.

One `WatchCacheSet` subscribes ONCE to the kvstore's dispatcher
(`KVStore.subscribe`) and routes every event to a per-resource
`ResourceCache` keyed by registry prefix. Each cache holds:

- `key -> _Entry(obj, version, enc)` — the stored object REF (the
  store's objects are never mutated in place, so sharing the ref is
  safe and copy-free) plus a lazily computed JSON encoding. Because the
  store's logical clock is global and every write bumps it, an object's
  resourceVersion uniquely identifies its bytes — the encode cache can
  never serve stale bytes, and an object listed N times (every
  controller relist, every reflector sync) is serialized ONCE.
- a monotone `version` + condition variable: `wait_until(v)` gives
  read-your-writes consistency (a client that just wrote at version v
  LISTs at >= v, exactly Kubernetes' waitUntilFreshAndBlock). The
  dispatcher normally trails writes by microseconds; the bounded wait
  falls back to a direct store read on timeout so a wedged dispatcher
  degrades to the old path instead of erroring.

LIST responses for the HTTP tier are assembled from the cached
per-object fragments (`list_encoded`): a 5k-node LIST that used to pay
a full json.dumps per request becomes a byte join. Watch frames are
cached the same way (`frame_bytes`): one event fanned out to N watch
connections is encoded once, keyed by its globally unique version.
"""

from __future__ import annotations

import json
import threading
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.utils import sanitizer


class _Entry:
    __slots__ = ("obj", "version", "enc")

    def __init__(self, obj: dict, version: int):
        self.obj = obj
        self.version = version
        self.enc: Optional[bytes] = None


class ResourceCache:
    """Event-fed mirror of one registry prefix ('/registry/pods/')."""

    def __init__(self, prefix: str, store, cache_set: "WatchCacheSet"):
        self.prefix = prefix
        self._store = store
        self._set = cache_set
        self._lock = sanitizer.lock("watchcache.resource")
        self._items: Dict[str, _Entry] = {}
        self._sorted: Optional[List[str]] = None  # lazily (re)sorted keys
        # Everything <= seed_version is reflected (from the seed list);
        # everything <= the SET's applied version is reflected (events
        # are dispatched in global version order). The freshness floor
        # is the max of the two.
        self.seed_version = 0
        # Seed from the store's current state; events that raced in are
        # buffered by the set's _BufferingRoute and replayed after (the
        # route registers BEFORE this list, so nothing can be missed —
        # apply() drops versions the seed already covered).
        objs, at = store.list(prefix, copy=False)
        with self._lock:
            for obj in objs:
                key = self._key_of(obj)
                if key is not None:
                    self._items[key] = _Entry(
                        obj, int(obj.get("metadata", {})
                                 .get("resourceVersion", "0") or "0")
                    )
            self.seed_version = at

    def _key_of(self, obj: dict) -> Optional[str]:
        meta = obj.get("metadata", {})
        name = meta.get("name", "")
        if not name:
            return None
        ns = meta.get("namespace", "")
        return self.prefix + (f"{ns}/{name}" if ns else name)

    # -- event feed (dispatcher thread) --------------------------------

    def apply(self, version: int, etype: str, key: str, obj: dict) -> None:
        with self._lock:
            if etype == "DELETED":
                # Version-guarded like the upsert branch: a stale
                # buffered DELETED replayed during seeding must not
                # remove a NEWER recreated object the seed captured.
                cur = self._items.get(key)
                if cur is not None and version >= cur.version:
                    del self._items[key]
                    self._sorted = None
            else:
                cur = self._items.get(key)
                if cur is None:
                    self._sorted = None
                if cur is None or version >= cur.version:
                    self._items[key] = _Entry(obj, version)

    # -- consistency ---------------------------------------------------

    @property
    def version(self) -> int:
        """The freshness floor: every write at or below it is
        reflected here (LIST responses report this, so a watch resumed
        from it sees exactly the later events)."""
        return max(self.seed_version, self._set.applied)

    def fresh(self, timeout: float = 2.0) -> bool:
        """Catch up to the store's CURRENT version — read-your-writes
        (Kubernetes' waitUntilFreshAndBlock). Runs due TTL expirations
        first so a quiet store can't serve dead TTL'd objects from
        memory. False on timeout (wedged dispatcher) — caller falls
        back to a direct store read."""
        self._store.expire_now()
        target = self._store.version
        if target <= self.seed_version:
            return True
        return self._set.wait_applied(target, timeout)

    # -- reads ---------------------------------------------------------

    def _keys_sorted_locked(self) -> List[str]:
        if self._sorted is None:
            self._sorted = sorted(self._items)
        return self._sorted

    def get(self, key: str) -> Optional[dict]:
        """The stored object ref (read-only) or None."""
        with self._lock:
            e = self._items.get(key)
            return None if e is None else e.obj

    def get_encoded(self, key: str) -> Optional[bytes]:
        with self._lock:
            e = self._items.get(key)
            if e is None:
                return None
            if e.enc is None:
                e.enc = json.dumps(e.obj).encode()
            return e.enc

    def _snapshot_entries_locked_free(self, prefix: str) -> List[_Entry]:
        """Consistent entry snapshot under a SHORT lock hold. The
        per-object work (selector filtering, lazy encoding) happens
        OUTSIDE the lock: the dispatcher thread needs it for apply(),
        so a large LIST must not stall watch fan-out for the duration
        of thousands of json.dumps calls. Entries are immutable per
        version and `enc` writes are idempotent (bytes deterministic
        per resourceVersion), so the unlocked access is benign."""
        with self._lock:
            keys = self._keys_sorted_locked()
            items = self._items
            if prefix == self.prefix:
                return [items[k] for k in keys]
            return [items[k] for k in keys if k.startswith(prefix)]

    def list_refs(
        self, prefix: str, pred: Optional[Callable] = None
    ) -> Tuple[List[dict], int]:
        """(object refs under prefix in key order, cache version).
        Refs are read-only; callers that hand objects out copy them
        (same contract as KVStore.list(copy=False))."""
        # Version BEFORE the snapshot: events landing in between are
        # included-but-unclaimed (a resumed watch re-delivers them,
        # idempotent). The reverse order would claim events the
        # snapshot missed — a resumed watch would skip them forever.
        version = self.version
        entries = self._snapshot_entries_locked_free(prefix)
        out = [e.obj for e in entries]
        if pred is not None:
            out = [o for o in out if pred(o)]
        return out, version

    def list_encoded(
        self, prefix: str, pred: Optional[Callable] = None
    ) -> Tuple[bytes, int, int]:
        """(b'obj,obj,...' joined fragments, count, version) for the
        HTTP LIST fast path. Each object's encoding is computed at most
        once per resourceVersion; encoding runs OUTSIDE the cache lock
        (see _snapshot_entries_locked_free)."""
        version = self.version  # before the snapshot — see list_refs
        entries = self._snapshot_entries_locked_free(prefix)
        frags: List[bytes] = []
        for e in entries:
            if pred is not None and not pred(e.obj):
                continue
            if e.enc is None:
                e.enc = json.dumps(e.obj).encode()
            frags.append(e.enc)
        return b", ".join(frags), len(frags), version

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class WatchCacheSet:
    """All resource caches over one store, fed by one subscriber.

    Freshness is tracked GLOBALLY: the store's logical clock spans all
    resources, and events reach the one subscriber in version order, so
    "every cache reflects all writes <= applied" holds after each event
    regardless of which cache it routed to. That makes wait_applied()
    work even when the triggering write touched another resource."""

    def __init__(self, store):
        self._store = store
        self._lock = sanitizer.lock("watchcache.set")
        self._caches: Dict[str, ResourceCache] = {}  # prefix -> cache
        self._routes: List[Tuple[str, object]] = []
        self.applied = 0  # highest event version processed by the feed
        self._applied_cond = threading.Condition(
            sanitizer.lock("watchcache.applied")
        )
        # Encoded watch frames keyed by (event type, version): the
        # store's version clock is global, so within one store the key
        # uniquely identifies the frame bytes. One event fanned out to
        # N watch connections is json.dumps'd once. Per-set (per-store)
        # on purpose: two stores' clocks both start at 1.
        self._frame_lock = sanitizer.lock("watchcache.frames")
        self._frames: Dict[Tuple[str, int], bytes] = {}
        # Per-resource applied watermark: highest event version seen
        # FOR each resource (keyed by the '/registry/<res>/' segment).
        # The fan-out lag SLI compares a stream's delivered version
        # against ITS resource's watermark — comparing against the
        # global `applied` would charge a caught-up services watch
        # with every pod write's version (false SLO warns). Plain dict:
        # single writer (the dispatcher), GIL-atomic reads.
        self._applied_by_resource: Dict[str, int] = {}
        store.subscribe(self._on_event)

    def _on_event(
        self, version: int, etype: str, key: str, obj: dict, prev
    ) -> None:
        for prefix, cache in self._routes:
            if key.startswith(prefix):
                cache.apply(version, etype, key, obj)
                break
        # key shape '/registry/<resource>/...' — split bounded at 3.
        parts = key.split("/", 3)
        if len(parts) > 2:
            self._applied_by_resource[parts[2]] = version
        with self._applied_cond:
            self.applied = version
            self._applied_cond.notify_all()

    def applied_version(self, resource: str) -> int:
        """Highest event version the feed has processed for ONE
        resource (0 = no event seen yet) — the fan-out lag SLI's
        comparison point."""
        return self._applied_by_resource.get(resource, 0)

    def wait_applied(self, version: int, timeout: float = 2.0) -> bool:
        """Block until the feed has processed every event <= version."""
        if self.applied >= version:
            return True
        deadline = _time.monotonic() + timeout
        with self._applied_cond:
            while self.applied < version:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._applied_cond.wait(remaining)
        return True

    def cache_for(self, prefix: str) -> ResourceCache:
        """The cache mirroring `prefix`, created (and seeded) on first
        use. A buffering route registers BEFORE seeding so no event can
        fall between the seed snapshot and the live feed."""
        cache = self._caches.get(prefix)
        if cache is not None:
            return cache
        with self._lock:
            cache = self._caches.get(prefix)
            if cache is not None:
                return cache
            holder = _BufferingRoute(prefix)
            self._routes = self._routes + [(prefix, holder)]
            cache = ResourceCache(prefix, self._store, self)
            holder.drain_into(cache)
            # Swap the buffering route for the live cache.
            self._routes = [
                (p, cache if c is holder else c) for p, c in self._routes
            ]
            self._caches[prefix] = cache
            return cache

    def peek(self, prefix: str) -> Optional[ResourceCache]:
        return self._caches.get(prefix)

    def frame_bytes(self, etype: str, version: int, obj) -> bytes:
        """Encoded b'{"type": ..., "object": ...}' watch frame (no
        trailing newline), cached by (etype, version) when nonzero."""
        if not version:
            return json.dumps({"type": etype, "object": obj}).encode()
        key = (etype, version)
        with self._frame_lock:
            hit = self._frames.get(key)
        if hit is not None:
            return hit
        enc = json.dumps({"type": etype, "object": obj}).encode()
        with self._frame_lock:
            if len(self._frames) >= 8192:
                self._frames.clear()  # cheap bound; re-encode on miss
            self._frames[key] = enc
        return enc


class _BufferingRoute:
    """Stand-in route that buffers events while its real cache seeds;
    drain_into() replays them (idempotent — apply() drops versions the
    seed already covered) and then forwards directly, preserving the
    dispatcher's version order."""

    def __init__(self, prefix: str):
        self.prefix = prefix
        self._lock = sanitizer.lock("watchcache.bufroute")
        self._buf: List[tuple] = []
        self._target: Optional[ResourceCache] = None

    def apply(self, version: int, etype: str, key: str, obj: dict) -> None:
        with self._lock:
            if self._target is None:
                self._buf.append((version, etype, key, obj))
                return
            target = self._target
        target.apply(version, etype, key, obj)

    def drain_into(self, cache: ResourceCache) -> None:
        # Replay UNDER the lock: a live event racing in must queue
        # behind the replay, never interleave ahead of older buffered
        # events (a DELETED overtaken by a buffered older ADDED would
        # resurrect the object).
        with self._lock:
            for version, etype, key, obj in self._buf:
                cache.apply(version, etype, key, obj)
            self._buf = []
            self._target = cache


