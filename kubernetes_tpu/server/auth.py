"""Authentication and authorization.

Behavioral parity with the reference's auth stack:
- request authenticators: basic auth + bearer token
  (plugin/pkg/auth/authenticator/{password/passwordfile,token/tokenfile},
  pkg/apiserver/authn.go:35)
- service-account JWTs (pkg/serviceaccount/jwt.go) — the reference signs
  RS256 with the cluster key; we sign HS256 (HMAC-SHA256) with a cluster
  secret since there is no bundled RSA implementation. Claims mirror the
  reference: iss, sub, and the kubernetes.io/serviceaccount/* set.
- ABAC authorizer from a policy file of one-JSON-object-per-line
  (pkg/auth/authorizer/abac/abac.go), with the same matching rules:
  empty/'*' fields match everything, a '*' user matches all users.

Users and groups: pkg/auth/user/user.go.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class UserInfo:
    """pkg/auth/user/user.go DefaultInfo."""

    name: str
    uid: str = ""
    groups: Tuple[str, ...] = ()


class AuthenticationError(Exception):
    """Surfaces as HTTP 401."""


# -- authenticators (pkg/apiserver/authn.go) --------------------------------


class PasswordAuthenticator:
    """Basic auth against an in-memory map or a CSV file of
    password,username,uid lines (passwordfile.go)."""

    def __init__(self, users: Optional[Dict[str, Tuple[str, UserInfo]]] = None):
        # username -> (password, UserInfo)
        self.users = users or {}

    @classmethod
    def from_file(cls, path: str) -> "PasswordAuthenticator":
        users: Dict[str, Tuple[str, UserInfo]] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = [p.strip() for p in line.split(",")]
                if len(parts) < 3:
                    raise ValueError(f"malformed password file line: {line!r}")
                password, name, uid = parts[0], parts[1], parts[2]
                users[name] = (password, UserInfo(name=name, uid=uid))
        return cls(users)

    def authenticate_password(self, username: str, password: str) -> UserInfo:
        entry = self.users.get(username)
        if entry is None or not hmac.compare_digest(
            entry[0].encode(), password.encode()
        ):
            raise AuthenticationError("invalid username/password")
        return entry[1]


class TokenAuthenticator:
    """Bearer tokens from a CSV file of token,username,uid[,groups]
    lines (tokenfile.go)."""

    def __init__(self, tokens: Optional[Dict[str, UserInfo]] = None):
        self.tokens = tokens or {}

    @classmethod
    def from_file(cls, path: str) -> "TokenAuthenticator":
        tokens: Dict[str, UserInfo] = {}
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = [p.strip() for p in line.split(",")]
                if len(parts) < 3:
                    raise ValueError(f"malformed token file line: {line!r}")
                token, name, uid = parts[0], parts[1], parts[2]
                groups = tuple(g for g in parts[3:] if g)
                tokens[token] = UserInfo(name=name, uid=uid, groups=groups)
        return cls(tokens)

    def authenticate_token(self, token: str) -> UserInfo:
        info = self.tokens.get(token)
        if info is None:
            raise AuthenticationError("invalid bearer token")
        return info


# -- service-account JWTs (pkg/serviceaccount/jwt.go) -----------------------

ISSUER = "kubernetes-tpu/serviceaccount"
_SA_CLAIM_PREFIX = "kubernetes.io/serviceaccount/"
SERVICE_ACCOUNT_USERNAME_PREFIX = "system:serviceaccount:"


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    pad = "=" * (-len(s) % 4)
    return base64.urlsafe_b64decode(s + pad)


class ServiceAccountTokenManager:
    """Mint and verify service-account JWTs (HS256)."""

    def __init__(self, signing_key: bytes):
        self.key = signing_key

    def mint(
        self, namespace: str, name: str, uid: str = "", secret_name: str = ""
    ) -> str:
        header = {"alg": "HS256", "typ": "JWT"}
        claims = {
            "iss": ISSUER,
            "sub": f"{SERVICE_ACCOUNT_USERNAME_PREFIX}{namespace}:{name}",
            _SA_CLAIM_PREFIX + "namespace": namespace,
            _SA_CLAIM_PREFIX + "service-account.name": name,
            _SA_CLAIM_PREFIX + "service-account.uid": uid,
            _SA_CLAIM_PREFIX + "secret.name": secret_name,
        }
        signing_input = f"{_b64url(json.dumps(header).encode())}.{_b64url(json.dumps(claims).encode())}"
        sig = hmac.new(self.key, signing_input.encode(), hashlib.sha256).digest()
        return f"{signing_input}.{_b64url(sig)}"

    def authenticate_token(self, token: str) -> UserInfo:
        try:
            header_b64, claims_b64, sig_b64 = token.split(".")
            signing_input = f"{header_b64}.{claims_b64}".encode()
            expected = hmac.new(self.key, signing_input, hashlib.sha256).digest()
            if not hmac.compare_digest(expected, _b64url_decode(sig_b64)):
                raise AuthenticationError("invalid token signature")
            claims = json.loads(_b64url_decode(claims_b64))
        except (ValueError, binascii.Error, json.JSONDecodeError):
            raise AuthenticationError("malformed service account token")
        if claims.get("iss") != ISSUER:
            raise AuthenticationError("unrecognized token issuer")
        ns = claims.get(_SA_CLAIM_PREFIX + "namespace", "")
        name = claims.get(_SA_CLAIM_PREFIX + "service-account.name", "")
        if not ns or not name:
            raise AuthenticationError("token missing service account claims")
        return UserInfo(
            name=f"{SERVICE_ACCOUNT_USERNAME_PREFIX}{ns}:{name}",
            uid=claims.get(_SA_CLAIM_PREFIX + "service-account.uid", ""),
            groups=("system:serviceaccounts", f"system:serviceaccounts:{ns}"),
        )


class X509Authenticator:
    """Client-certificate authentication: a TLS peer certificate's
    Subject CommonName is the username and its Organization values are
    the groups — the reference's x509 request authenticator with the
    CommonNameUserConversion (pkg/apiserver/authn.go:35,
    plugin/pkg/auth/authenticator/request/x509/x509.go). Chain
    verification against --client-ca-file happens in the TLS handshake
    (ssl.CERT_OPTIONAL); by the time a peer cert reaches this class it
    is already CA-verified."""

    def authenticate_peer_cert(self, peercert: dict) -> UserInfo:
        """`peercert` is ssl.SSLSocket.getpeercert()'s dict form."""
        if not peercert:
            raise AuthenticationError("no client certificate presented")
        name = ""
        groups: List[str] = []
        for rdn in peercert.get("subject", ()):
            for key, value in rdn:
                if key == "commonName" and not name:
                    name = value
                elif key == "organizationName":
                    groups.append(value)
        if not name:
            raise AuthenticationError("client certificate has no CommonName")
        return UserInfo(name=name, groups=tuple(groups))


class UnionAuthenticator:
    """Try each authenticator in order (union.go)."""

    def __init__(
        self,
        password: Optional[PasswordAuthenticator] = None,
        tokens: Optional[List] = None,
    ):
        self.password = password
        self.tokens = tokens or []

    def authenticate_request(self, authorization_header: str) -> UserInfo:
        """Parse an Authorization header (Basic or Bearer)."""
        if not authorization_header:
            raise AuthenticationError("no credentials provided")
        scheme, _, rest = authorization_header.partition(" ")
        scheme = scheme.lower()
        if scheme == "basic" and self.password is not None:
            try:
                decoded = base64.b64decode(rest.strip()).decode()
                username, _, password = decoded.partition(":")
            except (binascii.Error, UnicodeDecodeError):
                raise AuthenticationError("malformed basic auth header")
            return self.password.authenticate_password(username, password)
        if scheme == "bearer":
            token = rest.strip()
            last_err: Optional[AuthenticationError] = None
            for t in self.tokens:
                try:
                    return t.authenticate_token(token)
                except AuthenticationError as e:
                    last_err = e
            raise last_err or AuthenticationError("no token authenticator")
        raise AuthenticationError(f"unsupported authorization scheme {scheme!r}")


# -- ABAC authorizer (pkg/auth/authorizer/abac/abac.go) ---------------------


class AuthorizationError(Exception):
    """Surfaces as HTTP 403."""


@dataclass
class AuthzAttributes:
    """pkg/auth/authorizer/interfaces.go Attributes."""

    user: UserInfo
    readonly: bool = False
    resource: str = ""
    namespace: str = ""


@dataclass
class Policy:
    """One ABAC policy line. Empty fields match everything."""

    user: str = ""
    group: str = ""
    readonly: bool = False  # True limits to read-only verbs
    resource: str = ""
    namespace: str = ""

    def matches(self, a: AuthzAttributes) -> bool:
        if self.user and self.user != "*" and self.user != a.user.name:
            return False
        if self.group and self.group != "*" and self.group not in a.user.groups:
            return False
        if self.readonly and not a.readonly:
            return False
        if self.resource and self.resource != "*" and self.resource != a.resource:
            return False
        if (
            self.namespace
            and self.namespace != "*"
            and self.namespace != a.namespace
        ):
            return False
        return True


class ABACAuthorizer:
    """Policy-list authorizer; any matching line allows."""

    def __init__(self, policies: List[Policy]):
        self.policies = policies

    @classmethod
    def from_file(cls, path: str) -> "ABACAuthorizer":
        policies: List[Policy] = []
        with open(path) as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    raw = json.loads(line)
                except json.JSONDecodeError as e:
                    raise ValueError(f"{path}:{i}: invalid policy JSON: {e}")
                policies.append(
                    Policy(
                        user=raw.get("user", ""),
                        group=raw.get("group", ""),
                        readonly=bool(raw.get("readonly", False)),
                        resource=raw.get("resource", ""),
                        namespace=raw.get("namespace", ""),
                    )
                )
        return cls(policies)

    def authorize(self, attrs: AuthzAttributes) -> None:
        for p in self.policies:
            if p.matches(attrs):
                return
        raise AuthorizationError(
            f"user {attrs.user.name!r} is not allowed to "
            f"{'read' if attrs.readonly else 'write'} {attrs.resource or '*'}"
            + (f" in {attrs.namespace}" if attrs.namespace else "")
        )


class AlwaysAllowAuthorizer:
    def authorize(self, attrs: AuthzAttributes) -> None:
        return None


class AlwaysDenyAuthorizer:
    def authorize(self, attrs: AuthzAttributes) -> None:
        raise AuthorizationError("always deny")
