"""HTTP transport for the API server.

Reference: route installation in pkg/apiserver/api_installer.go:268-284
and the chunked-JSON watch server (pkg/apiserver/watch.go:45-102).

Routes (all under /api/v1):
    GET|POST   /{resource}                          cluster-scoped or all-ns
    GET|PUT|DELETE /{resource}/{name}               cluster-scoped
    GET|POST   /namespaces/{ns}/{resource}
    GET|PUT|DELETE /namespaces/{ns}/{resource}/{name}
    PUT        /namespaces/{ns}/{resource}/{name}/status
    POST       /namespaces/{ns}/bindings
    POST       /namespaces/{ns}/pods/{name}/binding
    GET        /watch/{resource}            (+ /watch/namespaces/{ns}/{resource})
Plus /healthz, /metrics, /version, /api.

Watch responses are chunked newline-delimited JSON frames
{"type": ..., "object": ...} — same wire shape as the reference.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu import __version__
from kubernetes_tpu.models import conversion
from kubernetes_tpu.server.api import APIError, APIServer
from kubernetes_tpu.server.registry import RESOURCES
from kubernetes_tpu.utils import metrics, sli, tracing

_REQS = metrics.DEFAULT.counter(
    "apiserver_request_count", "API requests by verb/resource/code",
    ("verb", "resource", "code"),
)
# Histogram (not summary): bucketed latencies aggregate across
# scrapes/instances and the SLO gate reads interpolated quantiles off
# the same series (the reference moved the scheduler/apiserver SLO
# metrics the same way).
_LATENCY = metrics.DEFAULT.histogram(
    "apiserver_request_latencies_seconds", "API request latency",
    ("verb", "resource"),
)
_INFLIGHT_REJECTS = metrics.DEFAULT.counter(
    "apiserver_dropped_requests_total",
    "Requests rejected by the max-in-flight limit",
)


def _first_container_port(pod: dict, name: str) -> int:
    """The pod's first declared container port — the default target for
    the proxy and redirect verbs when the client gives no ':port'."""
    for c in pod.get("spec", {}).get("containers", []):
        for p in c.get("ports", []):
            if p.get("containerPort", 0):
                return p["containerPort"]
    raise APIError(
        400, "BadRequest",
        f"pod {name!r} declares no container port; use {name}:<port>",
    )


#: Subresource suffixes whose requests are long-running by design —
#: exempt from the latency SLO exactly like the reference's ignored
#: verbs/resources (test/e2e/util.go:1286-1301 skips WATCHLIST/PROXY).
_LONG_RUNNING = ("watch", "proxy", "portforward", "exec", "run", "log")


def _request_is_long_running(parts, query) -> bool:
    """Max-in-flight passthrough test (pkg/apiserver/handlers.go
    MaxInFlightLimit: requests matching the long-running regex bypass
    the limit — a hung watch or kubelet relay must not eat a slot
    forever). Like the reference's regex, 'proxy' etc. match ANYWHERE
    in the path: proxy requests carry subpaths after the verb."""
    if query.get("watch") in ("true", "1"):
        return True
    if any(p in ("watch", "proxy", "portforward", "exec", "run") for p in parts):
        return True
    return (
        bool(parts)
        and parts[-1] == "log"
        and query.get("follow") in ("true", "1")
    )


def reset_request_latency() -> None:
    """Start a fresh measurement window on the process-global request
    latency summary. The reference's e2e SLO gate scrapes a freshly
    started cluster's apiserver (test/e2e/util.go:1286); in-process
    suites share ONE registry across many clusters, so a test gating
    on p99 must open its own window or it inherits every earlier
    test's observations."""
    _LATENCY.reset()


def high_latency_requests(threshold: float = 1.0, summary=None):
    """The HighLatencyRequests SLO gate (reference: test/e2e/
    util.go:1286 scrapes apiserver request-latency summaries and fails
    e2e when p99 exceeds the roadmap's 1 s bar, docs/roadmap.md:69).
    Returns [(verb, resource, p99_seconds)] violations. `summary`
    defaults to the live apiserver latency series; tests pass their
    own so suites sharing the process-global registry can't pollute
    each other's gates."""
    summary = summary if summary is not None else _LATENCY
    keys = summary.label_values()
    out = []
    for verb, resource in keys:
        if resource.rsplit("/", 1)[-1] in _LONG_RUNNING:
            continue
        p99 = summary.quantile(0.99, verb=verb, resource=resource)
        if p99 == p99 and p99 > threshold:  # NaN-safe
            out.append((verb, resource, p99))
    return sorted(out)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "kubernetes-tpu-apiserver"
    # Nagle + delayed-ACK interact catastrophically with keep-alive
    # request/response traffic (~40ms stalls per request on loopback);
    # the reference's Go net/http also runs with TCP_NODELAY.
    disable_nagle_algorithm = True
    api: APIServer  # set by serve()
    # Inbound protection (pkg/apiserver/handlers.go MaxInFlightLimit,
    # wired at pkg/master/master.go): a BoundedSemaphore shared by all
    # handler threads, or None for unlimited. Long-running requests
    # (watch/exec/proxy/...) bypass it.
    inflight = None

    # Silence default stderr logging; metrics carry the signal.
    def log_message(self, fmt, *args):  # noqa: N802
        pass

    # -- plumbing -----------------------------------------------------

    def _send_json(self, code: int, obj: dict) -> None:
        version = getattr(self, "wire_version", "v1")
        if version != "v1":
            obj = conversion.from_internal(obj, version)
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(
        self, code: int, body, content_type: str = "text/plain"
    ) -> None:
        data = body.encode() if isinstance(body, str) else body
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _read_body(self, kind_hint: str = "") -> dict:
        """Parse (and version-convert) the request body. `kind_hint` is
        the kind implied by the route: the API accepts kind-less bodies
        (api.create setdefaults kind from the path), but conversion
        dispatches ON kind — a kind-less v1beta3 body would silently
        skip conversion and store legacy field names internally."""
        length = int(self.headers.get("Content-Length", 0) or 0)
        if length == 0:
            return {}
        raw = self.rfile.read(length)
        try:
            body = json.loads(raw)
        except json.JSONDecodeError as e:
            raise APIError(400, "BadRequest", f"invalid JSON body: {e}")
        version = getattr(self, "wire_version", "v1")
        if version != "v1" and isinstance(body, dict):
            if kind_hint and not body.get("kind"):
                body["kind"] = kind_hint
            body = conversion.to_internal(body, version)
        return body

    def _kind_of(self, resource: str) -> str:
        info = RESOURCES.get(resource)
        return info.kind if info is not None else ""

    def _serve_ui(self) -> None:
        """Live dashboard (reference: pkg/ui serves the www/ AngularJS
        app at /ui/; ours is an original self-contained SPA that polls
        the REST API — hash-routed per-resource views, auto-refresh)."""
        self._send_text(200, _UI_PAGE, "text/html; charset=utf-8")

    def _serve_debug(self, rest: Tuple[str, ...]) -> None:
        from kubernetes_tpu.utils import debug, flightrecorder

        def _limit() -> int:
            try:
                return int(self.query.get("limit", "64"))
            except ValueError:
                raise APIError(400, "BadRequest", "limit must be numeric")

        if rest == ("traces",):
            # Recent scheduling traces (this process's buffer — the
            # in-process cluster topology shares one buffer across all
            # daemons), filterable to traces touching one pod.
            self._send_text(
                200,
                tracing.render_json(
                    pod=self.query.get("pod", ""), limit=_limit()
                ),
                "application/json",
            )
            return
        if rest == ("decisions",):
            # The scheduling flight recorder: per-pod decisions with
            # explain verdicts (ktctl explain's data source), joined
            # with /debug/traces by traceId.
            self._send_text(
                200,
                flightrecorder.render_decisions_json(
                    pod=self.query.get("pod", ""), limit=_limit()
                ),
                "application/json",
            )
            return
        if rest == ("solves",):
            # Per-tick solve records: mode, duration, wave/Sinkhorn
            # convergence telemetry, traceId.
            self._send_text(
                200,
                flightrecorder.render_solves_json(limit=_limit()),
                "application/json",
            )
            return
        if rest == ("slo",):
            # The SLO engine over the live metrics registry: per-
            # objective pass/warn/burn verdicts (utils/slo.py; the data
            # behind `ktctl slo` and the check.sh SLO smoke).
            from kubernetes_tpu.utils import slo

            self._send_text(
                200, json.dumps(slo.evaluate()), "application/json"
            )
            return
        if rest == ("capacity",):
            # The capacity & fragmentation plane (utils/capacity.py):
            # last sample's fragmentation score, probe-shape headroom
            # table, top-k stranded nodes, per-node utilization and the
            # fragmentation trend ring — `ktctl top capacity`'s data
            # source. A cluster whose scheduler never sampled returns
            # sampled:false (the ktctl miss contract keys on it). The
            # module keeps jax off its import path, so a thin
            # control-plane apiserver can serve the cold shape.
            from kubernetes_tpu.utils import capacity

            self._send_text(
                200,
                json.dumps(capacity.DEFAULT.snapshot()),
                "application/json",
            )
            return
        if rest == ("rebalance",):
            # The rebalancing plane (utils/rebalance.py): last defrag
            # plan/cycle, move-outcome table and improvement trend —
            # `ktctl rebalance`'s data source. sampled:false until the
            # descheduler executes its first cycle (the ktctl miss
            # contract keys on it); jax stays off the import path so a
            # thin apiserver can serve the cold shape.
            from kubernetes_tpu.utils import rebalance

            self._send_text(
                200,
                json.dumps(rebalance.DEFAULT.snapshot()),
                "application/json",
            )
            return
        if rest == ("kernels",):
            # The XLA compile/cost ledger (ops/ledger.py): per-kernel
            # compile events with cost/memory analysis — `ktctl profile
            # kernels`' data source. Each shape row carries a
            # `contract` verdict (ops/contracts.py): the observed
            # staged-shape signature joined against the kernel's
            # declared contract, so a drifted bucket reads as
            # "mismatch: dim P=... off its lattice" right here. A
            # process that never dispatched a kernel has an empty
            # ledger BY DEFINITION, so the module is read from
            # sys.modules instead of imported: a thin control-plane
            # apiserver must not load jax to say "no compiles
            # recorded".
            import sys as _sys

            led = _sys.modules.get("kubernetes_tpu.ops.ledger")
            payload = (
                led.DEFAULT.to_dict()
                if led is not None
                else {"kernels": [], "summary": {"compiles": 0}}
            )
            self._send_text(
                200, json.dumps(payload), "application/json"
            )
            return
        if rest == ("device-profile",):
            # On-demand device trace (utils/profiler.py wrapping
            # jax.profiler.trace): blocks this handler thread for
            # ?seconds= while every other thread's dispatches land in
            # the trace; returns the server-side directory.
            from kubernetes_tpu.utils import profiler

            try:
                seconds = float(self.query.get("seconds", "2"))
            except ValueError:
                raise APIError(400, "BadRequest", "seconds must be numeric")
            try:
                info = profiler.capture_device_trace(seconds=seconds)
            except profiler.TraceInProgress as e:
                raise APIError(409, "Conflict", str(e))
            except profiler.ProfilerUnavailable as e:
                raise APIError(503, "ServiceUnavailable", str(e))
            self._send_text(200, json.dumps(info), "application/json")
            return
        if rest == ("alerts",):
            # The burn-rate alert engine (utils/alerts.py): per-rule
            # state machine snapshot + recent transitions — `ktctl
            # alerts`' data source. sampled:false until the health
            # plane evaluated at least once over a sampled retention
            # store (the ktctl miss contract keys on it).
            from kubernetes_tpu.utils import alerts

            self._send_text(
                200, json.dumps(alerts.DEFAULT.snapshot()),
                "application/json",
            )
            return
        if rest == ("timeseries",):
            # The retention plane (utils/timeseries.py): series
            # inventory, or — with ?series= — windowed figures
            # (rate/increase/delta/quantiles) per label set over
            # ?window= seconds.
            from kubernetes_tpu.utils import timeseries

            try:
                window_s = float(self.query.get("window", "300"))
            except ValueError:
                raise APIError(400, "BadRequest", "window must be numeric")
            self._send_text(
                200,
                json.dumps(
                    timeseries.DEFAULT.snapshot(
                        series=self.query.get("series", ""),
                        window_s=window_s,
                    )
                ),
                "application/json",
            )
            return
        if rest == ("health",):
            self._serve_debug_health()
            return
        if rest == ("requests",):
            body = debug.DEFAULT_REQUEST_LOG.render()
        elif rest == ("stacks",):
            body = debug.dump_stacks()
        elif rest == ("profile",):
            try:
                seconds = float(self.query.get("seconds", "2"))
            except ValueError:
                raise APIError(400, "BadRequest", "seconds must be numeric")
            fmt = self.query.get("format", "top")
            if fmt not in ("top", "collapsed"):
                raise APIError(
                    400, "BadRequest", "format must be top or collapsed"
                )
            body = debug.sample_profile(seconds=seconds, fmt=fmt)
        else:
            raise APIError(
                404, "NotFound",
                "debug endpoints: /debug/requests /debug/stacks "
                "/debug/profile /debug/traces /debug/decisions "
                "/debug/solves /debug/slo /debug/kernels "
                "/debug/capacity /debug/rebalance /debug/device-profile "
                "/debug/alerts /debug/timeseries /debug/health",
            )
        self._send_text(200, body, "text/plain; charset=utf-8")

    def _health_checks(self) -> dict:
        """The /healthz subcheck dict (kvstore, watch hub, replication,
        flight recorder) — also the component half of the /debug/health
        rollup, so the probe and the rollup can never disagree about a
        dependency's state."""
        from kubernetes_tpu.utils import flightrecorder

        checks = {}
        try:
            store = self.api.store
            if store.closed:
                checks["kvstore"] = {
                    "status": "unhealthy", "message": "store closed",
                }
            else:
                checks["kvstore"] = {
                    "status": "ok", "resourceVersion": store.version,
                }
        except Exception as e:
            checks["kvstore"] = {"status": "unhealthy", "message": str(e)}
        try:
            alive = self.api.store.dispatcher_alive()
            checks["watchHub"] = (
                {"status": "ok"}
                if alive
                else {
                    "status": "unhealthy",
                    "message": "watch dispatcher thread dead",
                }
            )
        except Exception as e:
            checks["watchHub"] = {"status": "unhealthy", "message": str(e)}
        rep = getattr(self.api, "replication", None)
        if rep is not None:
            # HA subcheck: role + commit index + per-follower lag
            # (leader side) or journaled/commit watermarks (follower).
            # A dead follower link flips the check unhealthy — the
            # load balancer should stop preferring this replica's
            # writes before quorum stalls, not after.
            try:
                st = rep.status()
                followers = st.get("followers", [])
                dead = [
                    f["name"] for f in followers if not f.get("alive", True)
                ]
                check = {
                    "status": "unhealthy" if dead else "ok",
                    "role": st.get("role", ""),
                    "commitIndex": st.get("commitIndex", 0),
                    "followerLag": {
                        f["name"]: f.get("lagVersions", 0)
                        for f in followers
                    },
                }
                if dead:
                    check["message"] = (
                        "unreachable followers: " + ", ".join(dead)
                    )
                checks["replication"] = check
            except Exception as e:
                checks["replication"] = {
                    "status": "unhealthy", "message": str(e),
                }
        try:
            size, cap = flightrecorder.DEFAULT.ring_stats()
            checks["flightRecorder"] = (
                {"status": "ok", "decisions": size, "capacity": cap}
                if size <= cap
                else {
                    "status": "unhealthy",
                    "message": f"ring overflow: {size} > {cap}",
                }
            )
        except Exception as e:
            checks["flightRecorder"] = {
                "status": "unhealthy", "message": str(e),
            }
        return checks

    def _serve_healthz(self) -> None:
        """/healthz with JSON subchecks (kvstore, watch hub, flight
        recorder), 200 only when every check passes — the reference's
        bare "ok" told an operator nothing about WHICH dependency was
        sick. Stays ahead of the auth chain like the plain probe did
        (load balancers and kubelets probe unauthenticated)."""
        checks = self._health_checks()
        healthy = all(c.get("status") == "ok" for c in checks.values())
        self._send_json(
            200 if healthy else 503,
            {
                "kind": "Health",
                "status": "ok" if healthy else "unhealthy",
                "checks": checks,
            },
        )

    #: A follower trailing the leader's commit index by more than this
    #: many versions verdicts the replication component "warn" before
    #: the link actually dies (mirrors the alert rule's threshold).
    _REPLICATION_LAG_WARN = 1024
    #: A lease record whose renew timestamp is older than this reads
    #: stale — holders renew every ~1s against 5s windows, so 30s of
    #: silence means the tier is leaderless or wedged.
    _LEASE_STALE_S = 30.0

    def _serve_debug_health(self) -> None:
        """GET /debug/health: the HA-aware rollup. Joins the /healthz
        subchecks, /replication/status (role, commit index, follower
        lag), the lease records in kube-system, the SLO report, and
        the alert engine into per-component pass/warn/burn verdicts
        plus one overall worst — the `ktctl top health` data source.
        `sampled` keys the miss contract: an unmeasured cluster (no
        SLI samples AND no alert evaluations) exits the CLI 1."""
        from kubernetes_tpu.utils import alerts, slo

        checks = self._health_checks()
        components = {}
        for name, c in checks.items():
            comp = dict(c)
            comp["verdict"] = "pass" if c.get("status") == "ok" else "burn"
            components[name] = comp
        rep = components.get("replication")
        if rep is not None and rep["verdict"] == "pass":
            # Alive links can still be falling behind: sustained lag is
            # the pre-quorum-loss signal (warn, not burn — the link is
            # up and catching up is still possible).
            lag = max(rep.get("followerLag", {}).values(), default=0)
            if lag > self._REPLICATION_LAG_WARN:
                rep["verdict"] = "warn"
                rep["message"] = f"follower lag {lag} versions"
        # Lease tier: every lease record in kube-system (scheduler
        # standby, kvstore tiers) with holder/token/age. A stale or
        # holderless lease is warn — the tier is between leaders, which
        # the warm standby exists to make brief.
        try:
            from kubernetes_tpu.utils.lease import (
                HOLDER_KEY,
                RENEW_KEY,
                TOKEN_KEY,
            )

            items = self.api.list("endpoints", namespace="kube-system")[
                "items"
            ]
            leases = []
            verdict = "pass"
            now = time.time()
            for obj in items:
                ann = (obj.get("metadata", {}) or {}).get(
                    "annotations", {}
                ) or {}
                if HOLDER_KEY not in ann:
                    continue
                try:
                    renewed = float(ann.get(RENEW_KEY, "0") or "0")
                except ValueError:
                    renewed = 0.0
                age = max(0.0, now - renewed) if renewed else None
                stale = age is None or age > self._LEASE_STALE_S
                holder = ann.get(HOLDER_KEY, "")
                leases.append(
                    {
                        "name": obj.get("metadata", {}).get("name", ""),
                        "holder": holder,
                        "token": ann.get(TOKEN_KEY, ""),
                        "ageS": None if age is None else round(age, 1),
                        "stale": stale,
                    }
                )
                if stale or not holder:
                    verdict = "warn"
            if leases:
                components["leases"] = {
                    "status": "ok" if verdict == "pass" else "stale",
                    "verdict": verdict,
                    "leases": leases,
                }
        except Exception as e:
            components["leases"] = {
                "status": "unhealthy", "verdict": "warn", "message": str(e),
            }
        slo_report = slo.evaluate()
        components["slo"] = {
            "status": slo_report["verdict"],
            "verdict": (
                slo_report["verdict"]
                if slo_report["verdict"] != "no_data"
                else "pass"
            ),
            "sampled": slo_report["sampled"],
            "objectivesBurning": [
                e["name"]
                for e in slo_report["objectives"]
                if e["verdict"] in ("warn", "burn")
            ],
        }
        alert_snap = alerts.DEFAULT.snapshot()
        firing = alert_snap["firing"]
        sev = {
            r["name"]: r["severity"] for r in alert_snap["rules"]
        }
        if not alert_snap["sampled"]:
            alert_verdict = "pass"  # unmeasured: surfaced via `sampled`
        elif any(sev.get(n) == "page" for n in firing):
            alert_verdict = "burn"
        elif firing:
            alert_verdict = "warn"
        else:
            alert_verdict = "pass"
        components["alerts"] = {
            "status": "firing" if firing else "ok",
            "verdict": alert_verdict,
            "sampled": alert_snap["sampled"],
            "firing": firing,
            "evaluations": alert_snap["evaluations"],
        }
        overall = slo.worst(
            *[c["verdict"] for c in components.values()]
        )
        self._send_json(
            200,
            {
                "kind": "HealthRollup",
                "verdict": overall,
                "sampled": bool(
                    slo_report["sampled"] or alert_snap["sampled"]
                ),
                "components": components,
            },
        )

    def _serve_replication(self, verb: str, rest: Tuple[str, ...]) -> None:
        """The WAL-shipping ingest plane (store/replication.py).

        POST /replication/append — leader hub -> this follower:
        {"lines": [...], "commit": N} journals + applies; {"bootstrap":
        state} installs a dump_state() snapshot; commit=-1 is a pure
        status probe. Bodies are internal wire format — no version
        conversion, no auth (peer plane, like /healthz).
        GET /replication/status — role/commit/lag introspection."""
        rep = getattr(self.api, "replication", None)
        if rest == ("status",) and verb == "GET":
            if rep is None:
                raise APIError(
                    404, "NotFound", "replication not configured"
                )
            self._send_json(200, rep.status())
            return
        if rest != ("append",) or verb != "POST":
            raise APIError(
                404, "NotFound",
                "replication endpoints: POST /replication/append, "
                "GET /replication/status",
            )
        from kubernetes_tpu.store.replication import (
            FollowerReplica,
            ReplicationError,
        )

        if not isinstance(rep, FollowerReplica):
            raise APIError(
                409, "Conflict",
                "this apiserver does not front a follower replica",
            )
        length = int(self.headers.get("Content-Length", 0) or 0)
        try:
            body = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError as e:
            raise APIError(400, "BadRequest", f"invalid JSON body: {e}")
        try:
            if "bootstrap" in body:
                rep.bootstrap(body["bootstrap"])
                journaled = rep.store.journaled_version
            else:
                journaled = rep.append(
                    list(body.get("lines", ())),
                    int(body.get("commit", -1)),
                )
        except ReplicationError as e:
            # 409: the shipper must NOT retry into a promoted follower
            # (a stale leader's stream) — it surfaces as a dead link.
            raise APIError(409, "Conflict", str(e))
        self._send_json(200, dict(rep.status(), journaled=journaled))

    def _forward_leader(self, verb: str) -> Tuple[str, int]:
        """Follower write path: relay the request verbatim to the
        leader apiserver and pass its response through. The follower
        stays a pure read fan-out — its store is a replica and refuses
        local mutation; clients keep one endpoint list and never need
        to know who leads (the reference gets this for free from etcd:
        any member proxies writes to the raft leader)."""
        import urllib.error
        import urllib.request

        url = self.api.leader_url.rstrip("/") + self.path
        length = int(self.headers.get("Content-Length", 0) or 0)
        data = self.rfile.read(length) if length else None
        headers = {}
        for h in ("Content-Type", "Authorization"):
            if self.headers.get(h):
                headers[h] = self.headers[h]
        # One trace end-to-end across the hop: reuse the client's
        # X-Trace-Id when it stamped one; otherwise mint an id HERE so
        # the follower's request-log entry and the leader's carry the
        # same trace id (before this, an unstamped forwarded mutation
        # appeared as two unrelated requests at /debug/requests).
        tid = (
            self.headers.get(tracing.TRACE_HEADER)
            or tracing.current_trace_id()
            or tracing.new_trace_id()
        )
        headers[tracing.TRACE_HEADER] = tid
        self._request_trace_id = tid
        req = urllib.request.Request(
            url, data=data, headers=headers, method=verb
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                body = resp.read()
                code = resp.status
                ctype = resp.headers.get("Content-Type", "application/json")
        except urllib.error.HTTPError as e:
            body = e.read()
            code = e.code
            ctype = e.headers.get("Content-Type", "application/json")
        except urllib.error.URLError as e:
            raise APIError(
                502, "BadGateway", f"leader forward failed: {e}"
            )
        self._send_text(code, body, ctype)
        return "forwarded", code

    def _route(self) -> Tuple[str, ...]:
        parsed = urlparse(self.path)
        self.query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        return tuple(s for s in parsed.path.split("/") if s)

    # -- verbs --------------------------------------------------------

    def do_GET(self):  # noqa: N802
        self._dispatch("GET")

    def do_POST(self):  # noqa: N802
        self._dispatch("POST")

    def do_PUT(self):  # noqa: N802
        self._dispatch("PUT")

    def do_DELETE(self):  # noqa: N802
        self._dispatch("DELETE")

    def do_PATCH(self):  # noqa: N802
        self._dispatch("PATCH")

    def _dispatch(self, verb: str) -> None:
        # Propagated request trace (Dapper hop): a client that stamped
        # X-Trace-Id gets this request recorded as a span under ITS
        # trace id — the scheduler's bind call and the apiserver's
        # handling merge into one trace at /debug/traces. No header,
        # no cost. (In-process LocalTransport calls skip HTTP entirely
        # and join the caller's trace via the contextvar instead.)
        tid = self.headers.get(tracing.TRACE_HEADER)
        # Stashed for the request log (reset per request — keep-alive
        # reuses this handler instance): /debug/requests entries join
        # /debug/traces on it.
        self._request_trace_id = tid or ""
        if not tid:
            return self._dispatch_inner(verb)
        with tracing.trace(
            f"{verb} {urlparse(self.path).path}", trace_id=tid
        ):
            return self._dispatch_inner(verb)

    def _dispatch_inner(self, verb: str) -> None:
        start = time.monotonic()
        resource = ""
        code = 200
        # Reset per request: keep-alive connections reuse this handler
        # instance, and a prior request's version must not leak.
        self.wire_version = "v1"
        try:
            parts = self._route()
            if parts == ("healthz",):
                self._serve_healthz()
                return
            if parts and parts[0] == "replication":
                # Internal replication plane (store/replication.py
                # HTTPLink): peer traffic, ahead of the auth chain like
                # /healthz — the WAL stream must keep flowing while the
                # user-facing auth config churns.
                self._serve_replication(verb, parts[1:])
                return
            if parts == ("metrics",):
                self._send_text(
                    200, metrics.DEFAULT.render(), "text/plain; version=0.0.4"
                )
                return
            if parts == ("version",):
                self._send_json(200, {"gitVersion": __version__, "platform": "tpu"})
                return
            if parts == ("validate",):
                # Component validation report (pkg/apiserver/validator.go):
                # probe every registered component; 500 when any fails.
                statuses = self.api.list("componentstatuses")["items"]
                report = []
                all_healthy = bool(statuses)
                for cs in statuses:
                    cond = (cs.get("conditions") or [{}])[0]
                    healthy = cond.get("status") == "True"
                    all_healthy = all_healthy and healthy
                    report.append(
                        {
                            "component": cs["metadata"]["name"],
                            "health": "ok" if healthy else "unhealthy",
                            "msg": cond.get("message", ""),
                        }
                    )
                self._send_json(200 if all_healthy else 500, {"validate": report})
                return
            if parts == ("api",):
                self._send_json(
                    200,
                    {"kind": "APIVersions", "versions": list(conversion.VERSIONS)},
                )
                return
            if parts and parts[0] == "debug":
                # Debug surfaces (pkg/httplog + net/http/pprof analogs),
                # behind the same auth chain as the API.
                self._check_auth(verb, parts)
                self._serve_debug(parts[1:])
                return
            if parts == ("swagger.json",) or parts == ("swaggerapi",):
                # API discovery document (reference serves swagger 1.2
                # from api/swagger-spec/ via pkg/apiserver; ours is
                # generated from the live resource registry). Behind
                # the same auth chain as the API (master.go wraps the
                # FULL mux, UI included).
                self._check_auth(verb, parts)
                self._send_json(200, _swagger_doc())
                return
            if parts and parts[0] == "swagger-ui":
                # Interactive API browser over /swagger.json (the
                # reference vendors third_party/swagger-ui/ and wires
                # it in pkg/master/master.go; ours is a self-contained
                # page — zero-egress box, no external assets).
                self._check_auth(verb, parts)
                self._send_text(
                    200, _SWAGGER_UI_PAGE, "text/html; charset=utf-8"
                )
                return
            if parts and parts[0] == "ui":
                # Any /ui/* path serves the SPA (it hash-routes
                # client-side, like the reference's app shell).
                self._check_auth(verb, parts)
                self._serve_ui()
                return
            if (
                len(parts) < 2
                or parts[0] != "api"
                or parts[1] not in conversion.VERSIONS
            ):
                raise APIError(404, "NotFound", f"unknown path {self.path!r}")
            # Multi-version negotiation (pkg/api/latest/latest.go:32-78):
            # bodies decode from — and responses encode to — the path's
            # version; the registry/store speak internal (v1) only.
            self.wire_version = parts[1]
            rest = parts[2:]
            self._check_auth(verb, rest)
            sem = self.inflight
            if sem is None or _request_is_long_running(rest, self.query):
                resource, code = self._api_v1(verb, rest)
            elif sem.acquire(blocking=False):
                try:
                    resource, code = self._api_v1(verb, rest)
                finally:
                    sem.release()
            else:
                _INFLIGHT_REJECTS.inc()
                raise APIError(
                    429, "TooManyRequests",
                    "too many requests in flight; retry",
                )
        except APIError as e:
            code = e.code
            self._send_json(e.code, e.to_status())
        except (BrokenPipeError, ConnectionResetError):
            code = 499
        except Exception as e:  # pragma: no cover - crash containment
            code = 500
            try:
                self._send_json(
                    500,
                    {
                        "kind": "Status",
                        "status": "Failure",
                        "reason": "InternalError",
                        "message": str(e),
                        "code": 500,
                    },
                )
            except Exception:  # ktlint: disable=KT003
                pass  # client already gone; the 500 has nowhere to go
        finally:
            duration = time.monotonic() - start
            _REQS.inc(verb=verb, resource=resource, code=str(code))
            _LATENCY.observe(duration, verb=verb, resource=resource)
            from kubernetes_tpu.utils import debug

            debug.DEFAULT_REQUEST_LOG.record(
                verb, self.path, code, duration,
                trace_id=getattr(self, "_request_trace_id", ""),
            )

    def _check_auth(self, verb: str, rest: Tuple[str, ...]) -> None:
        """Authenticate + authorize an /api request. Reference:
        handler chain in pkg/master/master.go:584-585 (authn wraps
        authz wraps the REST mux); 401 on bad credentials, 403 on
        policy denial."""
        authenticator = getattr(self, "authenticator", None)
        authorizer = getattr(self, "authorizer", None)
        if authenticator is None and authorizer is None:
            return
        from kubernetes_tpu.server import auth as authpkg

        user = authpkg.UserInfo(name="system:anonymous")
        # x509 first, like the reference's request-authenticator union
        # (authn.go:35): a CA-verified client cert IS the identity; the
        # Authorization header is only consulted without one.
        peercert = None
        getpeercert = getattr(self.connection, "getpeercert", None)
        if getpeercert is not None:
            try:
                peercert = getpeercert()
            except ValueError:
                peercert = None
        if peercert:
            try:
                user = authpkg.X509Authenticator().authenticate_peer_cert(
                    peercert
                )
            except authpkg.AuthenticationError as e:
                raise APIError(401, "Unauthorized", str(e))
        elif authenticator is not None:
            try:
                user = authenticator.authenticate_request(
                    self.headers.get("Authorization", "")
                )
            except authpkg.AuthenticationError as e:
                raise APIError(401, "Unauthorized", str(e))
        if authorizer is not None:
            # Derive (resource, namespace) from the path shape. The
            # watch/redirect prefixes are verbs, not resources — policy
            # is written against the underlying resource.
            resource, ns = "", ""
            if rest and rest[0] in ("watch", "redirect"):
                rest = rest[1:]
            if len(rest) == 3 and rest[0] == "namespaces" and rest[2] == "finalize":
                resource = "namespaces"  # cluster-scoped subresource path
            elif len(rest) >= 3 and rest[0] == "namespaces":
                ns, resource = rest[1], rest[2]
            elif rest:
                resource = rest[0]
            # Bulk verbs ride the resource segment ("pods:bulk");
            # policy is written against the underlying resource.
            resource = resource.partition(":")[0]
            try:
                authorizer.authorize(
                    authpkg.AuthzAttributes(
                        user=user,
                        readonly=verb in ("GET", "HEAD"),
                        resource=resource,
                        namespace=ns,
                    )
                )
            except authpkg.AuthorizationError as e:
                raise APIError(403, "Forbidden", str(e))

    # -- /api/v1 router ----------------------------------------------

    def _api_v1(self, verb: str, rest: Tuple[str, ...]) -> Tuple[str, int]:
        api = self.api
        if (
            verb in ("POST", "PUT", "DELETE", "PATCH")
            and api.leader_url
            and getattr(api.store, "replica", False)
        ):
            # Stateless-apiserver write path: this replica's store is
            # read-only; every mutation forwards to the leader. Reads
            # and watches stay local (the watch cache fans out on every
            # replica — that's the whole point of N apiservers).
            return self._forward_leader(verb)
        q = self.query
        lsel = q.get("labelSelector", "")
        fsel = q.get("fieldSelector", "")

        if not rest:
            self._send_json(
                200,
                {
                    "kind": "APIResourceList",
                    "resources": sorted(
                        {i.name for i in RESOURCES.values()}
                    ),
                },
            )
            return "", 200

        # Watch endpoints: /watch/{resource} or /watch/namespaces/{ns}/{resource}
        if rest[0] == "watch":
            wrest = rest[1:]
            if len(wrest) == 1:
                resource, ns = wrest[0], ""
            elif len(wrest) == 3 and wrest[0] == "namespaces":
                resource, ns = wrest[2], wrest[1]
            else:
                raise APIError(404, "NotFound", f"bad watch path {self.path!r}")
            self._serve_watch(resource, ns, lsel, fsel, q)
            # Same long-running metrics label as ?watch=true — a watch
            # holds its connection for its lifetime and must not feed
            # the plain-GET p99 series the SLO gate reads.
            return resource + "/watch", 200

        # Legacy REDIRECT verb (pkg/apiserver/redirect.go:57-100 +
        # api_installer.go:280): GET /redirect/... answers 307 with the
        # resource's backend Location — pods (pod IP:port), services
        # (a ready endpoint), nodes (the kubelet API) — instead of
        # relaying like /proxy does.
        if rest[0] == "redirect":
            if verb != "GET":
                raise APIError(
                    405, "MethodNotAllowed", "redirect supports GET only"
                )
            return self._redirect(rest[1:])

        # Namespace finalize subresource (not a namespaced collection
        # path): PUT /api/v1/namespaces/{name}/finalize.
        if (
            len(rest) == 3
            and rest[0] == "namespaces"
            and rest[2] == "finalize"
            and verb == "PUT"
        ):
            out = self.api.finalize_namespace(rest[1], self._read_body())
            self._send_json(200, out)
            return "namespaces", 200

        # Namespaced paths.
        if rest[0] == "namespaces" and len(rest) >= 3:
            ns = rest[1]
            resource = rest[2]
            if resource == "bindings" and verb == "POST":
                body = self._read_body()
                name = body.get("metadata", {}).get("name", "")
                if name:
                    tracing.note_pods((name,))
                out = api.bind(ns, body)
                self._send_json(201, out)
                return "bindings", 201
            if resource == "bulkbindings" and verb == "POST":
                body = self._read_body()
                tracing.note_pods(
                    n
                    for n in (
                        b.get("metadata", {}).get("name", "")
                        for b in body.get("bindings", ())
                    )
                    if n
                )
                # The whole body dict rides through: it carries the
                # optional "atomic" (all-or-nothing gang commit) flag
                # alongside "bindings".
                results = api.bind_bulk(ns, body)
                self._send_json(
                    200, {"kind": "BindingResultList", "results": results}
                )
                return "bulkbindings", 200
            if resource == "bulkevents" and verb == "POST":
                body = self._read_body()
                results = api.create_events_bulk(ns, body.get("items", []))
                self._send_json(
                    200, {"kind": "EventResultList", "results": results}
                )
                return "bulkevents", 200
            if ":" in resource and verb == "POST" and len(rest) == 3:
                # Bulk object verbs: POST .../{resource}:bulk (create),
                # :bulkupdate, :bulkdelete — N objects through one
                # store group commit (the API-plane write fast path).
                return self._bulk(resource, ns)
            if len(rest) == 3:
                return self._collection(verb, resource, ns, lsel, fsel)
            name = rest[3]
            if len(rest) == 5 and rest[4] == "binding" and verb == "POST":
                tracing.note_pods((name,))
                body = self._read_body()
                body.setdefault("metadata", {})["name"] = name
                out = api.bind(ns, body)
                self._send_json(201, out)
                return "bindings", 201
            if (
                len(rest) == 5
                and rest[4] == "eviction"
                and resource == "pods"
                and verb == "POST"
            ):
                # Eviction subresource (shape: policy/v1 Eviction) —
                # graceful delete; the victim goes Terminating now and
                # is removed when its kubelet confirms.
                out = api.evict_pod(ns, name, self._read_body())
                self._send_json(201, out)
                return "pods/eviction", 201
            if len(rest) == 5 and rest[4] == "status" and verb == "PUT":
                out = api.update_status(
                    resource, ns, name, self._read_body(self._kind_of(resource))
                )
                self._send_json(200, out)
                return resource, 200
            if (
                len(rest) == 5
                and rest[4] == "log"
                and resource == "pods"
                and verb == "GET"
            ):
                # GET /pods/{name}/log (pkg/registry/pod/etcd/etcd.go:45
                # LogREST): resolve the pod's kubelet and relay.
                return self._pod_log(ns, name)
            if (
                len(rest) == 5
                and rest[4] == "portforward"
                and resource == "pods"
                and verb == "GET"
            ):
                # Websocket tunnel relayed through to the pod's kubelet
                # (pkg/registry/pod/etcd/etcd.go:49 PortForwardREST +
                # pkg/client/portforward; SPDY there, websocket here).
                self.api.connect(resource, ns, name, "portforward")
                self._pod_portforward(ns, name)
                return "pods/portforward", 101
            if (
                len(rest) >= 5
                and rest[4] == "proxy"
                and resource == "pods"
                and verb in ("GET", "POST")
            ):
                # Pod proxy subresource (etcd.go:47 ProxyREST): relay
                # an HTTP request to the pod's port. Name may carry
                # ":port" (reference's pods/name:port/proxy form) —
                # parsed ONCE here so admission and the relay can't
                # disagree on the pod name.
                pod_name, _, port_s = name.partition(":")
                self.api.connect(resource, ns, pod_name, "proxy")
                return self._pod_proxy(
                    verb, ns, pod_name,
                    int(port_s) if port_s.isdigit() else 0,
                    rest[5:],
                )
            if (
                len(rest) >= 5
                and rest[4] == "proxy"
                and resource == "services"
                and verb in ("GET", "POST")
            ):
                # Services proxy subresource (pkg/registry/service/
                # rest.go ResourceLocation + pkg/apiserver/proxy.go):
                # relay to a randomly-chosen ready endpoint. Name may
                # carry ":port" selecting an endpoint port by name or
                # number.
                svc_name, _, port_s = name.partition(":")
                self.api.connect(resource, ns, svc_name, "proxy")
                ip, port = self.api.service_location(ns, svc_name, port_s)
                url = f"http://{ip}:{port}/" + "/".join(rest[5:])
                code = self._relay_http(url, verb, "service proxy")
                return "services/proxy", code
            if len(rest) == 5 and rest[4] in ("exec", "attach", "run") and verb == "POST":
                # CONNECT subresources (pkg/apiserver/api_installer.go
                # CONNECT routes). Admission (DenyExecOnPrivileged) runs
                # inside pod_exec; the call relays to the node agent's
                # API (pkg/kubelet/server.go /exec/) as JSON run-exec.
                if resource != "pods":
                    raise APIError(
                        404, "NotFound", f"{resource} has no {rest[4]} subresource"
                    )
                body = self._read_body()
                container = self.query.get("container") or body.get("container", "")
                if "command" not in body and "command" in self.query:
                    body["command"] = [self.query["command"]]
                out = api.pod_exec(ns, name, container, body)
                self._send_json(200, out)
                return "pods/exec", 200
            if len(rest) == 4:
                return self._item(verb, resource, ns, name)
            raise APIError(404, "NotFound", f"unknown path {self.path!r}")

        # Cluster-scoped or cross-namespace.
        resource = rest[0]
        if ":" in resource and verb == "POST" and len(rest) == 1:
            return self._bulk(resource, "")
        info = RESOURCES.get(resource)
        if info is None:
            raise APIError(404, "NotFound", f"unknown resource {resource!r}")
        if (
            len(rest) >= 3
            and resource == "nodes"
            and rest[2] == "proxy"
            and verb == "GET"
        ):
            # Node proxy subresource: relay to the node's kubelet API
            # (reference: pkg/master/master.go:497-520 dials node:10250
            # for logs/stats/spec through the apiserver).
            return self._node_proxy(rest[1], rest[3:])
        if len(rest) == 1:
            return self._collection(verb, resource, "", lsel, fsel)
        if info.namespaced and len(rest) >= 2:
            raise APIError(
                400, "BadRequest", f"{resource} is namespaced; use /namespaces/.."
            )
        if len(rest) == 2:
            return self._item(verb, resource, "", rest[1])
        if len(rest) == 3 and rest[2] == "status" and verb == "PUT":
            # Cluster-scoped status subresource — PUT /nodes/{n}/status
            # is every kubelet's heartbeat write (the reference installs
            # status routes for all resources, api_installer.go).
            out = api.update_status(
                resource, "", rest[1], self._read_body(self._kind_of(resource))
            )
            self._send_json(200, out)
            return resource, 200
        raise APIError(404, "NotFound", f"unknown path {self.path!r}")

    def _bulk(self, spec: str, ns: str) -> Tuple[str, int]:
        """POST {resource}:bulk|:bulkupdate|:bulkdelete — batch verbs
        committing N objects under one WAL group commit (api.create_bulk
        and friends). Bodies: {"items": [...]} for create/update,
        {"names": [...]} for delete. Per-item Status results in order."""
        base, _, bulk_verb = spec.partition(":")
        if RESOURCES.get(base) is None:
            raise APIError(404, "NotFound", f"unknown resource {base!r}")
        # No kind hint: the body is a bulk ENVELOPE, not an object —
        # version conversion dispatches on kind and would mangle it.
        # Bulk verbs are v1-only by contract.
        body = self._read_body()
        if bulk_verb == "bulk":
            # copy=False: the just-parsed body is private to this
            # request — the store may own the dicts outright.
            results = self.api.create_bulk(
                base, ns, body.get("items", []), copy=False
            )
        elif bulk_verb == "bulkupdate":
            results = self.api.update_bulk(
                base, ns, body.get("items", []), copy=False
            )
        elif bulk_verb == "bulkdelete":
            results = self.api.delete_bulk(base, ns, body.get("names", []))
        else:
            raise APIError(
                404, "NotFound",
                f"unknown bulk verb {bulk_verb!r} "
                "(bulk, bulkupdate, bulkdelete)",
            )
        self._send_json(200, {"kind": "BulkResultList", "results": results})
        return f"{base}/{bulk_verb}", 200

    # -- pod subresources proxied to the kubelet API ------------------

    def _pod_log(self, ns: str, name: str) -> Tuple[str, int]:
        tail_raw = self.query.get("tailLines") or self.query.get("tail")
        tail = None
        if tail_raw:
            try:
                tail = int(tail_raw)
            except ValueError:
                raise APIError(
                    400, "BadRequest", f"invalid tailLines {tail_raw!r}"
                )
        text = self.api.pod_log(
            ns,
            name,
            container=self.query.get("container", ""),
            tail=tail,
        )
        self._send_text(200, text)
        return "pods/log", 200

    def _pod_portforward(self, ns: str, name: str) -> None:
        """Relay a websocket tunnel: client <-> apiserver <-> kubelet."""
        from kubernetes_tpu.utils import websocket as ws

        key = self.headers.get("Sec-WebSocket-Key")
        if self.headers.get("Upgrade", "").lower() != "websocket" or not key:
            raise APIError(
                400, "BadRequest", "port-forward requires websocket upgrade"
            )
        port = self.query.get("port", "")
        if not port.isdigit():
            raise APIError(400, "BadRequest", f"invalid ?port={port!r}")
        base, _pod = self.api.kubelet_location(ns, name)
        parsed = urlparse(base)
        upstream = ws.WebSocketClient(
            parsed.hostname,
            parsed.port,
            f"/portForward/{ns or 'default'}/{name}/{port}",
        )
        upstream.clear_timeout()
        self.send_response(101, "Switching Protocols")
        for hname, value in ws.handshake_headers(key):
            self.send_header(hname, value)
        self.end_headers()
        ws.relay_ws_ws(
            ws.ServerEndpoint(self.rfile, self.wfile, raw_socket=self.connection),
            upstream,
        )
        self.close_connection = True

    def _relay_http(self, url: str, verb: str, what: str) -> int:
        """Relay one HTTP request (with query string, body, and salient
        headers) to `url`, passing the upstream's status/body through.
        Shared by the pod and node proxy subresources."""
        import urllib.error
        import urllib.request

        raw_query = urlparse(self.path).query
        if raw_query:
            url += "?" + raw_query
        data = None
        headers = {}
        if verb == "POST":
            length = int(self.headers.get("Content-Length", 0) or 0)
            data = self.rfile.read(length) if length else b""
            if self.headers.get("Content-Type"):
                headers["Content-Type"] = self.headers["Content-Type"]
        if self.headers.get("Accept"):
            headers["Accept"] = self.headers["Accept"]
        req = urllib.request.Request(url, data=data, headers=headers, method=verb)
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                body = resp.read()
                ctype = resp.headers.get("Content-Type", "text/plain")
                code = resp.status
        except urllib.error.HTTPError as e:
            body = e.read()
            ctype = e.headers.get("Content-Type", "text/plain")
            code = e.code
        except urllib.error.URLError as e:
            raise APIError(502, "BadGateway", f"{what} dial failed: {e}")
        self._send_text(code, body, ctype)
        return code

    def _pod_proxy(
        self,
        verb: str,
        ns: str,
        name: str,
        port: int,
        subpath: Tuple[str, ...],
    ) -> Tuple[str, int]:
        """Relay one HTTP request to the pod's port (host network:
        the pod's host IP + the explicit, or first declared, container
        port)."""
        base, pod = self.api.kubelet_location(ns, name)
        port = port or _first_container_port(pod, name)
        host = urlparse(base).hostname or "127.0.0.1"
        url = f"http://{host}:{port}/" + "/".join(subpath)
        code = self._relay_http(url, verb, "pod proxy")
        return "pods/proxy", code

    def _redirect(self, rest: Tuple[str, ...]) -> Tuple[str, int]:
        """Resolve a resource's backend location and answer 307
        (RedirectHandler: ResourceLocation per storage kind)."""
        if len(rest) == 4 and rest[0] == "namespaces":
            ns, resource, name = rest[1], rest[2], rest[3]
        elif len(rest) == 2:
            ns, resource, name = "", rest[0], rest[1]
        else:
            raise APIError(404, "NotFound", f"bad redirect path {self.path!r}")
        base, _, port_s = name.partition(":")
        if resource == "services":
            ip, port = self.api.service_location(ns, base, port_s)
            location = f"http://{ip}:{port}/"
        elif resource == "pods":
            pod = self.api.get("pods", ns, base)
            ip = pod.get("status", {}).get("podIP", "")
            if not ip:
                raise APIError(
                    409, "Conflict", f"pod {base!r} has no pod IP yet"
                )
            if not port_s:
                port = _first_container_port(pod, base)
            elif port_s.isdigit():
                port = int(port_s)
            else:
                # Named container port, like the service form resolves
                # endpoint port names.
                port = next(
                    (
                        p["containerPort"]
                        for c in pod.get("spec", {}).get("containers", [])
                        for p in c.get("ports", [])
                        if p.get("name") == port_s and p.get("containerPort")
                    ),
                    0,
                )
                if not port:
                    raise APIError(
                        400, "BadRequest",
                        f"pod {base!r} has no container port named {port_s!r}",
                    )
            location = f"http://{ip}:{port}/"
        elif resource == "nodes":
            # kubelet_location resolves via a pod normally; nodes
            # resolve directly from their status.
            node = self.api.get("nodes", "", base)
            status = node.get("status", {})
            port = (
                status.get("daemonEndpoints", {})
                .get("kubeletEndpoint", {})
                .get("port", 0)
            )
            if not port:
                raise APIError(
                    501, "NotImplemented",
                    f"node {base!r} does not publish a kubelet API endpoint",
                )
            ip = next(
                (
                    a.get("address")
                    for a in status.get("addresses", [])
                    if a.get("type") == "InternalIP"
                ),
                "127.0.0.1",
            )
            location = f"http://{ip}:{port}/"
        else:
            raise APIError(
                405, "MethodNotAllowed", f"{resource} is not a redirector"
            )
        self.send_response(307)
        self.send_header("Location", location)
        self.send_header("Content-Length", "0")
        self.end_headers()
        return f"{resource}/redirect", 307

    def _node_proxy(
        self, node_name: str, subpath: Tuple[str, ...]
    ) -> Tuple[str, int]:
        """GET /nodes/{name}/proxy/{path} -> the node's kubelet API."""
        node = self.api.get("nodes", "", node_name)
        status = node.get("status", {})
        port = (
            status.get("daemonEndpoints", {})
            .get("kubeletEndpoint", {})
            .get("port", 0)
        )
        if not port:
            raise APIError(
                501, "NotImplemented",
                f"node {node_name!r} does not publish a kubelet API endpoint",
            )
        ip = next(
            (
                a.get("address")
                for a in status.get("addresses", [])
                if a.get("type") == "InternalIP"
            ),
            "127.0.0.1",
        )
        url = f"http://{ip}:{port}/" + "/".join(subpath)
        code = self._relay_http(url, "GET", "kubelet proxy")
        return "nodes/proxy", code

    def _collection(self, verb, resource, ns, lsel, fsel) -> Tuple[str, int]:
        api = self.api
        if verb == "GET":
            if self.query.get("watch") in ("true", "1"):
                self._serve_watch(resource, ns, lsel, fsel, self.query)
                # Distinct metrics label: a watch holds its connection
                # for its whole lifetime — folding that duration into
                # the plain-GET latency series would wreck the p99 SLO
                # signal (the reference uses verb WATCHLIST the same
                # way, pkg/apiserver/metrics.go).
                return resource + "/watch", 200
            # Watch-cache fast path: the response is assembled from
            # per-object encodings cached by resourceVersion — repeat
            # LISTs (controller relists, reflector syncs) never
            # re-serialize unchanged objects. Non-v1 wire versions and
            # live componentstatuses fall back to the dict path.
            if getattr(self, "wire_version", "v1") == "v1":
                enc = api.list_response_bytes(resource, ns, lsel, fsel)
                if enc is not None:
                    self._send_text(200, enc, "application/json")
                    return resource, 200
            # copy=False: the list is encoded and discarded right here,
            # so the store's read-only refs skip a full deep copy.
            self._send_json(200, api.list(resource, ns, lsel, fsel, copy=False))
            return resource, 200
        if verb == "POST":
            body = self._read_body(self._kind_of(resource))
            if resource == "pods":
                name = body.get("metadata", {}).get("name", "")
                if name:
                    tracing.note_pods((name,))
            out = api.create(resource, ns, body)
            self._send_json(201, out)
            return resource, 201
        raise APIError(405, "MethodNotAllowed", f"{verb} not allowed on collection")

    def _item(self, verb, resource, ns, name) -> Tuple[str, int]:
        api = self.api
        if verb == "GET":
            enc = None
            if getattr(self, "wire_version", "v1") == "v1":
                # Cached per-object encoding (miss = absent object or
                # stale cache: the slow path owns 404 semantics).
                enc = api.get_response_bytes(resource, ns, name)
            if enc is not None:
                self._send_text(200, enc, "application/json")
            else:
                self._send_json(200, api.get(resource, ns, name))
        elif verb == "PUT":
            self._send_json(
                200, api.update(resource, ns, name, self._read_body(self._kind_of(resource)))
            )
        elif verb == "PATCH":
            # All three reference patch types, selected by Content-Type
            # (resthandler.go:446): json-patch / strategic-merge /
            # merge (the default; plain application/json means merge).
            # The kind hint lets a kind-less partial v1beta3 merge body
            # still version-convert; json-patch op arrays pass through
            # untouched and address internal (v1) field names.
            ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
            ptype = {
                "application/json-patch+json": "json",
                "application/strategic-merge-patch+json": "strategic",
            }.get(ctype, "merge")
            self._send_json(
                200,
                api.patch(
                    resource, ns, name,
                    self._read_body(self._kind_of(resource)),
                    patch_type=ptype,
                ),
            )
        elif verb == "DELETE":
            grace = None
            g = self.query.get("gracePeriodSeconds")
            if g is not None:
                try:
                    grace = int(g)
                except ValueError:
                    raise APIError(
                        400, "BadRequest",
                        f"gracePeriodSeconds must be numeric, got {g!r}",
                    )
            self._send_json(
                200, api.delete(resource, ns, name, grace_period_seconds=grace)
            )
        else:
            raise APIError(405, "MethodNotAllowed", f"{verb} not allowed on item")
        return resource, 200

    def _serve_watch(self, resource, ns, lsel, fsel, q) -> None:
        try:
            since = int(q.get("resourceVersion", "0") or "0")
            timeout = float(q.get("timeoutSeconds", "0") or "0") or None
            maxsize = int(q.get("maxsize", "4096") or "4096")
        except ValueError:
            raise APIError(
                400, "BadRequest",
                "resourceVersion/timeoutSeconds/maxsize must be numeric",
            )
        # Both transports the reference serves (pkg/apiserver/watch.go:
        # 45-102): websocket when the client asks to upgrade, chunked
        # newline-JSON otherwise. Frame payloads are identical.
        websocket = (
            self.headers.get("Upgrade", "").lower() == "websocket"
            and self.headers.get("Sec-WebSocket-Key")
        )
        stream = self.api.watch(
            resource, ns, since=since, label_selector=lsel,
            field_selector=fsel, maxsize=maxsize,
        )
        from kubernetes_tpu.utils import websocket as ws

        if websocket:
            self.send_response(101, "Switching Protocols")
            for name, value in ws.handshake_headers(
                self.headers["Sec-WebSocket-Key"]
            ):
                self.send_header(name, value)
            self.end_headers()
        else:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
        deadline = None if timeout is None else time.monotonic() + timeout
        try:
            while True:
                wait = 1.0
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        break
                ev = stream.next(timeout=wait)
                if ev is None:
                    if stream.closed:
                        break
                    continue
                # Burst coalescing: drain whatever else is already
                # queued (bounded) and ship ONE socket write. At bulk
                # churn rates a write+flush syscall per event made this
                # writer thread the slow consumer — the store would
                # drop the stream mid-drill.
                batch = [ev]
                while len(batch) < 512:
                    nxt = stream.next(timeout=0)
                    if nxt is None:
                        break
                    batch.append(nxt)
                out = []
                version = getattr(self, "wire_version", "v1")
                for ev in batch:
                    obj = ev.object
                    if version != "v1" and isinstance(obj, dict):
                        obj = conversion.from_internal(obj, version)
                        frame = json.dumps(
                            {"type": ev.type, "object": obj}
                        ).encode()
                    else:
                        # Shared frame cache: one event fanned out to
                        # N watch connections is encoded once (keyed
                        # by the store's globally unique version).
                        frame = self.api.caches.frame_bytes(
                            ev.type, ev.version, obj
                        )
                    if websocket:
                        out.append(ws.encode_frame(frame))
                    else:
                        frame += b"\n"
                        out.append(
                            b"%x\r\n" % len(frame) + frame + b"\r\n"
                        )
                self.wfile.write(b"".join(out))
                self.wfile.flush()
                # Fan-out lag SLI: how many store versions this
                # connection's delivery trails ITS resource's applied
                # watermark by (one observation per burst, not per
                # event). Filtered streams — selector OR namespace
                # scoped — are skipped: events filtered out of their
                # view never advance the delivered version, which
                # would read as permanent false lag against the
                # resource-wide watermark.
                last_v = batch[-1].version
                if last_v and not ns and not lsel and not fsel:
                    applied = self.api.caches.applied_version(resource)
                    if applied:
                        sli.observe_watch_lag(resource, applied - last_v)
        except (BrokenPipeError, ConnectionResetError, socket.timeout):
            pass
        finally:
            stream.close()
            try:
                if websocket:
                    self.wfile.write(ws.encode_frame(b"", ws.OP_CLOSE))
                else:
                    self.wfile.write(b"0\r\n\r\n")
            except Exception:  # ktlint: disable=KT003
                pass  # watch client already gone mid-close
            self.close_connection = True


def _swagger_doc() -> dict:
    """OpenAPI-style discovery doc generated from the resource registry
    (reference ships a static api/swagger-spec/v1.json; generating from
    RESOURCES means the doc can't drift from the router)."""
    from kubernetes_tpu.server.registry import unique_resources

    paths = {}
    for info in unique_resources():
        base = (
            f"/api/v1/namespaces/{{namespace}}/{info.name}"
            if info.namespaced
            else f"/api/v1/{info.name}"
        )
        paths[base] = {
            "get": {"summary": f"list {info.kind} objects"},
            "post": {"summary": f"create a {info.kind}"},
        }
        paths[base + "/{name}"] = {
            "get": {"summary": f"read a {info.kind}"},
            "put": {"summary": f"replace a {info.kind}"},
            "delete": {"summary": f"delete a {info.kind}"},
        }
        paths[f"/api/v1/watch/{info.name}"] = {
            "get": {"summary": f"watch {info.kind} objects (chunked or websocket)"}
        }
    paths["/api/v1/namespaces/{namespace}/pods/{name}/log"] = {
        "get": {"summary": "read container logs (kubelet relay)"}
    }
    paths["/api/v1/namespaces/{namespace}/pods/{name}/exec"] = {
        "post": {"summary": "run a command in a container (kubelet relay)"}
    }
    paths["/api/v1/namespaces/{namespace}/bindings"] = {
        "post": {"summary": "bind a pod to a node"}
    }
    return {
        "openapi": "3.0.0",
        "info": {"title": "kubernetes-tpu", "version": __version__},
        "paths": paths,
    }


#: Interactive API browser (reference: third_party/swagger-ui/ wired
#: at /swagger-ui/ by pkg/master/master.go). Self-contained: renders
#: /swagger.json as expandable per-path operation cards with a
#: "try it" runner for GET operations (path params become inputs).
_SWAGGER_UI_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>kubernetes-tpu API</title>
<style>
body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1c2733}
header{background:#1c2733;color:#fff;padding:14px 22px;font-size:18px}
header a{color:#8fd0ff;text-decoration:none;margin-left:14px;font-size:13px}
#paths{max-width:980px;margin:18px auto;padding:0 16px}
.path{background:#fff;border:1px solid #dde3ea;border-radius:6px;margin:8px 0}
.path>summary{padding:9px 14px;cursor:pointer;font-family:ui-monospace,monospace;
  font-size:13px;display:flex;gap:10px;align-items:center}
.verb{font-size:11px;font-weight:700;border-radius:3px;padding:2px 7px;color:#fff}
.get{background:#2f81f7}.post{background:#2da44e}.put{background:#bf8700}
.delete{background:#cf222e}
.op{border-top:1px solid #eef1f5;padding:10px 16px;font-size:13px}
.op .summary{color:#4a5766;margin-left:8px}
.try{margin-top:8px}
.try input{font-family:ui-monospace,monospace;font-size:12px;margin:0 6px 4px 0;
  padding:3px 6px;border:1px solid #c6ccd4;border-radius:4px}
.try button{padding:3px 12px;border:0;border-radius:4px;background:#2f81f7;
  color:#fff;cursor:pointer;font-size:12px}
pre.result{background:#0d1117;color:#d7e1ec;font-size:11px;padding:10px;
  border-radius:6px;max-height:340px;overflow:auto;white-space:pre-wrap}
</style></head><body>
<header>kubernetes-tpu API browser
  <a href="/swagger.json">swagger.json</a><a href="/ui/">dashboard</a>
  <a href="/metrics">metrics</a></header>
<div id="paths">loading /swagger.json…</div>
<script>
(async () => {
  const doc = await (await fetch('/swagger.json')).json();
  const root = document.getElementById('paths');
  root.innerHTML = '<p style="color:#4a5766">' +
    (doc.info ? doc.info.title + ' v' + doc.info.version + ' — ' : '') +
    Object.keys(doc.paths).length + ' paths</p>';
  for (const [path, ops] of Object.entries(doc.paths).sort()) {
    const det = document.createElement('details');
    det.className = 'path';
    const verbs = Object.keys(ops).map(v =>
      '<span class="verb ' + v + '">' + v.toUpperCase() + '</span>').join('');
    det.innerHTML = '<summary>' + verbs + ' ' + path + '</summary>';
    for (const [verb, op] of Object.entries(ops)) {
      const d = document.createElement('div');
      d.className = 'op';
      d.innerHTML = '<span class="verb ' + verb + '">' + verb.toUpperCase() +
        '</span><span class="summary">' + (op.summary || '') + '</span>';
      if (verb === 'get') {
        const params = [...path.matchAll(/{([^}]+)}/g)].map(m => m[1]);
        const form = document.createElement('div');
        form.className = 'try';
        form.innerHTML = params.map(p =>
          '<input placeholder="' + p + '" data-p="' + p + '">').join('') +
          '<button>try it</button><pre class="result" hidden></pre>';
        form.querySelector('button').onclick = async () => {
          let url = path;
          form.querySelectorAll('input').forEach(i => {
            url = url.replace('{' + i.dataset.p + '}',
                              encodeURIComponent(i.value || 'default'));
          });
          const out = form.querySelector('pre');
          out.hidden = false;
          out.textContent = 'GET ' + url + ' …';
          try {
            const r = await fetch(url);
            const text = await r.text();
            let body = text;
            try { body = JSON.stringify(JSON.parse(text), null, 1); }
            catch (e) {}
            out.textContent = 'HTTP ' + r.status + '\\n' + body;
          } catch (e) { out.textContent = String(e); }
        };
        d.appendChild(form);
      }
      det.appendChild(d);
    }
    root.appendChild(det);
  }
})();
</script></body></html>
"""


#: The live dashboard: a self-contained single-page app (no external
#: assets — this box has zero egress, and the reference vendors its
#: AngularJS app into pkg/ui/datafile.go for the same reason). Hash
#: routing gives per-resource views; every view polls the REST API and
#: re-renders, so the page tracks the cluster live (VERDICT r2 item 10).
_UI_PAGE = """<!doctype html>
<html><head><title>kubernetes-tpu</title>
<meta charset="utf-8">
<style>
 body { font-family: -apple-system, 'Segoe UI', sans-serif; margin: 0;
        background: #f6f8fa; color: #1f2328; }
 header { background: #1b1f24; color: #eee; padding: 10px 18px;
          display: flex; align-items: baseline; gap: 16px; }
 header h1 { font-size: 1.05em; margin: 0; font-weight: 600; }
 header a { color: #9cc4ff; text-decoration: none; font-size: .85em; }
 nav { background: #fff; border-bottom: 1px solid #d8dee4;
       padding: 6px 18px; display: flex; flex-wrap: wrap; gap: 4px; }
 nav a { padding: 4px 10px; border-radius: 6px; text-decoration: none;
         color: #1f2328; font-size: .9em; }
 nav a.active { background: #0969da; color: #fff; }
 nav a:hover:not(.active) { background: #eaeef2; }
 main { padding: 16px 18px; }
 table { border-collapse: collapse; background: #fff; width: 100%;
         box-shadow: 0 1px 2px rgba(0,0,0,.06); }
 th { text-align: left; font-size: .78em; text-transform: uppercase;
      letter-spacing: .04em; color: #57606a; }
 td, th { border-bottom: 1px solid #e6e9ec; padding: 7px 12px;
          font-size: .9em; }
 tr:hover td { background: #f6f8fa; }
 .pill { display: inline-block; padding: 1px 9px; border-radius: 10px;
         font-size: .82em; background: #eaeef2; }
 .ok  { background: #dafbe1; color: #116329; }
 .bad { background: #ffebe9; color: #a40e26; }
 .warn{ background: #fff8c5; color: #7d4e00; }
 .cards { display: flex; flex-wrap: wrap; gap: 12px; margin-bottom: 16px; }
 .card { background: #fff; border: 1px solid #d8dee4; border-radius: 8px;
         padding: 10px 16px; min-width: 110px; cursor: pointer; }
 .card b { display: block; font-size: 1.5em; }
 .card span { color: #57606a; font-size: .82em; }
 .muted { color: #57606a; font-size: .85em; }
 select { margin-left: auto; }
 pre { background: #fff; border: 1px solid #d8dee4; padding: 10px;
       overflow-x: auto; font-size: .85em; }
</style></head>
<body>
<header><h1>kubernetes-tpu</h1>
 <span id=status class=muted></span>
 <a href="/swagger-ui/">api</a> <a href="/metrics">metrics</a>
 <a href="/healthz">healthz</a> <a href="/debug/requests">requests</a>
 <select id=nsSel title=namespace></select>
</header>
<nav id=nav></nav>
<main id=main>loading…</main>
<script>
const RESOURCES = {
 pods: {cols: ['name','phase','node','ready','restarts','age'],
  row: p => [name(p), pill(p.status&&p.status.phase), (p.spec||{}).nodeName||'',
   ready(p), restarts(p), age(p)]},
 nodes: {ns: false, cols: ['name','status','cpu','memory','pods','age'],
  row: n => {const c=(n.status||{}).capacity||{};
   return [name(n), nodeReady(n), c.cpu||'', c.memory||'', c.pods||'', age(n)];}},
 services: {cols: ['name','type','cluster-ip','ports','selector','age'],
  row: s => {const sp=s.spec||{};
   return [name(s), sp.type||'ClusterIP', sp.clusterIP||'',
    (sp.ports||[]).map(p=>p.port+(p.nodePort?':'+p.nodePort:'')+'/'+(p.protocol||'TCP')).join(', '),
    kv(sp.selector), age(s)];}},
 replicationcontrollers: {cols: ['name','desired','current','selector','age'],
  row: r => [name(r), (r.spec||{}).replicas||0, (r.status||{}).replicas||0,
   kv((r.spec||{}).selector), age(r)]},
 endpoints: {cols: ['name','endpoints','age'],
  row: e => [name(e), (e.subsets||[]).map(s =>
   (s.addresses||[]).map(a=>a.ip).join(',')+':'+ (s.ports||[]).map(p=>p.port).join(',')
  ).join(' | ') || '<none>', age(e)]},
 events: {cols: ['last seen','count','reason','object','message'],
  row: e => [e.lastTimestamp||e.firstTimestamp||'', e.count||1,
   pill(e.reason, /fail|unhealthy|kill/i.test(e.reason||'')?'bad':''),
   ((e.involvedObject||{}).kind||'')+'/'+((e.involvedObject||{}).name||''),
   e.message||'']},
 namespaces: {ns: false, cols: ['name','phase','age'],
  row: n => [name(n), pill((n.status||{}).phase), age(n)]},
 secrets: {cols: ['name','type','keys','age'],
  row: s => [name(s), s.type||'Opaque', Object.keys(s.data||{}).join(', '), age(s)]},
 serviceaccounts: {cols: ['name','secrets','age'],
  row: s => [name(s), (s.secrets||[]).map(x=>x.name).join(', '), age(s)]},
 resourcequotas: {cols: ['name','hard','used','age'],
  row: r => [name(r), kv((r.spec||{}).hard), kv((r.status||{}).used), age(r)]},
 limitranges: {cols: ['name','age'], row: l => [name(l), age(l)]},
 persistentvolumes: {ns: false, cols: ['name','capacity','phase','claim','age'],
  row: v => [name(v), kv((v.spec||{}).capacity), pill((v.status||{}).phase),
   (((v.spec||{}).claimRef)||{}).name||'', age(v)]},
 persistentvolumeclaims: {cols: ['name','phase','volume','age'],
  row: c => [name(c), pill((c.status||{}).phase), (c.spec||{}).volumeName||'', age(c)]},
 podgroups: {cols: ['name','min-member','phase','bound','age'],
  row: g => [name(g), ((g.spec||{}).minMember)||1,
   pill((g.status||{}).phase||'Pending'),
   ((g.status||{}).bound||0)+'/'+((g.status||{}).members||0), age(g)]},
 podtemplates: {cols: ['name','containers','age'],
  row: t => [name(t), (((t.template||{}).spec||{}).containers||[])
   .map(c=>c.name).join(', '), age(t)]},
 priorityclasses: {ns: false,
  cols: ['name','value','global-default','preemption-policy','age'],
  row: c => [name(c), c.value||0, String(!!c.globalDefault),
   c.preemptionPolicy||'PreemptLowerPriority', age(c)]},
 componentstatuses: {ns: false, cols: ['name','status','message'],
  row: c => {const cond=(c.conditions||[{}])[0];
   return [name(c), pill(cond.status==='True'?'Healthy':'Unhealthy',
    cond.status==='True'?'ok':'bad'), cond.message||''];}},
};
const esc = s => String(s==null?'':s).replace(/[&<>"]/g,
 c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;'}[c]));
// Escaping happens EXACTLY ONCE, at the table sink: row builders
// return plain strings (escaped there), or {h: html} for trusted
// markup whose dynamic parts were esc()'d at construction (pill).
const name = o => (o.metadata||{}).name||'';
const kv = m => Object.entries(m||{}).map(([k,v])=>k+'='+v).join(',');
const pill = (txt, cls) => txt ? {h: '<span class="pill '+(cls||
 (/running|active|true|bound|healthy|normal|scheduled/i.test(txt)?'ok':
  /fail|error|unhealthy|lost|terminat/i.test(txt)?'bad':
  /pending/i.test(txt)?'warn':''))+'">'+esc(txt)+'</span>'} : '';
function age(o){const t=(o.metadata||{}).creationTimestamp; if(!t) return '';
 const s=Math.max(0,(Date.now()-Date.parse(t))/1000)|0;
 return s<120?s+'s':s<7200?(s/60|0)+'m':s<172800?(s/3600|0)+'h':(s/86400|0)+'d';}
function ready(p){const cs=(p.status||{}).containerStatuses||[];
 return cs.filter(c=>c.ready).length+'/'+((p.spec||{}).containers||[]).length;}
function restarts(p){return ((p.status||{}).containerStatuses||[])
 .reduce((a,c)=>a+(c.restartCount||0),0);}
function nodeReady(n){const c=((n.status||{}).conditions||[])
 .find(x=>x.type==='Ready'); const un=(n.spec||{}).unschedulable;
 let txt=c&&c.status==='True'?'Ready':'NotReady';
 if(un) txt+=',Unschedulable';
 return pill(txt, txt==='Ready'?'ok':'bad');}
let NS='default';
async function getJSON(u){
 // Bounded: a blackholed request must fail fast, or the no-overlap
 // render gate would freeze polling until the browser's own timeout.
 const r=await fetch(u, {signal: AbortSignal.timeout(4000)});
 if(!r.ok) throw new Error(r.status);
 return r.json();}
const listPath=(res)=> (RESOURCES[res]&&RESOURCES[res].ns===false)
 ? '/api/v1/'+res : '/api/v1/namespaces/'+encodeURIComponent(NS)+'/'+res;
function route(){return location.hash.replace(/^#\\/?/, '')||'overview';}
function nav(){const cur=route();
 document.getElementById('nav').innerHTML =
  ['overview', ...Object.keys(RESOURCES)].map(r =>
   '<a href="#/'+r+'" class="'+(r===cur?'active':'')+'">'+r+'</a>').join('');}
async function refreshNamespaces(){
 try{const d=await getJSON('/api/v1/namespaces');
  const names=(d.items||[]).map(n=>name(n)).filter(Boolean);
  if(!names.includes(NS)) names.push(NS);
  const sel=document.getElementById('nsSel');
  // Compare the OPTION VALUES, not innerHTML (browsers normalize
  // serialized markup, so a string compare would rebuild — and close
  // an open dropdown — on every tick).
  const have=[...sel.options].map(o=>o.value).join('\\u0000');
  if(have!==names.join('\\u0000')){
   sel.innerHTML=names.map(n=>'<option>'+esc(n)+'</option>').join('');}
  sel.value=NS;
 }catch(e){}}
async function renderOverview(){
 const lists=await Promise.all(Object.keys(RESOURCES).map(async r=>{
  try{const d=await getJSON(listPath(r)); return [r, d.items||[]];}
  catch(e){return [r, null];}}));
 let html='<div class=cards>'+lists.map(([r,items]) =>
  '<div class=card onclick="location.hash=\\'#/'+r+'\\'"><b>'+
  (items===null?'?':items.length)+'</b><span>'+r+'</span></div>').join('')+'</div>';
 const ev=lists.find(([r])=>r==='events');
 if(ev && ev[1]!==null){
  html+='<h3>recent events</h3>'+tableFor('events', ev[1].slice(-12).reverse());}
 return html;}
function tableFor(res, items){const def=RESOURCES[res];
 const cell=v => (v&&v.h) ? v.h : esc(String(v));
 return '<table><tr>'+def.cols.map(c=>'<th>'+esc(c)+'</th>').join('')+'</tr>'+
  items.map(o=>'<tr>'+def.row(o).map(v=>'<td>'+cell(v)+'</td>').join('')+'</tr>').join('')+
  '</table>';}
let renderGen=0, rendering=false, lastOverview=0;
async function render(force){nav(); refreshNamespaces();
 const cur=route();
 // Be a polite API client: never overlap request rounds, and poll the
 // request-heavy overview (one list per resource kind) at 6s instead
 // of 2s so a parked tab can't crowd the max-in-flight budget.
 if(rendering && !force) return;
 if(cur==='overview' && !force && Date.now()-lastOverview < 5500) return;
 rendering=true;
 const gen=++renderGen;
 const main=document.getElementById('main');
 try{
  let html;
  if(cur==='overview'){html=await renderOverview();}
  else if(RESOURCES[cur]){const d=await getJSON(listPath(cur));
   const items=d.items||[];
   html='<p class=muted>'+items.length+' object(s)'+
    (RESOURCES[cur].ns===false?'':' in namespace '+esc(NS))+
    ' &middot; <a href="'+listPath(cur)+'">raw json</a></p>'+
    tableFor(cur, items);}
  else {html='unknown view '+esc(cur);}
  // A slower, earlier render must never paint over a newer one
  // (hashchange + the 2s tick can overlap via force).
  if(gen!==renderGen) return;
  if(cur==='overview') lastOverview=Date.now();
  main.innerHTML=html;
  document.getElementById('status').textContent='live · '+new Date().toLocaleTimeString();
 }catch(e){if(gen===renderGen)
  document.getElementById('status').textContent='api error: '+e;}
 finally{if(gen===renderGen) rendering=false;}
}
document.getElementById('nsSel').addEventListener('change', e=>{
 NS=e.target.value; render(true);});
window.addEventListener('hashchange', ()=>render(true));
render(true); setInterval(()=>render(false), 2000);
</script>
</body></html>"""


class _TLSCapableServer(ThreadingHTTPServer):
    """ThreadingHTTPServer that TLS-wraps each accepted connection with
    do_handshake_on_connect=False: the handshake then happens on the
    handler thread's first read, so a client that stalls mid-handshake
    ties up one daemon thread instead of the accept loop.

    Accepted sockets are tracked (weakly) so close_connections() can
    sever live keep-alive sessions on shutdown: a process restart
    resets every TCP connection, and an in-process restart (tests, the
    HTTP-tier-only restart path) must behave the same — otherwise a
    successor on the same port coexists with the predecessor's handler
    threads still serving stale keep-alive clients."""

    ssl_context = None

    def __init__(self, *args, **kwargs):
        import weakref

        super().__init__(*args, **kwargs)
        self._conns: "weakref.WeakSet" = weakref.WeakSet()
        self._conns_lock = threading.Lock()

    def get_request(self):
        sock, addr = self.socket.accept()
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(
                sock, server_side=True, do_handshake_on_connect=False
            )
        with self._conns_lock:
            self._conns.add(sock)
        return sock, addr

    def close_connections(self) -> None:
        import socket as _socket

        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass


class APIHTTPServer:
    """Owns the listening socket + serving thread."""

    def __init__(
        self,
        api: APIServer,
        host: str = "127.0.0.1",
        port: int = 0,
        authenticator=None,
        authorizer=None,
        publish_master: bool = False,
        max_in_flight: int = 0,
        tls_cert_file: str = "",
        tls_key_file: str = "",
        client_ca_file: str = "",
    ):
        # publish_master: create/reconcile the "kubernetes" service +
        # endpoints on start (pkg/master/publish.go). Off by default so
        # unit fixtures see only the objects they create; the daemon
        # launchers turn it on.
        # max_in_flight: cap on concurrently-served non-long-running
        # API requests; excess get 429 (pkg/apiserver/handlers.go).
        # 0 = unlimited (unit-test default; the daemon passes 400 like
        # the reference's --max-requests-inflight).
        self._publish_master = publish_master
        handler = type(
            "BoundHandler",
            (_Handler,),
            {
                "api": api,
                "authenticator": authenticator,
                "authorizer": authorizer,
                "inflight": (
                    threading.BoundedSemaphore(max_in_flight)
                    if max_in_flight > 0
                    else None
                ),
            },
        )
        self.httpd = _TLSCapableServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.api = api
        self._thread: Optional[threading.Thread] = None
        # TLS + x509 client-cert authn (--tls-cert-file /
        # --tls-private-key-file / --client-ca-file; reference:
        # cmd/kube-apiserver/app/server.go secure serving +
        # pkg/apiserver/authn.go x509). CERT_OPTIONAL: clients without
        # certs still reach basic/token auth; clients WITH certs must
        # chain to the CA or the handshake fails. Sockets are wrapped
        # PER CONNECTION with a deferred handshake so a stalled client
        # blocks only its own handler thread, never the accept loop.
        self._tls = False
        if tls_cert_file and tls_key_file:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert_file, tls_key_file)
            if client_ca_file:
                ctx.load_verify_locations(client_ca_file)
                ctx.verify_mode = ssl.CERT_OPTIONAL
            self.httpd.ssl_context = ctx
            self._tls = True

    @property
    def address(self) -> str:
        host, port = self.httpd.server_address[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> "APIHTTPServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.1}, daemon=True
        )
        self._thread.start()
        if self._publish_master:
            host, port = self.httpd.server_address[:2]
            if host in ("0.0.0.0", "::", ""):
                # A wildcard bind is not a routable endpoint address;
                # publish a real interface IP (the reference resolves a
                # public address the same way before publishing).
                import socket as _socket

                try:
                    with _socket.socket(
                        _socket.AF_INET, _socket.SOCK_DGRAM
                    ) as probe:
                        # UDP connect only records the peer addr;
                        # it cannot block.  # ktlint: disable=KT004
                        probe.connect(("10.255.255.255", 1))
                        host = probe.getsockname()[0]
                except OSError:
                    host = "127.0.0.1"
            self.api.publish_master_service(host, port)
        return self

    def stop(self, release_store: bool = True) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        # Sever live keep-alive connections: a dead server must not
        # keep answering old clients through lingering handler threads
        # (a successor may be about to bind the same port).
        self.httpd.close_connections()
        if self._thread:
            self._thread.join(timeout=5)
        # Release the store (WAL handle + data-dir flock): a stopped
        # apiserver must let a successor open the same --data-dir.
        # release_store=False keeps it live for callers that hand the
        # SAME APIServer to a replacement front-end (HTTP-tier-only
        # restart; the store outlives the listener like etcd outlives
        # the reference apiserver).
        if release_store:
            self.api.store.close()
