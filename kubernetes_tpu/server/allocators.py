"""Service cluster-IP and node-port allocators.

The reference apiserver owns two allocation pools for services: the
portal/cluster-IP range (pkg/registry/service/ipallocator/allocator.go)
and the node-port range (pkg/registry/service/portallocator/
allocator.go), both wired into the service REST storage
(pkg/master/master.go:440-455) and exercised at create/update/delete
(pkg/registry/service/rest.go:68-131).  On restart the reference runs a
repair pass that rebuilds the in-memory bitmaps from the stored
services (pkg/registry/service/ipallocator/controller/repair.go); here
`repair_from` does the same from a store listing.

Both pools are the same shape — a contiguous integer range with a
bitmap of allocations — so they share one implementation.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Iterable, List


class AllocationError(Exception):
    """Requested value unavailable or the pool is exhausted."""


class _RangeAllocator:
    """Bitmap allocator over [0, size) offsets with a rolling scan
    pointer so sequential allocate_next calls spread across the range
    instead of immediately reusing just-released values (the reference
    randomizes for the same reason, ipallocator/allocator.go:160)."""

    def __init__(self, size: int):
        self._size = size
        self._used = set()
        self._next = 0
        self._lock = threading.Lock()

    def _offset_name(self, offset: int) -> str:
        raise NotImplementedError

    def _allocate_offset(self, offset: int) -> None:
        with self._lock:
            if offset in self._used:
                raise AllocationError(
                    f"{self._offset_name(offset)} is already allocated"
                )
            self._used.add(offset)

    def _allocate_next_offset(self) -> int:
        with self._lock:
            if len(self._used) >= self._size:
                raise AllocationError("range is full")
            for i in range(self._size):
                offset = (self._next + i) % self._size
                if offset not in self._used:
                    self._used.add(offset)
                    self._next = (offset + 1) % self._size
                    return offset
            raise AllocationError("range is full")  # pragma: no cover

    def _release_offset(self, offset: int) -> None:
        with self._lock:
            self._used.discard(offset)

    def _offset_allocated(self, offset: int) -> bool:
        with self._lock:
            return offset in self._used

    @property
    def free(self) -> int:
        with self._lock:
            return self._size - len(self._used)


class IPAllocator(_RangeAllocator):
    """Cluster-IP pool over a CIDR; network and broadcast addresses are
    excluded, matching ipallocator.NewCIDRRange."""

    def __init__(self, cidr: str):
        self.network = ipaddress.ip_network(cidr)
        base = int(self.network.network_address) + 1
        size = self.network.num_addresses - 2
        if size < 1:
            raise ValueError(f"service CIDR {cidr} has no allocatable addresses")
        self._base = base
        super().__init__(size)

    def _offset_name(self, offset: int) -> str:
        return str(ipaddress.ip_address(self._base + offset))

    def _offset_of(self, ip: str) -> int:
        try:
            addr = ipaddress.ip_address(ip)
        except ValueError:
            raise AllocationError(f"{ip!r} is not a valid IP address")
        offset = int(addr) - self._base
        if not (0 <= offset < self._size):
            raise AllocationError(
                f"{ip} is not in the service IP range {self.network}"
            )
        return offset

    def allocate(self, ip: str) -> None:
        self._allocate_offset(self._offset_of(ip))

    def allocate_next(self) -> str:
        return str(ipaddress.ip_address(self._base + self._allocate_next_offset()))

    def release(self, ip: str) -> None:
        try:
            self._release_offset(self._offset_of(ip))
        except AllocationError:
            pass  # out-of-range IPs were never ours to track

    def mark(self, ip: str) -> None:
        """Repair-pass variant of allocate: out-of-range / duplicate
        stored values are tolerated (the reference repair loop logs and
        continues rather than refusing to start)."""
        try:
            self._allocate_offset(self._offset_of(ip))
        except AllocationError:
            pass


class PortAllocator(_RangeAllocator):
    """Node-port pool over an inclusive [lo, hi] port range (reference
    default 30000-32767, portallocator wired at master.go:446)."""

    def __init__(self, lo: int = 30000, hi: int = 32767):
        if not (0 < lo <= hi <= 65535):
            raise ValueError(f"invalid node port range {lo}-{hi}")
        self.lo, self.hi = lo, hi
        super().__init__(hi - lo + 1)

    def _offset_name(self, offset: int) -> str:
        return f"port {self.lo + offset}"

    def allocate(self, port: int) -> None:
        if not (self.lo <= port <= self.hi):
            raise AllocationError(
                f"port {port} is not in the node port range {self.lo}-{self.hi}"
            )
        self._allocate_offset(port - self.lo)

    def is_allocated(self, port: int) -> bool:
        return self.lo <= port <= self.hi and self._offset_allocated(port - self.lo)

    def allocate_next(self) -> int:
        return self.lo + self._allocate_next_offset()

    def release(self, port: int) -> None:
        if self.lo <= port <= self.hi:
            self._release_offset(port - self.lo)

    def mark(self, port: int) -> None:
        try:
            self.allocate(port)
        except AllocationError:
            pass


def service_ips_in_use(services: Iterable[dict]) -> List[str]:
    """Cluster IPs recorded in stored service objects (headless 'None'
    and unset excluded)."""
    out = []
    for svc in services:
        ip = (svc.get("spec") or {}).get("clusterIP") or ""
        if ip and ip != "None":
            out.append(ip)
    return out


def service_node_ports_in_use(services: Iterable[dict]) -> List[int]:
    out = []
    for svc in services:
        for port in (svc.get("spec") or {}).get("ports") or []:
            np = port.get("nodePort") or 0
            if np:
                out.append(np)
    return out
