"""Admission control: mutate/deny requests after authn/authz, before
storage.

Behavioral parity with the reference's admission framework
(pkg/admission/: Interface, chain.go, plugins.go) and the standard
plugin set (plugin/pkg/admission/): AlwaysAdmit, AlwaysDeny,
LimitRanger, NamespaceAutoprovision, NamespaceExists,
NamespaceLifecycle, ResourceQuota, ServiceAccount,
SecurityContextDeny, DenyExecOnPrivileged.

Plugins see wire-form dicts (the apiserver's storage currency) and may
mutate them in place (LimitRanger defaulting, ServiceAccount
defaulting) or raise AdmissionError to reject (HTTP 403, matching the
reference's apiserver.errToAPIStatus forbidden mapping).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.models.quantity import Quantity, parse_quantity

CREATE = "CREATE"
UPDATE = "UPDATE"
DELETE = "DELETE"
CONNECT = "CONNECT"


class AdmissionError(Exception):
    """Rejection; surfaces as HTTP 403 Forbidden (or the plugin's code,
    e.g. 404 NotFound from the namespace plugins)."""

    def __init__(self, message: str, code: int = 403):
        super().__init__(message)
        self.code = code
        self.message = message
        self.reason = {404: "NotFound", 409: "Conflict"}.get(code, "Forbidden")


@dataclass
class Attributes:
    """Reference: pkg/admission/attributes.go."""

    operation: str  # CREATE | UPDATE | DELETE | CONNECT
    resource: str  # plural REST name, e.g. "pods"
    namespace: str = ""
    name: str = ""
    subresource: str = ""
    obj: Optional[dict] = None  # wire form; None for DELETE


class Interface:
    """A single admission plugin (pkg/admission/interfaces.go)."""

    def handles(self, operation: str) -> bool:
        return True

    def admit(self, attrs: Attributes) -> None:  # may mutate attrs.obj
        raise NotImplementedError

    def commit(self, attrs: Attributes) -> None:
        """Called after the store write succeeded (best-effort hook for
        usage bookkeeping); must not raise."""
        return None


class Chain(list):
    """Ordered plugin list; first rejection wins (pkg/admission/chain.go)."""

    def admit(self, attrs: Attributes) -> None:
        for plugin in self:
            if plugin.handles(attrs.operation):
                plugin.admit(attrs)

    def commit(self, attrs: Attributes) -> None:
        for plugin in self:
            if plugin.handles(attrs.operation):
                plugin.commit(attrs)


# -- plugin registry (pkg/admission/plugins.go) -----------------------------

_PLUGINS: Dict[str, Callable] = {}
_plugins_lock = threading.Lock()


def register_plugin(name: str, factory: Callable) -> None:
    with _plugins_lock:
        if name in _PLUGINS:
            raise ValueError(f"admission plugin {name!r} already registered")
        _PLUGINS[name] = factory


def new_from_plugins(api, names: List[str]) -> Chain:
    """Instantiate a chain from plugin names (--admission-control flag,
    cmd/kube-apiserver/app/server.go:184)."""
    chain = Chain()
    for name in names:
        factory = _PLUGINS.get(name)
        if factory is None:
            raise ValueError(f"unknown admission plugin {name!r}")
        chain.append(factory(api))
    return chain


# -- helpers ----------------------------------------------------------------


def _pod_resource_total(pod: dict, key: str) -> Quantity:
    """Sum a resource across containers (limits, falling back to requests)."""
    total = 0
    for c in pod.get("spec", {}).get("containers", []):
        res = c.get("resources", {})
        v = (res.get("limits") or {}).get(key) or (res.get("requests") or {}).get(key)
        if v:
            total += parse_quantity(v).milli_value()
    return Quantity.from_milli(total)


# -- plugins ----------------------------------------------------------------


class AlwaysAdmit(Interface):
    """plugin/pkg/admission/admit."""

    def admit(self, attrs: Attributes) -> None:
        return None


class AlwaysDeny(Interface):
    """plugin/pkg/admission/deny."""

    def admit(self, attrs: Attributes) -> None:
        raise AdmissionError("admission plugin AlwaysDeny rejected the request")


class NamespaceExists(Interface):
    """Reject requests in namespaces that do not exist
    (plugin/pkg/admission/namespace/exists)."""

    def __init__(self, api):
        self.api = api

    def handles(self, operation: str) -> bool:
        return operation in (CREATE, UPDATE, DELETE)

    def admit(self, attrs: Attributes) -> None:
        if not attrs.namespace or attrs.resource == "namespaces":
            return
        from kubernetes_tpu.server.api import APIError

        try:
            self.api.get("namespaces", "", attrs.namespace)
        except APIError:
            raise AdmissionError(f"namespace {attrs.namespace!r} does not exist", 404)


class NamespaceAutoprovision(Interface):
    """Create the namespace on first use
    (plugin/pkg/admission/namespace/autoprovision)."""

    def __init__(self, api):
        self.api = api

    def handles(self, operation: str) -> bool:
        return operation == CREATE

    def admit(self, attrs: Attributes) -> None:
        if not attrs.namespace or attrs.resource == "namespaces":
            return
        from kubernetes_tpu.server.api import APIError

        try:
            self.api.get("namespaces", "", attrs.namespace)
        except APIError:
            try:
                self.api.create(
                    "namespaces", "", {"metadata": {"name": attrs.namespace}}
                )
            except APIError as e:
                if e.code != 409:  # racing creator won: fine
                    raise


class NamespaceLifecycle(Interface):
    """Reject creates in missing or Terminating namespaces
    (plugin/pkg/admission/namespace/lifecycle)."""

    def __init__(self, api):
        self.api = api

    def handles(self, operation: str) -> bool:
        return operation == CREATE

    def admit(self, attrs: Attributes) -> None:
        if not attrs.namespace or attrs.resource == "namespaces":
            return
        from kubernetes_tpu.server.api import APIError

        try:
            ns = self.api.get("namespaces", "", attrs.namespace)
        except APIError:
            raise AdmissionError(f"namespace {attrs.namespace!r} does not exist", 404)
        if ns.get("status", {}).get("phase") == "Terminating":
            raise AdmissionError(
                f"namespace {attrs.namespace!r} is terminating; "
                f"cannot create {attrs.resource}"
            )


class LimitRanger(Interface):
    """Apply container defaults and enforce min/max from LimitRange
    objects (plugin/pkg/admission/limitranger/admission.go)."""

    def __init__(self, api):
        self.api = api

    def handles(self, operation: str) -> bool:
        return operation in (CREATE, UPDATE)

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        items = self.api.list("limitranges", attrs.namespace)["items"]
        for lr in items:
            for limit in lr.get("spec", {}).get("limits", []):
                if limit.get("type", "Container") == "Container":
                    self._apply_container_limit(limit, attrs.obj)
                elif limit.get("type") == "Pod":
                    self._check_pod_limit(limit, attrs.obj)

    def _apply_container_limit(self, limit: dict, pod: dict) -> None:
        defaults = limit.get("default", {})
        mins = limit.get("min", {})
        maxes = limit.get("max", {})
        for c in pod.get("spec", {}).get("containers", []):
            res = c.setdefault("resources", {})
            limits = res.setdefault("limits", {})
            for key, v in defaults.items():
                limits.setdefault(key, v)
            for key, mn in mins.items():
                have = limits.get(key)
                if have and parse_quantity(have).milli_value() < parse_quantity(
                    mn
                ).milli_value():
                    raise AdmissionError(
                        f"minimum {key} usage per Container is {mn}; "
                        f"container {c.get('name')!r} requests {have}"
                    )
            for key, mx in maxes.items():
                have = limits.get(key)
                if have and parse_quantity(have).milli_value() > parse_quantity(
                    mx
                ).milli_value():
                    raise AdmissionError(
                        f"maximum {key} usage per Container is {mx}; "
                        f"container {c.get('name')!r} requests {have}"
                    )

    def _check_pod_limit(self, limit: dict, pod: dict) -> None:
        for key, mx in (limit.get("max") or {}).items():
            total = _pod_resource_total(pod, key)
            if total.milli_value() > parse_quantity(mx).milli_value():
                raise AdmissionError(
                    f"maximum {key} usage per Pod is {mx}; total requested {total}"
                )
        for key, mn in (limit.get("min") or {}).items():
            total = _pod_resource_total(pod, key)
            if total.milli_value() and total.milli_value() < parse_quantity(
                mn
            ).milli_value():
                raise AdmissionError(
                    f"minimum {key} usage per Pod is {mn}; total requested {total}"
                )


# Hard-limit keys a ResourceQuota can carry for object counts
# (reference: pkg/api/types.go ResourceQuota resource names). Shared
# with the ResourceQuotaManager backstop controller — one list, one
# definition of "countable".
COUNTED_RESOURCES = frozenset(
    {
        "pods",
        "services",
        "replicationcontrollers",
        "secrets",
        "persistentvolumeclaims",
        "resourcequotas",
    }
)


class ResourceQuotaAdmission(Interface):
    """Enforce namespace ResourceQuota hard limits and keep
    status.used current (plugin/pkg/admission/resourcequota).

    The apiserver serializes admission with the store write (see
    APIServer create/update/delete), so the check-then-act here cannot
    race another writer past a hard limit."""

    def __init__(self, api):
        self.api = api

    def handles(self, operation: str) -> bool:
        return operation in (CREATE, UPDATE, DELETE)

    def admit(self, attrs: Attributes) -> None:
        """Enforce only; no status writes here. A rejected (or later
        failing) request must leave quota status untouched — recording
        happens in commit() after the store write lands."""
        if not attrs.namespace or attrs.resource == "resourcequotas":
            return
        for quota in self.api.list("resourcequotas", attrs.namespace)["items"]:
            hard = quota.get("spec", {}).get("hard", {})
            if self._relevant(hard, attrs):
                self._enforce(hard, attrs)

    def commit(self, attrs: Attributes) -> None:
        """Post-write: recompute used from the store (now exact — the
        write already landed) and persist it when it changed."""
        if not attrs.namespace or attrs.resource == "resourcequotas":
            return
        from kubernetes_tpu.server.api import APIError

        for quota in self.api.list("resourcequotas", attrs.namespace)["items"]:
            hard = quota.get("spec", {}).get("hard", {})
            if not self._relevant(hard, attrs):
                continue
            used = self._usage(attrs.namespace, hard)
            if used == quota.get("status", {}).get("used", {}):
                continue  # unchanged: skip the write, don't wake watchers
            try:
                self.api.update_status(
                    "resourcequotas",
                    attrs.namespace,
                    quota["metadata"]["name"],
                    {"status": {"hard": dict(hard), "used": used}},
                )
            except APIError:
                pass  # backstop controller reconciles

    @staticmethod
    def _relevant(hard: dict, attrs: Attributes) -> bool:
        """Skip quotas that track nothing this request touches."""
        if attrs.resource in hard and attrs.resource in COUNTED_RESOURCES:
            return True
        return attrs.resource == "pods" and ("cpu" in hard or "memory" in hard)

    def _usage(self, namespace: str, hard: dict) -> dict:
        used: Dict[str, str] = {}
        pods = None
        for key in hard:
            if key in COUNTED_RESOURCES:
                n = len(self.api.list(key, namespace)["items"])
                used[key] = str(n)
            elif key in ("cpu", "memory"):
                if pods is None:
                    pods = self.api.list("pods", namespace)["items"]
                total = 0
                for pod in pods:
                    total += _pod_resource_total(pod, key).milli_value()
                used[key] = str(Quantity.from_milli(total))
        return used

    def _old_pod_total(self, attrs: Attributes, key: str) -> int:
        """Milli-total of `key` in the stored version of attrs' pod (for
        UPDATE/DELETE deltas); 0 when it doesn't exist."""
        from kubernetes_tpu.server.api import APIError

        try:
            old = self.api.get("pods", attrs.namespace, attrs.name)
        except APIError:
            return 0
        return _pod_resource_total(old, key).milli_value()

    def _enforce(self, hard: dict, attrs: Attributes) -> None:
        # `used` reflects the store BEFORE this request's write lands.
        used = self._usage(attrs.namespace, hard)
        counted = attrs.resource in hard and attrs.resource in COUNTED_RESOURCES
        if attrs.operation == CREATE and counted:
            if int(used[attrs.resource]) + 1 > parse_quantity(
                hard[attrs.resource]
            ).value():
                raise AdmissionError(
                    f"limited to {hard[attrs.resource]} {attrs.resource}", 403
                )
        if attrs.resource == "pods":
            for key in ("cpu", "memory"):
                if key not in hard:
                    continue
                if attrs.operation == CREATE and attrs.obj is not None:
                    delta = _pod_resource_total(attrs.obj, key).milli_value()
                elif attrs.operation == UPDATE and attrs.obj is not None:
                    delta = _pod_resource_total(
                        attrs.obj, key
                    ).milli_value() - self._old_pod_total(attrs, key)
                else:
                    continue  # deletes only shrink usage
                have = parse_quantity(used[key]).milli_value()
                cap = parse_quantity(hard[key]).milli_value()
                if delta > 0 and have + delta > cap:
                    raise AdmissionError(
                        f"{key} quota exceeded: used {used[key]}, "
                        f"requested {Quantity.from_milli(delta)}, "
                        f"hard limit {hard[key]}"
                    )


class ServiceAccountAdmission(Interface):
    """Default pods to the 'default' ServiceAccount and require the
    referenced account to exist (plugin/pkg/admission/serviceaccount)."""

    def __init__(self, api, require_account: bool = False):
        self.api = api
        self.require_account = require_account

    def handles(self, operation: str) -> bool:
        return operation == CREATE

    # Where every container sees its API credential (the reference's
    # DefaultAPITokenMountPath, plugin/pkg/admission/serviceaccount).
    TOKEN_MOUNT_PATH = "/var/run/secrets/kubernetes.io/serviceaccount"

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        spec = attrs.obj.setdefault("spec", {})
        if not spec.get("serviceAccount"):
            spec["serviceAccount"] = "default"
        if self.require_account:
            from kubernetes_tpu.server.api import APIError

            try:
                self.api.get("serviceaccounts", attrs.namespace, spec["serviceAccount"])
            except APIError:
                raise AdmissionError(
                    f"service account {attrs.namespace}/{spec['serviceAccount']} "
                    "does not exist"
                )
        self._mount_api_token(attrs.namespace, spec)

    def _mount_api_token(self, namespace: str, spec: dict) -> None:
        """Mount the account's token Secret (minted by the Token
        controller) into every container at the well-known path —
        reference admission.go mountServiceAccountToken. Soft-fails
        when the account or its token doesn't exist yet: the plugin
        must not block pods during controller warm-up."""
        from kubernetes_tpu.server.api import APIError

        try:
            sa = self.api.get("serviceaccounts", namespace, spec["serviceAccount"])
        except APIError:
            return
        token_secret = None
        for ref in sa.get("secrets") or []:
            name = ref.get("name", "")
            try:
                sec = self.api.get("secrets", namespace, name)
            except APIError:
                continue
            if sec.get("type") == "kubernetes.io/service-account-token":
                token_secret = name
                break
        if token_secret is None:
            return
        volumes = spec.setdefault("volumes", [])
        vol_name = next(
            (
                v["name"]
                for v in volumes
                if (v.get("secret") or {}).get("secretName") == token_secret
            ),
            None,
        )
        if vol_name is None:
            vol_name = token_secret
            if any(v.get("name") == vol_name for v in volumes):
                vol_name = f"{token_secret}-sa"
            volumes.append(
                {"name": vol_name, "secret": {"secretName": token_secret}}
            )
        for c in spec.get("containers") or []:
            mounts = c.setdefault("volumeMounts", [])
            if any(m.get("mountPath") == self.TOKEN_MOUNT_PATH for m in mounts):
                continue
            mounts.append(
                {
                    "name": vol_name,
                    "mountPath": self.TOKEN_MOUNT_PATH,
                    "readOnly": True,
                }
            )


class PodGroupAdmission(Interface):
    """Reject pods referencing unknown or oversized PodGroups (the
    gang-scheduling admission gate; no reference analog — follows the
    sig-scheduling coscheduling controller's membership rules).

    A pod labeled with POD_GROUP_LABEL must name a PodGroup in its own
    namespace, and when the group declares spec.maxMember, admitting
    the pod must not push membership past it — an oversized group can
    never gang-place atomically and would pin the whole group Pending.
    UPDATE/PATCH is gated too (joining a gang by relabeling an existing
    pod is the same membership change); updates that leave the label
    untouched pass without re-checking."""

    def __init__(self, api):
        self.api = api

    def handles(self, operation: str) -> bool:
        return operation in (CREATE, UPDATE)

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        from kubernetes_tpu.models.objects import POD_GROUP_LABEL
        from kubernetes_tpu.server.api import APIError

        group = (
            attrs.obj.get("metadata", {}).get("labels", {}) or {}
        ).get(POD_GROUP_LABEL, "")
        if not group:
            # Unlabeled (or label-removing) writes always admit — and
            # this is every ordinary pod UPDATE in the cluster, so it
            # must return before any store fetch.
            return
        if attrs.operation == UPDATE:
            try:
                old = self.api.get("pods", attrs.namespace, attrs.name)
            except APIError:
                old = {}
            old_group = (
                old.get("metadata", {}).get("labels", {}) or {}
            ).get(POD_GROUP_LABEL, "")
            if group == old_group:
                return  # membership unchanged: nothing to vet
        try:
            pg = self.api.get("podgroups", attrs.namespace, group)
        except APIError:
            raise AdmissionError(
                f"pod group {attrs.namespace}/{group} does not exist", 404
            )
        max_member = int(pg.get("spec", {}).get("maxMember", 0) or 0)
        if not max_member:
            return
        # Live members only: terminated pods (Succeeded/Failed) and
        # pods being deleted no longer occupy a gang slot — counting
        # them would permanently reject replacements for crashed
        # members and wedge the gang below minMember. The pod being
        # admitted never counts itself (relevant on relabel-updates).
        # copy=False: the list is counted and discarded — a full
        # deep copy of the namespace's pods under the admission lock
        # would stall every concurrent write for nothing.
        members = sum(
            1
            for p in self.api.list(
                "pods", attrs.namespace,
                label_selector=f"{POD_GROUP_LABEL}={group}",
                copy=False,
            )["items"]
            if p.get("metadata", {}).get("name") != attrs.name
            and p.get("status", {}).get("phase") not in ("Succeeded", "Failed")
            and not p.get("metadata", {}).get("deletionTimestamp")
        )
        if members + 1 > max_member:
            raise AdmissionError(
                f"pod group {attrs.namespace}/{group} is full "
                f"({members} live members, maxMember {max_member})"
            )


class PriorityAdmission(Interface):
    """Resolve and freeze pod priority (no analog in this reference
    tree; follows the later reference's Priority admission plugin).

    CREATE: a pod naming spec.priorityClassName gets spec.priority and
    spec.preemptionPolicy copied from the class (unknown class: 404);
    a pod naming none inherits the globalDefault class (highest value
    wins when several are marked) or priority 0. A caller-supplied
    spec.priority must agree with the resolved value — priority comes
    from classes, never free-form.

    UPDATE: priorityClassName/priority/preemptionPolicy are immutable
    (a priority bump would silently re-rank a queued pod past peers
    that were admitted under the old value); omitted fields carry over
    from the stored pod so status-ish full updates keep passing."""

    _FROZEN = ("priorityClassName", "priority", "preemptionPolicy")

    def __init__(self, api):
        self.api = api

    def handles(self, operation: str) -> bool:
        return operation in (CREATE, UPDATE)

    def _default_class(self) -> Optional[dict]:
        best = None
        for pc in self.api.list("priorityclasses", "", copy=False)["items"]:
            if not pc.get("globalDefault"):
                continue
            if best is None or int(pc.get("value", 0)) > int(best.get("value", 0)):
                best = pc
        return best

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        spec = attrs.obj.setdefault("spec", {})
        if attrs.operation == UPDATE:
            from kubernetes_tpu.server.api import APIError

            try:
                old = self.api.get("pods", attrs.namespace, attrs.name)
            except APIError:
                return  # racing delete: the update will 404 on its own
            old_spec = old.get("spec", {})
            for field_ in self._FROZEN:
                if field_ not in spec or spec[field_] in ("", None):
                    if field_ in old_spec:
                        spec[field_] = old_spec[field_]
                elif spec[field_] != old_spec.get(field_):
                    # Compare against the STORED value (None when the
                    # pod never had one) — defaulting to the new value
                    # would let any update grant itself arbitrary
                    # priority after creation.
                    raise AdmissionError(
                        f"spec.{field_} is immutable "
                        f"(was {old_spec.get(field_)!r})"
                    )
            return
        name = spec.get("priorityClassName", "")
        if name:
            from kubernetes_tpu.server.api import APIError

            try:
                pc = self.api.get("priorityclasses", "", name)
            except APIError:
                raise AdmissionError(
                    f"priority class {name!r} does not exist", 404
                )
        else:
            pc = self._default_class()
        value = int(pc.get("value", 0)) if pc else 0
        supplied = spec.get("priority")
        if supplied is not None and int(supplied) != value:
            raise AdmissionError(
                f"spec.priority {supplied} conflicts with priority class "
                f"value {value}; priority is resolved from "
                "priorityClassName, not set directly"
            )
        if pc:
            spec["priorityClassName"] = pc["metadata"]["name"]
            spec["priority"] = value
            policy = pc.get("preemptionPolicy", "")
            if policy:
                spec["preemptionPolicy"] = policy
        elif supplied is not None:
            spec["priority"] = 0


class SecurityContextDeny(Interface):
    """Reject pods that request privileged mode, added capabilities, or
    custom SELinux/RunAsUser options
    (plugin/pkg/admission/securitycontext/scdeny)."""

    def handles(self, operation: str) -> bool:
        return operation in (CREATE, UPDATE)

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        for c in attrs.obj.get("spec", {}).get("containers", []):
            sc = c.get("securityContext") or {}
            if sc.get("privileged"):
                raise AdmissionError(
                    f"container {c.get('name')!r}: privileged mode is forbidden"
                )
            if (sc.get("capabilities") or {}).get("add"):
                raise AdmissionError(
                    f"container {c.get('name')!r}: added capabilities are forbidden"
                )
            if sc.get("seLinuxOptions") or sc.get("runAsUser") is not None:
                raise AdmissionError(
                    f"container {c.get('name')!r}: SecurityContext overrides "
                    "are forbidden"
                )


class DenyExecOnPrivileged(Interface):
    """Deny exec/attach on pods with privileged containers
    (plugin/pkg/admission/exec)."""

    def __init__(self, api):
        self.api = api

    def handles(self, operation: str) -> bool:
        return operation == CONNECT

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.subresource not in ("exec", "attach"):
            return
        from kubernetes_tpu.server.api import APIError

        try:
            pod = self.api.get("pods", attrs.namespace, attrs.name)
        except APIError:
            return
        for c in pod.get("spec", {}).get("containers", []):
            if (c.get("securityContext") or {}).get("privileged"):
                raise AdmissionError(
                    "cannot exec into or attach to a privileged container"
                )


register_plugin("AlwaysAdmit", lambda api: AlwaysAdmit())
register_plugin("AlwaysDeny", lambda api: AlwaysDeny())
register_plugin("NamespaceExists", NamespaceExists)
register_plugin("NamespaceAutoProvision", NamespaceAutoprovision)
register_plugin("NamespaceLifecycle", NamespaceLifecycle)
register_plugin("LimitRanger", LimitRanger)
register_plugin("ResourceQuota", ResourceQuotaAdmission)
register_plugin("ServiceAccount", ServiceAccountAdmission)
register_plugin("PodGroup", PodGroupAdmission)
register_plugin("Priority", PriorityAdmission)
register_plugin("SecurityContextDeny", lambda api: SecurityContextDeny())
register_plugin("DenyExecOnPrivileged", DenyExecOnPrivileged)
