"""Resource registry: which resources exist, their kinds, scoping,
validation, and storage layout.

Reference: the resource->storage map assembled in
pkg/master/master.go:460-494 and the per-resource registries under
pkg/registry/.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional

from kubernetes_tpu.models import objects as O
from kubernetes_tpu.models import validation as V


@dataclass(frozen=True)
class ResourceInfo:
    name: str  # plural REST name, e.g. "pods"
    kind: str
    cls: type
    namespaced: bool = True
    validator: Optional[Callable] = None
    ttl: Optional[float] = None  # seconds; events are TTL'd
    # Optional wire-form validator twin (same checks, no typed
    # decode) — the bulk-path fast validator; parity with `validator`
    # is pinned by tests.
    wire_validator: Optional[Callable] = None

    def key(self, namespace: str, name: str) -> str:
        if self.namespaced:
            return f"/registry/{self.name}/{namespace}/{name}"
        return f"/registry/{self.name}/{name}"

    def prefix(self, namespace: str = "") -> str:
        if self.namespaced and namespace:
            return f"/registry/{self.name}/{namespace}/"
        return f"/registry/{self.name}/"


RESOURCES: Dict[str, ResourceInfo] = {}


def _register(info: ResourceInfo, *aliases: str) -> None:
    RESOURCES[info.name] = info
    for a in aliases:
        RESOURCES[a] = info


_register(
    ResourceInfo(
        "pods", "Pod", O.Pod,
        validator=V.validate_pod,
        wire_validator=V.validate_pod_wire,
    )
)
_register(
    ResourceInfo("nodes", "Node", O.Node, namespaced=False, validator=V.validate_node),
    "minions",  # legacy alias (reference: pkg/registry/minion)
)
_register(ResourceInfo("services", "Service", O.Service, validator=V.validate_service))
_register(ResourceInfo("endpoints", "Endpoints", O.Endpoints))
_register(
    ResourceInfo(
        "replicationcontrollers",
        "ReplicationController",
        O.ReplicationController,
        validator=V.validate_replication_controller,
    ),
    "rc",
)
_register(ResourceInfo("events", "Event", O.Event, ttl=3600.0))
_register(ResourceInfo("namespaces", "Namespace", O.Namespace, namespaced=False))
_register(ResourceInfo("secrets", "Secret", O.Secret))
_register(
    ResourceInfo(
        "serviceaccounts",
        "ServiceAccount",
        O.ServiceAccount,
        validator=V.validate_service_account,
    )
)
_register(
    ResourceInfo(
        "limitranges", "LimitRange", O.LimitRange, validator=V.validate_limit_range
    )
)
_register(
    ResourceInfo(
        "resourcequotas",
        "ResourceQuota",
        O.ResourceQuota,
        validator=V.validate_resource_quota,
    ),
    "quota",
)
_register(
    ResourceInfo(
        "persistentvolumes",
        "PersistentVolume",
        O.PersistentVolume,
        namespaced=False,
        validator=V.validate_persistent_volume,
    ),
    "pv",
)
_register(
    ResourceInfo(
        "persistentvolumeclaims",
        "PersistentVolumeClaim",
        O.PersistentVolumeClaim,
        validator=V.validate_persistent_volume_claim,
    ),
    "pvc",
)
_register(ResourceInfo("podtemplates", "PodTemplate", O.PodTemplate))
_register(
    ResourceInfo(
        "podgroups", "PodGroup", O.PodGroup, validator=V.validate_pod_group
    ),
    "pg",
)
_register(
    ResourceInfo(
        "priorityclasses",
        "PriorityClass",
        O.PriorityClass,
        namespaced=False,
        validator=V.validate_priority_class,
    ),
    "pc",
)
_register(
    ResourceInfo(
        "componentstatuses", "ComponentStatus", O.ComponentStatus, namespaced=False
    ),
    "cs",
)


# Field extractors for field selectors (reference: pkg/registry/pod/strategy
# PodToSelectableFields etc.). Values must be strings.
def unique_resources():
    """ResourceInfos deduped across aliases, sorted by name (the
    registry maps each info under its name PLUS aliases)."""
    seen = set()
    out = []
    for info in sorted(RESOURCES.values(), key=lambda i: i.name):
        if info.name in seen:
            continue
        seen.add(info.name)
        out.append(info)
    return out


def pod_fields(obj: dict) -> Dict[str, str]:
    return {
        "metadata.name": obj.get("metadata", {}).get("name", ""),
        "metadata.namespace": obj.get("metadata", {}).get("namespace", ""),
        "spec.nodeName": obj.get("spec", {}).get("nodeName", ""),
        "spec.host": obj.get("spec", {}).get("nodeName", ""),  # legacy name
        "status.phase": obj.get("status", {}).get("phase", ""),
    }


def generic_fields(obj: dict) -> Dict[str, str]:
    return {
        "metadata.name": obj.get("metadata", {}).get("name", ""),
        "metadata.namespace": obj.get("metadata", {}).get("namespace", ""),
    }


def event_fields(obj: dict) -> Dict[str, str]:
    inv = obj.get("involvedObject", {})
    f = generic_fields(obj)
    f.update(
        {
            "involvedObject.kind": inv.get("kind", ""),
            "involvedObject.name": inv.get("name", ""),
            "involvedObject.namespace": inv.get("namespace", ""),
            "involvedObject.uid": inv.get("uid", ""),
        }
    )
    return f


FIELD_EXTRACTORS: Dict[str, Callable[[dict], Dict[str, str]]] = {
    "pods": pod_fields,
    "events": event_fields,
}


def fields_for(resource: str, obj: dict) -> Dict[str, str]:
    return FIELD_EXTRACTORS.get(resource, generic_fields)(obj)
